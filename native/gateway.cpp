// Native gateway: the horizontally-scalable front-end's relay loop in C++.
//
// Ref: SURVEY §2.9 — the reference's socket tier is socket.io behind N
// Alfred instances (driver-base/src/documentDeltaConnection.ts:53,
// services/src/socketIoRedisPublisher.ts); §2.9 prescribes a native
// streaming front end in its place. The Python gateway
// (fluidframework_tpu/service/gateway.py) established the protocol: this
// file is the same relay with zero Python on the hot path — one epoll
// thread pumping length-prefixed frames between client sockets and ONE
// upstream backbone connection to the ordering core.
//
// Wire contract (shared with front_end.py / gateway.py):
//   frame      := u32be length + body
//   binary body: 0x01 ftype ...   (protocol/binwire.py)
//     client submit  (ftype 1) -> upstream fsubmit (ftype 3, u32 sid spliced)
//     upstream fops  (ftype 4) -> client ops (ftype 2, topic stripped),
//                                 fanned out per topic subscriber
//     columnar twins (ftype 5-8) relay IDENTICALLY: cols_submit (5) ->
//     cols_fsubmit (6) by the same 6-byte sid splice, cols_fops (8) ->
//     cols_ops (7) by the same topic strip — the column payload is
//     never parsed on the relay path.
//   JSON body  : {"t": ...}
//     connect -> fconnect (sid assigned, bin:1 forced), fconnected ->
//     connected; submit/signal/disconnect -> f*; storage RPCs forwarded
//     with rid remapped; fnack/fsignal routed by sid/topic.
//
// Compatibility: clients SHOULD negotiate the binary ops push ("bin":1
// — the driver default). Legacy JSON-ops clients are served too: each
// binary broadcast batch is decoded to the JSON ops frame once per
// topic (ops_body_to_json / cols_body_to_json below) and shared by
// every legacy subscriber. A batch that cannot be decoded sends the
// legacy session an error frame and closes it, so its reconnect +
// delta backfill repairs the sequence gap instead of stalling on it.
//
// JSON handling is a shallow top-level scanner: keys + raw value spans.
// Frames are REASSEMBLED from spans (never re-serialized), so payloads
// pass through byte-identical, exactly like the Python relay.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <deque>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint8_t kMagic = 0x01;
constexpr uint8_t kFtSubmit = 1;
constexpr uint8_t kFtOps = 2;
constexpr uint8_t kFtFsubmit = 3;
constexpr uint8_t kFtFops = 4;
constexpr uint8_t kFtColsSubmit = 5;
constexpr uint8_t kFtColsFsubmit = 6;
constexpr uint8_t kFtColsOps = 7;
constexpr uint8_t kFtColsFops = 8;
constexpr uint8_t kFtPresence = 11;
constexpr uint8_t kFtFpresence = 12;
constexpr size_t kMaxFrame = 8u * 1024 * 1024;     // front_end.py MAX_FRAME
constexpr size_t kMaxBuffered = 32u * 1024 * 1024; // slow-consumer drop

// ------------------------------------------------------------ JSON scanner

struct JsonField {
  std::string key;      // unescaped key
  const char* val;      // raw value span (escaped, verbatim)
  size_t val_len;
};

// Skip one JSON value starting at p; returns one past its end (nullptr on
// malformed input). Handles nesting + string escapes.
const char* skip_value(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  if (p >= end) return nullptr;
  if (*p == '"') {
    p++;
    while (p < end) {
      if (*p == '\\') { p += 2; continue; }
      if (*p == '"') return p + 1;
      p++;
    }
    return nullptr;
  }
  if (*p == '{' || *p == '[') {
    char open = *p, close = (open == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    while (p < end) {
      char c = *p;
      if (in_str) {
        if (c == '\\') { p += 2; continue; }
        if (c == '"') in_str = false;
        p++;
        continue;
      }
      if (c == '"') in_str = true;
      else if (c == open) depth++;
      else if (c == close) { depth--; if (depth == 0) return p + 1; }
      p++;
    }
    return nullptr;
  }
  // number / true / false / null
  while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\t' && *p != '\n' && *p != '\r')
    p++;
  return p;
}

// Unescape a JSON string body (between the quotes). Only the escapes the
// wire actually produces; \uXXXX is decoded for BMP codepoints.
std::string unescape(const char* p, size_t n) {
  std::string out;
  out.reserve(n);
  const char* end = p + n;
  while (p < end) {
    if (*p != '\\') { out.push_back(*p++); continue; }
    if (++p >= end) break;
    char c = *p++;
    switch (c) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        if (p + 4 > end) return out;
        unsigned cp = 0;
        for (int i = 0; i < 4; i++) {
          char h = p[i];
          cp <<= 4;
          if (h >= '0' && h <= '9') cp |= h - '0';
          else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
          else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
          else return out;
        }
        p += 4;
        if (cp < 0x80) out.push_back((char)cp);
        else if (cp < 0x800) {
          out.push_back((char)(0xC0 | (cp >> 6)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else {
          out.push_back((char)(0xE0 | (cp >> 12)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default: out.push_back(c);
    }
  }
  return out;
}

// Scan a top-level JSON object into key -> raw value span fields.
bool scan_object(const char* body, size_t len, std::vector<JsonField>* out) {
  const char* p = body;
  const char* end = body + len;
  while (p < end && *p != '{') p++;
  if (p >= end) return false;
  p++;
  while (p < end) {
    while (p < end && (*p == ' ' || *p == ',' || *p == '\t' || *p == '\n' ||
                       *p == '\r'))
      p++;
    if (p < end && *p == '}') return true;
    if (p >= end || *p != '"') return false;
    const char* kstart = ++p;
    while (p < end && *p != '"') {
      if (*p == '\\') p++;
      p++;
    }
    if (p >= end) return false;
    std::string key = unescape(kstart, (size_t)(p - kstart));
    p++;  // closing quote
    while (p < end && (*p == ' ' || *p == ':')) p++;
    const char* vstart = p;
    const char* vend = skip_value(p, end);
    if (!vend) return false;
    out->push_back({std::move(key), vstart, (size_t)(vend - vstart)});
    p = vend;
  }
  return false;
}

const JsonField* find(const std::vector<JsonField>& fs, const char* key) {
  for (const auto& f : fs)
    if (f.key == key) return &f;
  return nullptr;
}

std::string str_value(const JsonField* f) {
  if (!f || f->val_len < 2 || f->val[0] != '"') return std::string();
  return unescape(f->val + 1, f->val_len - 2);
}

long long int_value(const JsonField* f, long long dflt = -1) {
  if (!f) return dflt;
  return strtoll(std::string(f->val, f->val_len).c_str(), nullptr, 10);
}

void append_json_str(std::string* out, const std::string& s) {
  out->push_back('"');
  for (char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

// ------------------------------------------- binwire -> JSON ops decode
// For JSON-ops legacy clients: decode an ops body (binwire.py layout)
// into the exact {"t":"ops","msgs":[message_to_dict(...)...]} frame the
// Python front end would send. One decode per batch per gateway, shared
// by every legacy subscriber of the topic.

void append_double(std::string* out, double v) {
  char buf[32];
  // shortest round-trip double; json accepts %.17g's forms
  snprintf(buf, sizeof buf, "%.17g", v);
  // ensure it parses as a float (json floats need . or e for Python to
  // produce a float — but int is fine too; Python accepts either)
  *out += buf;
}

struct BinReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint8_t u8() {
    if (p + 1 > end) { ok = false; return 0; }
    return *p++;
  }
  uint16_t u16() {
    if (p + 2 > end) { ok = false; return 0; }
    uint16_t v = ((uint16_t)p[0] << 8) | p[1];
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                 ((uint32_t)p[2] << 8) | p[3];
    p += 4;
    return v;
  }
  int32_t i32() { return (int32_t)u32(); }
  int64_t i64() {
    uint64_t hi = u32(), lo = u32();
    return (int64_t)((hi << 32) | lo);
  }
  double f64() {
    uint64_t hi = u32(), lo = u32();
    uint64_t bits = (hi << 32) | lo;
    double d;
    memcpy(&d, &bits, 8);
    return d;
  }
  std::string bytes_str(size_t n) {
    if (p + n > end) { ok = false; return std::string(); }
    std::string s((const char*)p, n);
    p += n;
    return s;
  }
};

// Decode an ops body (after MAGIC+ftype, i.e. starting at the pool) into
// a JSON frame body. Returns empty string on malformed input.
std::string ops_body_to_json(const uint8_t* body, size_t len) {
  BinReader r{body + 2, body + len};
  uint16_t npool = r.u16();
  std::vector<std::string> pool(npool);
  for (uint16_t i = 0; i < npool && r.ok; i++)
    pool[i] = r.bytes_str(r.u16());
  uint16_t nrec = r.u16();
  if (!r.ok) return std::string();
  std::string out = "{\"t\":\"ops\",\"msgs\":[";
  for (uint16_t i = 0; i < nrec; i++) {
    uint16_t cid_idx = r.u16();
    int64_t seq = r.i64(), msn = r.i64();
    int32_t cseq = r.i32(), rseq = r.i32();
    double ts = r.f64();
    uint8_t ntr = r.u8();
    std::string traces = "[";
    for (uint8_t t = 0; t < ntr && r.ok; t++) {
      uint16_t svc = r.u16(), act = r.u16();
      double hts = r.f64();
      if (svc >= npool || act >= npool) { r.ok = false; break; }
      if (t) traces += ",";
      traces += "{\"service\":";
      append_json_str(&traces, pool[svc]);
      traces += ",\"action\":";
      append_json_str(&traces, pool[act]);
      traces += ",\"timestamp\":";
      append_double(&traces, hts);
      traces += "}";
    }
    traces += "]";
    uint8_t kind = r.u8();
    std::string type_contents;  // '"type":...,"contents":...[,...]'
    if (kind <= 2) {
      uint16_t ds = r.u16(), ch = r.u16();
      if (ds >= npool || ch >= npool) r.ok = false;
      std::string op;
      if (kind == 0) {
        uint32_t pos = r.u32();
        std::string text = r.bytes_str(r.u16());
        op = "{\"type\":0,\"pos\":" + std::to_string(pos) + ",\"text\":";
        append_json_str(&op, text);
        op += "}";
      } else if (kind == 1) {
        uint32_t start = r.u32(), end2 = r.u32();
        op = "{\"type\":1,\"start\":" + std::to_string(start) +
             ",\"end\":" + std::to_string(end2) + "}";
      } else {
        uint32_t start = r.u32(), end2 = r.u32();
        std::string props = r.bytes_str(r.u16());
        op = "{\"type\":2,\"start\":" + std::to_string(start) +
             ",\"end\":" + std::to_string(end2) + ",\"props\":" + props +
             "}";
      }
      if (!r.ok) return std::string();
      type_contents = "\"type\":\"op\",\"contents\":{\"kind\":\"chanop\","
                      "\"address\":";
      append_json_str(&type_contents, pool[ds]);
      type_contents += ",\"contents\":{\"address\":";
      append_json_str(&type_contents, pool[ch]);
      type_contents += ",\"contents\":" + op + "}}";
      type_contents += ",\"metadata\":null,\"origin\":null";
    } else if (kind == 0xFF) {
      uint32_t ln = r.u32();
      std::string blob = r.bytes_str(ln);
      if (!r.ok) return std::string();
      // blob = {"type":...,"contents":...,"metadata":...[,"origin":...]}
      std::vector<JsonField> gf;
      if (!scan_object(blob.data(), blob.size(), &gf)) return std::string();
      const JsonField* ty = find(gf, "type");
      const JsonField* co = find(gf, "contents");
      const JsonField* md = find(gf, "metadata");
      const JsonField* og = find(gf, "origin");
      if (!ty) return std::string();
      type_contents = "\"type\":" + std::string(ty->val, ty->val_len);
      type_contents += ",\"contents\":";
      type_contents += co ? std::string(co->val, co->val_len) : "null";
      type_contents += ",\"metadata\":";
      type_contents += md ? std::string(md->val, md->val_len) : "null";
      type_contents += ",\"origin\":";
      type_contents += og ? std::string(og->val, og->val_len) : "null";
    } else {
      return std::string();
    }
    if (!r.ok) return std::string();
    if (i) out += ",";
    out += "{\"_kind\":\"seq\",\"client_id\":";
    if (cid_idx == 0xFFFF) {
      out += "null";
    } else {
      if (cid_idx >= npool) return std::string();
      append_json_str(&out, pool[cid_idx]);
    }
    out += ",\"sequence_number\":" + std::to_string(seq);
    out += ",\"minimum_sequence_number\":" + std::to_string(msn);
    out += ",\"client_sequence_number\":" + std::to_string(cseq);
    out += ",\"reference_sequence_number\":" + std::to_string(rseq);
    out += "," + type_contents;
    out += ",\"timestamp\":";
    append_double(&out, ts);
    out += ",\"traces\":" + traces + "}";
  }
  out += "]}";
  return out;
}

// Columnar ops decode for JSON-ops legacy clients. The column section
// (binwire.py columnar layout) is LITTLE-endian by design — numpy-native
// on the Python ends — so this reader is the LE twin of BinReader.

struct LeReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint16_t u16() {
    if (p + 2 > end) { ok = false; return 0; }
    uint16_t v = (uint16_t)p[0] | ((uint16_t)p[1] << 8);
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                 ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
    p += 4;
    return v;
  }
  int64_t i64() {
    uint64_t lo = u32(), hi = u32();
    return (int64_t)((hi << 32) | lo);
  }
  double f64() {
    uint64_t lo = u32(), hi = u32();
    uint64_t bits = (hi << 32) | lo;
    double d;
    memcpy(&d, &bits, 8);
    return d;
  }
  std::string bytes_str(size_t n) {
    if (p + n > end) { ok = false; return std::string(); }
    std::string s((const char*)p, n);
    p += n;
    return s;
  }
  bool skip(size_t n) {
    if (p + n > end) { ok = false; return false; }
    p += n;
    return true;
  }
};

int32_t rd_i32le(const uint8_t* p) {
  return (int32_t)((uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                   ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24));
}

int64_t rd_i64le(const uint8_t* p) {
  uint64_t lo = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
  uint64_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
  return (int64_t)((hi << 32) | lo);
}

// text_off holds CHARACTER offsets into the utf8 text blob; map them to
// byte offsets with a sequential walk (offsets are non-decreasing).
struct Utf8Walker {
  const char* s;
  size_t len;
  size_t byte = 0;
  long long ch = 0;
  size_t to_byte(long long target) {
    while (ch < target && byte < len) {
      unsigned char c = (unsigned char)s[byte];
      byte += (c < 0x80) ? 1 : (c < 0xE0) ? 2 : (c < 0xF0) ? 3 : 4;
      ch++;
    }
    return byte;
  }
};

// Decode a cols_ops body (MAGIC kFtColsOps stamp cols msns) into the
// exact {"t":"ops","msgs":[...]} frame front_end.py's JSON slot would
// produce. Empty string on malformed input.
std::string cols_body_to_json(const uint8_t* body, size_t len) {
  LeReader r{body + 2, body + len};
  std::string cid = r.bytes_str(r.u16());
  int64_t base_seq = r.i64();
  double ts = r.f64();
  uint16_t n = r.u16();
  std::string ds = r.bytes_str(r.u16());
  std::string ch = r.bytes_str(r.u16());
  if (!r.ok || n == 0) return std::string();
  const uint8_t* kind = r.p;
  if (!r.skip(n)) return std::string();
  const uint8_t* a = r.p;
  if (!r.skip(4ull * n)) return std::string();
  const uint8_t* b = r.p;
  if (!r.skip(4ull * n)) return std::string();
  const uint8_t* cseq = r.p;
  if (!r.skip(4ull * n)) return std::string();
  const uint8_t* rseq = r.p;
  if (!r.skip(4ull * n)) return std::string();
  const uint8_t* text_off = r.p;
  if (!r.skip(4ull * (n + 1))) return std::string();
  uint32_t tlen = r.u32();
  const char* text = (const char*)r.p;
  if (!r.skip(tlen)) return std::string();
  uint32_t plen = r.u32();
  const char* props_raw = (const char*)r.p;
  if (!r.skip(plen)) return std::string();
  const uint8_t* msns = r.p;
  if (!r.skip(8ull * n)) return std::string();
  // split the per-op props array (JSON list of dict-or-null) into spans
  std::vector<std::pair<const char*, size_t>> props_spans;
  if (plen) {
    const char* p = props_raw;
    const char* pend = props_raw + plen;
    while (p < pend && *p != '[') p++;
    if (p >= pend) return std::string();
    p++;
    while (p < pend) {
      while (p < pend && (*p == ' ' || *p == ',')) p++;
      if (p < pend && *p == ']') break;
      const char* vend = skip_value(p, pend);
      if (!vend) return std::string();
      props_spans.push_back({p, (size_t)(vend - p)});
      p = vend;
    }
    if (props_spans.size() != n) return std::string();
  }
  Utf8Walker w{text, tlen};
  std::string cid_json;
  append_json_str(&cid_json, cid);
  std::string out = "{\"t\":\"ops\",\"msgs\":[";
  for (uint16_t i = 0; i < n; i++) {
    uint8_t k = kind[i];
    std::string op;
    if (k == 0) {
      long long c0 = rd_i32le(text_off + 4ull * i);
      long long c1 = rd_i32le(text_off + 4ull * (i + 1));
      if (c1 < c0) return std::string();
      size_t b0 = w.to_byte(c0);
      size_t b1 = w.to_byte(c1);
      op = "{\"type\":0,\"pos\":" +
           std::to_string(rd_i32le(a + 4ull * i)) + ",\"text\":";
      append_json_str(&op, std::string(text + b0, b1 - b0));
      op += "}";
    } else if (k == 1) {
      op = "{\"type\":1,\"start\":" +
           std::to_string(rd_i32le(a + 4ull * i)) + ",\"end\":" +
           std::to_string(rd_i32le(b + 4ull * i)) + "}";
    } else if (k == 2) {
      op = "{\"type\":2,\"start\":" +
           std::to_string(rd_i32le(a + 4ull * i)) + ",\"end\":" +
           std::to_string(rd_i32le(b + 4ull * i)) + ",\"props\":";
      if (i < props_spans.size() && props_spans[i].second &&
          *props_spans[i].first == '{')
        op.append(props_spans[i].first, props_spans[i].second);
      else
        op += "{}";
      op += "}";
    } else {
      return std::string();
    }
    if (i) out += ",";
    out += "{\"_kind\":\"seq\",\"client_id\":" + cid_json;
    out += ",\"sequence_number\":" + std::to_string(base_seq + i);
    out += ",\"minimum_sequence_number\":" +
           std::to_string(rd_i64le(msns + 8ull * i));
    out += ",\"client_sequence_number\":" +
           std::to_string(rd_i32le(cseq + 4ull * i));
    out += ",\"reference_sequence_number\":" +
           std::to_string(rd_i32le(rseq + 4ull * i));
    out += ",\"type\":\"op\",\"contents\":{\"kind\":\"chanop\",\"address\":";
    append_json_str(&out, ds);
    out += ",\"contents\":{\"address\":";
    append_json_str(&out, ch);
    out += ",\"contents\":" + op + "}}";
    out += ",\"metadata\":null,\"origin\":null";
    out += ",\"timestamp\":";
    append_double(&out, ts);
    out += ",\"traces\":[]}";
  }
  out += "]}";
  return out;
}

// --------------------------------------------------------------- sessions

struct Session {
  int fd = -1;
  uint32_t sid = 0;        // 0 = not connected yet
  std::string topic;       // "tenant/doc" once connected
  bool binary = false;     // negotiated binwire ops push (bin:1)
  bool gated = false;      // connect in flight: buffer pushes
  std::vector<std::string> gate_buffer;
  size_t gate_bytes = 0;   // gate_buffer total, counted toward the
                           // slow-consumer bound (a gated session must
                           // not buffer unboundedly just because its
                           // connect reply is slow)
  std::string rbuf;        // partial inbound bytes
  std::deque<std::string> wq;  // pending outbound frames
  size_t wq_bytes = 0;
  size_t wq_off = 0;       // offset into wq.front()
  bool dead = false;
};

struct PendingRpc {
  int client_fd;
  std::string client_rid;  // raw span text ("7", "\"abc\"", or "" = absent)
  bool is_connect;
  uint32_t sid;            // for connect gating
};

struct Gateway {
  int epfd = -1;
  int listen_fd = -1;
  int up_fd = -1;
  int port = 0;
  std::string up_rbuf;
  std::deque<std::string> up_wq;
  size_t up_wq_off = 0;
  uint32_t next_sid = 1;
  long long next_rid = 1;
  std::unordered_map<int, Session> sessions;           // fd -> session
  std::unordered_map<uint32_t, int> sid_to_fd;
  std::unordered_map<long long, PendingRpc> pending;   // gateway rid -> rpc
  std::unordered_map<std::string, std::unordered_set<int>> topics;
  volatile bool stop = false;
};

void frame_header(std::string* out, size_t body_len) {
  out->push_back((char)((body_len >> 24) & 0xFF));
  out->push_back((char)((body_len >> 16) & 0xFF));
  out->push_back((char)((body_len >> 8) & 0xFF));
  out->push_back((char)(body_len & 0xFF));
}

std::string make_frame(const std::string& body) {
  std::string out;
  out.reserve(body.size() + 4);
  frame_header(&out, body.size());
  out += body;
  return out;
}

void arm_out(Gateway* g, int fd, bool want_out) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_out ? EPOLLOUT : 0);
  ev.data.fd = fd;
  epoll_ctl(g->epfd, EPOLL_CTL_MOD, fd, &ev);
}

void close_session(Gateway* g, int fd, bool notify_core);

// Queue a pre-framed byte string to a client session.
void send_to(Gateway* g, Session* s, std::string frame) {
  if (s->dead) return;
  if (s->gated) {
    s->gate_bytes += frame.size();
    if (s->wq_bytes + s->gate_bytes > kMaxBuffered) {
      s->dead = true;  // gated slow consumer: same bound as below
      return;
    }
    s->gate_buffer.push_back(std::move(frame));
    return;
  }
  s->wq_bytes += frame.size();
  if (s->wq_bytes + s->gate_bytes > kMaxBuffered) {
    s->dead = true;  // slow consumer: drop (mirrors MAX_BUFFERED)
    return;
  }
  bool was_empty = s->wq.empty();
  s->wq.push_back(std::move(frame));
  if (was_empty) {
    // opportunistic immediate write
    while (!s->wq.empty()) {
      const std::string& f = s->wq.front();
      ssize_t n = ::send(s->fd, f.data() + s->wq_off, f.size() - s->wq_off,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        s->dead = true;
        return;
      }
      s->wq_off += (size_t)n;
      if (s->wq_off == f.size()) {
        s->wq_bytes -= f.size();
        s->wq.pop_front();
        s->wq_off = 0;
      }
    }
    if (!s->wq.empty()) arm_out(g, s->fd, true);
  }
}

void send_upstream(Gateway* g, std::string frame) {
  bool was_empty = g->up_wq.empty();
  g->up_wq.push_back(std::move(frame));
  if (was_empty) {
    while (!g->up_wq.empty()) {
      const std::string& f = g->up_wq.front();
      ssize_t n = ::send(g->up_fd, f.data() + g->up_wq_off,
                         f.size() - g->up_wq_off,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        g->stop = true;
        return;
      }
      g->up_wq_off += (size_t)n;
      if (g->up_wq_off == f.size()) {
        g->up_wq.pop_front();
        g->up_wq_off = 0;
      }
    }
    if (!g->up_wq.empty()) arm_out(g, g->up_fd, true);
  }
}

void send_error(Gateway* g, Session* s, const std::string& rid_span,
                const std::string& msg) {
  std::string body = "{\"t\":\"error\"";
  if (!rid_span.empty()) {
    body += ",\"rid\":";
    body += rid_span;
  }
  body += ",\"message\":";
  append_json_str(&body, msg);
  body += "}";
  send_to(g, s, make_frame(body));
}

void detach_session(Gateway* g, Session* s, bool notify_core) {
  if (s->sid != 0) {
    auto it = g->topics.find(s->topic);
    if (it != g->topics.end()) {
      it->second.erase(s->fd);
      if (it->second.empty()) g->topics.erase(it);
    }
    if (notify_core && g->up_fd >= 0) {
      std::string body = "{\"t\":\"fdisconnect\",\"sid\":";
      body += std::to_string(s->sid);
      body += "}";
      send_upstream(g, make_frame(body));
    }
    g->sid_to_fd.erase(s->sid);
    s->sid = 0;
    s->topic.clear();
  }
}

void close_session(Gateway* g, int fd, bool notify_core) {
  auto it = g->sessions.find(fd);
  if (it == g->sessions.end()) return;
  detach_session(g, &it->second, notify_core);
  epoll_ctl(g->epfd, EPOLL_CTL_DEL, fd, nullptr);
  ::close(fd);
  g->sessions.erase(it);
}

// ----------------------------------------------------- client frame logic

void handle_client_json(Gateway* g, Session* s, const char* body, size_t len) {
  std::vector<JsonField> fields;
  if (!scan_object(body, len, &fields)) return;  // malformed: drop frame
  const JsonField* tf = find(fields, "t");
  std::string t = str_value(tf);
  const JsonField* ridf = find(fields, "rid");
  std::string rid_span = ridf ? std::string(ridf->val, ridf->val_len) : "";

  if (t == "connect") {
    if (s->sid != 0) detach_session(g, s, true);  // re-connect on live socket
    s->binary = int_value(find(fields, "bin"), 0) == 1;
    std::string tenant = str_value(find(fields, "tenant"));
    std::string doc = str_value(find(fields, "doc"));
    if (tenant.empty() || doc.empty()) {
      send_error(g, s, rid_span, "connect missing tenant/doc");
      return;
    }
    s->sid = g->next_sid++;
    s->topic = tenant + "/" + doc;
    s->gated = true;
    g->sid_to_fd[s->sid] = s->fd;
    g->topics[s->topic].insert(s->fd);
    long long grid = g->next_rid++;
    g->pending[grid] = {s->fd, rid_span, true, s->sid};
    // rebuild: {"t":"fconnect","sid":N,"rid":G,"bin":1, <other fields>}
    std::string out = "{\"t\":\"fconnect\",\"sid\":";
    out += std::to_string(s->sid);
    out += ",\"rid\":";
    out += std::to_string(grid);
    out += ",\"bin\":1";
    for (const auto& f : fields) {
      if (f.key == "t" || f.key == "rid" || f.key == "bin") continue;
      out += ",";
      append_json_str(&out, f.key);
      out += ":";
      out.append(f.val, f.val_len);
    }
    out += "}";
    send_upstream(g, make_frame(out));
  } else if (t == "submit") {
    if (s->sid == 0) { send_error(g, s, rid_span, "submit before connect"); return; }
    const JsonField* ops = find(fields, "ops");
    if (!ops) return;
    std::string out = "{\"t\":\"fsubmit\",\"sid\":";
    out += std::to_string(s->sid);
    out += ",\"ops\":";
    out.append(ops->val, ops->val_len);
    out += "}";
    send_upstream(g, make_frame(out));
  } else if (t == "signal") {
    if (s->sid == 0) { send_error(g, s, rid_span, "signal before connect"); return; }
    std::string out = "{\"t\":\"fsignal\",\"sid\":";
    out += std::to_string(s->sid);
    for (const auto& f : fields) {
      if (f.key == "t") continue;
      out += ",";
      append_json_str(&out, f.key);
      out += ":";
      out.append(f.val, f.val_len);
    }
    out += "}";
    send_upstream(g, make_frame(out));
  } else if (t == "disconnect") {
    detach_session(g, s, true);
  } else if (t == "ping") {
    // client liveness probe: answered at this hop (driver/network.py
    // recv-timeout escalation), never relayed upstream
    send_to(g, s, make_frame("{\"t\":\"pong\"}"));
  } else if (t == "get_deltas" || t == "get_versions" || t == "get_tree" ||
             t == "read_blob" || t == "write_blob" || t == "upload_summary") {
    long long grid = g->next_rid++;
    g->pending[grid] = {s->fd, rid_span, false, 0};
    std::string out = "{";
    bool first = true;
    for (const auto& f : fields) {
      if (f.key == "rid") continue;
      if (!first) out += ",";
      first = false;
      append_json_str(&out, f.key);
      out += ":";
      out.append(f.val, f.val_len);
    }
    out += first ? "\"rid\":" : ",\"rid\":";
    out += std::to_string(grid);
    out += "}";
    send_upstream(g, make_frame(out));
  } else {
    send_error(g, s, rid_span, "unknown frame type");
  }
}

void handle_client_frame(Gateway* g, Session* s, const char* body,
                         size_t len) {
  if (len >= 2 && (uint8_t)body[0] == kMagic) {
    uint8_t ft = (uint8_t)body[1];
    if ((ft == kFtSubmit || ft == kFtColsSubmit) && s->sid != 0) {
      // splice: 01 01 <batch> -> 01 03 u32sid <batch>; the columnar
      // twin is the identical rewrite (01 05 -> 01 06 u32sid)
      uint8_t hoptail_k =
          (ft == kFtColsSubmit && len >= 3) ? (uint8_t)body[len - 1] : 0;
      // sampled columnar frame (hoptail count > 0): splice the
      // gateway/relay hop before the trailing count byte — unsampled
      // frames cost one byte read, same as the Python gateway
      bool stamp = hoptail_k > 0 && hoptail_k < 0xFF;
      std::string out;
      out.reserve(len + 8 + 4 + (stamp ? 9 : 0));
      frame_header(&out, len + 4 + (stamp ? 9 : 0));
      out.push_back((char)kMagic);
      out.push_back((char)(ft == kFtSubmit ? kFtFsubmit : kFtColsFsubmit));
      out.push_back((char)((s->sid >> 24) & 0xFF));
      out.push_back((char)((s->sid >> 16) & 0xFF));
      out.push_back((char)((s->sid >> 8) & 0xFF));
      out.push_back((char)(s->sid & 0xFF));
      if (stamp) {
        out.append(body + 2, len - 3);  // content minus count byte
        out.push_back((char)1);         // hop id: gateway/relay
        struct timespec now_ts;
        clock_gettime(CLOCK_REALTIME, &now_ts);
        double now =
            (double)now_ts.tv_sec + (double)now_ts.tv_nsec * 1e-9;
        uint64_t bits;
        std::memcpy(&bits, &now, sizeof(bits));
        for (int i = 7; i >= 0; --i)
          out.push_back((char)((bits >> (8 * i)) & 0xFF));
        out.push_back((char)(hoptail_k + 1));
      } else {
        out.append(body + 2, len - 2);
      }
      send_upstream(g, std::move(out));
    } else {
      send_error(g, s, "", "unexpected binary frame");
    }
    return;
  }
  handle_client_json(g, s, body, len);
}

// --------------------------------------------------- upstream frame logic

void fan_out(Gateway* g, const std::string& topic, const std::string& frame) {
  auto it = g->topics.find(topic);
  if (it == g->topics.end()) return;
  // copy: send_to may mark sessions dead (erased later in the event loop)
  std::vector<int> fds(it->second.begin(), it->second.end());
  for (int fd : fds) {
    auto sit = g->sessions.find(fd);
    if (sit != g->sessions.end()) send_to(g, &sit->second, frame);
  }
}

// Decode a presence body (01 0B u16 n; n x [u16 cidlen cid (0xFFFF =
// null), u16 typelen type, u32 clen content-json]) into concatenated
// legacy {"t":"signal"} frames for a JSON session. The content span is
// already JSON and splices verbatim. Empty string on malformed input.
std::string presence_body_to_json_frames(const uint8_t* b, size_t len) {
  if (len < 4) return "";
  auto u16 = [&](size_t o) -> uint32_t {
    return ((uint32_t)b[o] << 8) | b[o + 1];
  };
  size_t off = 2;
  uint32_t n = u16(off);
  off += 2;
  std::string out;
  for (uint32_t i = 0; i < n; ++i) {
    if (off + 2 > len) return "";
    uint32_t cl = u16(off);
    off += 2;
    bool has_cid = cl != 0xFFFF;
    std::string cid;
    if (has_cid) {
      if (off + cl > len) return "";
      cid.assign((const char*)b + off, cl);
      off += cl;
    }
    if (off + 2 > len) return "";
    uint32_t tl = u16(off);
    off += 2;
    if (off + tl > len) return "";
    std::string type((const char*)b + off, tl);
    off += tl;
    if (off + 4 > len) return "";
    uint32_t clen = ((uint32_t)b[off] << 24) | ((uint32_t)b[off + 1] << 16) |
                    ((uint32_t)b[off + 2] << 8) | b[off + 3];
    off += 4;
    if (off + clen > len) return "";
    std::string sig =
        "{\"t\":\"signal\",\"signal\":{\"_kind\":\"signal\",\"client_id\":";
    if (has_cid) append_json_str(&sig, cid);
    else sig += "null";
    sig += ",\"type\":";
    append_json_str(&sig, type);
    sig += ",\"content\":";
    sig.append((const char*)b + off, clen);
    off += clen;
    sig += "}}";
    out += make_frame(sig);
  }
  return out;
}

void handle_upstream_frame(Gateway* g, const char* body, size_t len) {
  if (len >= 2 && (uint8_t)body[0] == kMagic) {
    uint8_t ft = (uint8_t)body[1];
    if ((ft == kFtFops || ft == kFtColsFops) && len >= 4) {
      // 01 04 u16 tlen topic <batch> -> topic, frame(01 02 <batch>);
      // the columnar twin strips identically (01 08 -> 01 07)
      size_t tlen = ((size_t)(uint8_t)body[2] << 8) | (uint8_t)body[3];
      if (4 + tlen > len) return;
      std::string topic(body + 4, tlen);
      std::string ops_body;
      ops_body.reserve(len - 4 - tlen + 2);
      ops_body.push_back((char)kMagic);
      ops_body.push_back((char)(ft == kFtFops ? kFtOps : kFtColsOps));
      ops_body.append(body + 4 + tlen, len - 4 - tlen);
      std::string bin_frame = make_frame(ops_body);
      auto it = g->topics.find(topic);
      if (it == g->topics.end()) return;
      std::string json_frame;  // lazily decoded once per batch
      bool json_failed = false;
      std::vector<int> fds(it->second.begin(), it->second.end());
      for (int fd : fds) {
        auto sit = g->sessions.find(fd);
        if (sit == g->sessions.end()) continue;
        Session* s = &sit->second;
        if (s->binary) {
          send_to(g, s, bin_frame);
        } else {
          if (json_frame.empty() && !json_failed) {
            std::string j =
                (ft == kFtFops)
                    ? ops_body_to_json((const uint8_t*)ops_body.data(),
                                       ops_body.size())
                    : cols_body_to_json((const uint8_t*)ops_body.data(),
                                        ops_body.size());
            if (j.empty()) json_failed = true;
            else json_frame = make_frame(j);
          }
          if (json_failed) {
            // a silently skipped batch would stall this session on
            // the seq gap forever — error + close instead, so its
            // reconnect + delta backfill repairs the stream
            send_error(g, s, "", "undecodable ops batch; reconnect");
            s->dead = true;
            continue;
          }
          send_to(g, s, json_frame);
        }
      }
    } else if (ft == kFtFpresence && len >= 4) {
      // 01 0C u16 tlen topic <batch> -> topic, frame(01 0B <batch>):
      // the presence lane's coalesced flush relays by the same topic
      // strip as fops — the batch bytes are never decoded for binary
      // subscribers
      size_t tlen = ((size_t)(uint8_t)body[2] << 8) | (uint8_t)body[3];
      if (4 + tlen > len) return;
      std::string topic(body + 4, tlen);
      std::string pbody;
      pbody.reserve(len - 4 - tlen + 2);
      pbody.push_back((char)kMagic);
      pbody.push_back((char)kFtPresence);
      pbody.append(body + 4 + tlen, len - 4 - tlen);
      std::string bin_frame = make_frame(pbody);
      auto it = g->topics.find(topic);
      if (it == g->topics.end()) return;
      std::string json_frames;  // lazily decoded once per flush
      bool json_tried = false;
      std::vector<int> fds(it->second.begin(), it->second.end());
      for (int fd : fds) {
        auto sit = g->sessions.find(fd);
        if (sit == g->sessions.end()) continue;
        Session* s = &sit->second;
        if (s->binary) {
          send_to(g, s, bin_frame);
        } else {
          if (!json_tried) {
            json_tried = true;
            json_frames = presence_body_to_json_frames(
                (const uint8_t*)pbody.data(), pbody.size());
          }
          // presence is ephemeral: a malformed batch drops silently —
          // unlike ops there is no sequence gap to stall on
          if (!json_frames.empty()) send_to(g, s, json_frames);
        }
      }
    }
    return;
  }
  std::vector<JsonField> fields;
  if (!scan_object(body, len, &fields)) return;
  const JsonField* ridf = find(fields, "rid");
  if (ridf) {
    long long grid = int_value(ridf);
    auto pit = g->pending.find(grid);
    if (pit == g->pending.end()) return;
    PendingRpc rpc = pit->second;
    g->pending.erase(pit);
    auto sit = g->sessions.find(rpc.client_fd);
    if (sit == g->sessions.end()) return;
    Session* s = &sit->second;
    const JsonField* tf = find(fields, "t");
    std::string t = str_value(tf);
    bool is_error = (t == "error");
    // rebuild the reply with the client's rid (and fconnected->connected)
    std::string out = "{";
    bool first = true;
    for (const auto& f : fields) {
      if (f.key == "rid" || f.key == "sid") continue;
      if (f.key == "t" && rpc.is_connect && !is_error) {
        if (!first) out += ",";
        first = false;
        out += "\"t\":\"connected\"";
        continue;
      }
      if (!first) out += ",";
      first = false;
      append_json_str(&out, f.key);
      out += ":";
      out.append(f.val, f.val_len);
    }
    if (!rpc.client_rid.empty()) {
      out += first ? "\"rid\":" : ",\"rid\":";
      out += rpc.client_rid;
    }
    out += "}";
    if (rpc.is_connect) {
      if (is_error) {
        // refused connect: unregister, drop the gate buffer
        s->gate_buffer.clear();
        s->gate_bytes = 0;
        s->gated = false;
        detach_session(g, s, false);
        send_to(g, s, make_frame(out));
      } else {
        // deliver connected FIRST, then the gated pushes, then ungate.
        // Each frame's bytes move from the gate account to the write
        // queue account as it replays — decrement BEFORE send_to so
        // the bound check never double-counts a frame mid-replay.
        s->gated = false;
        send_to(g, s, make_frame(out));
        for (auto& fbuf : s->gate_buffer) {
          s->gate_bytes -= fbuf.size();
          send_to(g, s, std::move(fbuf));
        }
        s->gate_buffer.clear();
      }
    } else {
      send_to(g, s, make_frame(out));
    }
    return;
  }
  const JsonField* tf = find(fields, "t");
  std::string t = str_value(tf);
  if (t == "fnack") {
    uint32_t sid = (uint32_t)int_value(find(fields, "sid"), 0);
    auto fit = g->sid_to_fd.find(sid);
    if (fit == g->sid_to_fd.end()) return;
    auto sit = g->sessions.find(fit->second);
    if (sit == g->sessions.end()) return;
    const JsonField* nack = find(fields, "nack");
    if (!nack) return;
    std::string out = "{\"t\":\"nack\",\"nack\":";
    out.append(nack->val, nack->val_len);
    out += "}";
    send_to(g, &sit->second, make_frame(out));
  } else if (t == "fsignal") {
    std::string topic = str_value(find(fields, "topic"));
    const JsonField* sig = find(fields, "signal");
    if (!sig) return;
    std::string out = "{\"t\":\"signal\",\"signal\":";
    out.append(sig->val, sig->val_len);
    out += "}";
    fan_out(g, topic, make_frame(out));
  } else if (t == "fops") {
    // core's JSON fallback for a batch binwire couldn't pack
    std::string topic = str_value(find(fields, "topic"));
    const JsonField* msgs = find(fields, "msgs");
    if (!msgs) return;
    std::string out = "{\"t\":\"ops\",\"msgs\":";
    out.append(msgs->val, msgs->val_len);
    out += "}";
    fan_out(g, topic, make_frame(out));
  } else if (t == "fdropped") {
    // core revoked this client's partition: close just that client so
    // its auto-reconnect lands on the takeover owner
    uint32_t sid = (uint32_t)int_value(find(fields, "sid"), 0);
    auto fit = g->sid_to_fd.find(sid);
    if (fit != g->sid_to_fd.end()) close_session(g, fit->second, false);
  }
}

// ------------------------------------------------------------- event loop

// Drain complete frames from a buffer; calls fn(body, len). Returns false
// on a framing error.
template <typename Fn>
bool drain_frames(std::string* buf, Fn fn) {
  size_t off = 0;
  while (buf->size() - off >= 4) {
    const unsigned char* p = (const unsigned char*)buf->data() + off;
    size_t n = ((size_t)p[0] << 24) | ((size_t)p[1] << 16) |
               ((size_t)p[2] << 8) | (size_t)p[3];
    if (n > kMaxFrame) return false;
    if (buf->size() - off - 4 < n) break;
    fn((const char*)buf->data() + off + 4, n);
    off += 4 + n;
  }
  if (off) buf->erase(0, off);
  return true;
}

void flush_wq(Gateway* g, int fd) {
  if (fd == g->up_fd) {
    while (!g->up_wq.empty()) {
      const std::string& f = g->up_wq.front();
      ssize_t n = ::send(fd, f.data() + g->up_wq_off, f.size() - g->up_wq_off,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        g->stop = true;
        return;
      }
      g->up_wq_off += (size_t)n;
      if (g->up_wq_off == f.size()) {
        g->up_wq.pop_front();
        g->up_wq_off = 0;
      }
    }
    arm_out(g, fd, false);
    return;
  }
  auto it = g->sessions.find(fd);
  if (it == g->sessions.end()) return;
  Session* s = &it->second;
  while (!s->wq.empty()) {
    const std::string& f = s->wq.front();
    ssize_t n = ::send(fd, f.data() + s->wq_off, f.size() - s->wq_off,
                       MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      s->dead = true;
      return;
    }
    s->wq_off += (size_t)n;
    if (s->wq_off == f.size()) {
      s->wq_bytes -= f.size();
      s->wq.pop_front();
      s->wq_off = 0;
    }
  }
  arm_out(g, fd, false);
}

}  // namespace

extern "C" {

void* gateway_create(const char* core_host, int core_port,
                     const char* listen_host, int listen_port) {
  auto* g = new Gateway();
  g->epfd = epoll_create1(0);
  if (g->epfd < 0) { delete g; return nullptr; }

  // upstream backbone
  g->up_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in up{};
  up.sin_family = AF_INET;
  up.sin_port = htons((uint16_t)core_port);
  if (inet_pton(AF_INET, core_host, &up.sin_addr) != 1) {
    hostent* he = gethostbyname(core_host);
    if (!he) { ::close(g->up_fd); delete g; return nullptr; }
    memcpy(&up.sin_addr, he->h_addr, (size_t)he->h_length);
  }
  if (connect(g->up_fd, (sockaddr*)&up, sizeof up) != 0) {
    ::close(g->up_fd);
    delete g;
    return nullptr;
  }
  int one = 1;
  setsockopt(g->up_fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);

  // listener (non-blocking: the accept drain loop must hit EAGAIN, not
  // block the relay)
  g->listen_fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  setsockopt(g->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)listen_port);
  if (inet_pton(AF_INET, listen_host, &addr.sin_addr) != 1)
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (bind(g->listen_fd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(g->listen_fd, 1024) != 0) {
    ::close(g->up_fd);
    ::close(g->listen_fd);
    delete g;
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(g->listen_fd, (sockaddr*)&addr, &alen);
  g->port = ntohs(addr.sin_port);

  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = g->listen_fd;
  epoll_ctl(g->epfd, EPOLL_CTL_ADD, g->listen_fd, &ev);
  ev.data.fd = g->up_fd;
  epoll_ctl(g->epfd, EPOLL_CTL_ADD, g->up_fd, &ev);
  return g;
}

int gateway_port(void* h) { return static_cast<Gateway*>(h)->port; }

void gateway_stop(void* h) { static_cast<Gateway*>(h)->stop = true; }

// Run the relay loop; returns 0 on clean stop, -1 when the core vanished.
int gateway_run(void* h) {
  auto* g = static_cast<Gateway*>(h);
  epoll_event events[256];
  char buf[256 * 1024];
  while (!g->stop) {
    int n = epoll_wait(g->epfd, events, 256, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n && !g->stop; i++) {
      int fd = events[i].data.fd;
      uint32_t evs = events[i].events;
      if (fd == g->listen_fd) {
        while (true) {
          int cfd = accept4(g->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Session s;
          s.fd = cfd;
          g->sessions.emplace(cfd, std::move(s));
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(g->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      if (fd == g->up_fd) {
        if (evs & EPOLLOUT) flush_wq(g, fd);
        if (evs & (EPOLLIN | EPOLLHUP | EPOLLERR)) {
          ssize_t r;
          while ((r = recv(fd, buf, sizeof buf, MSG_DONTWAIT)) > 0)
            g->up_rbuf.append(buf, (size_t)r);
          bool eof = (r == 0) || (r < 0 && errno != EAGAIN &&
                                  errno != EWOULDBLOCK);
          if (!drain_frames(&g->up_rbuf, [g](const char* b, size_t l) {
                handle_upstream_frame(g, b, l);
              }))
            eof = true;
          if (eof) {
            g->stop = true;  // core gone: every client is dead too
            return -1;
          }
        }
        continue;
      }
      auto sit = g->sessions.find(fd);
      if (sit == g->sessions.end()) continue;
      Session* s = &sit->second;
      if (evs & EPOLLOUT) flush_wq(g, fd);
      if (evs & (EPOLLIN | EPOLLHUP | EPOLLERR)) {
        ssize_t r;
        while ((r = recv(fd, buf, sizeof buf, MSG_DONTWAIT)) > 0)
          s->rbuf.append(buf, (size_t)r);
        bool eof = (r == 0) ||
                   (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK);
        if (!drain_frames(&s->rbuf, [g, s](const char* b, size_t l) {
              handle_client_frame(g, s, b, l);
            }))
          eof = true;
        if (eof || s->dead) {
          close_session(g, fd, true);
          continue;
        }
      }
      if (s->dead) close_session(g, fd, true);
    }
    // sweep sessions a fan-out marked dead (slow consumers)
    std::vector<int> dead;
    for (auto& [fd2, s2] : g->sessions)
      if (s2.dead) dead.push_back(fd2);
    for (int fd2 : dead) close_session(g, fd2, true);
  }
  return 0;
}

void gateway_destroy(void* h) {
  auto* g = static_cast<Gateway*>(h);
  for (auto& [fd, s] : g->sessions) ::close(fd);
  if (g->listen_fd >= 0) ::close(g->listen_fd);
  if (g->up_fd >= 0) ::close(g->up_fd);
  if (g->epfd >= 0) ::close(g->epfd);
  delete g;
}

}  // extern "C"
