// Content-addressed chunk store — the libgit2-role component.
//
// Ref role: nodegit/libgit2 gives the reference hash-addressed snapshot
// storage (git blobs/trees behind gitrest, SURVEY §2.9). Here: SHA-256
// addressed blobs fanned out over <dir>/<h[0:2]>/<h[2:]> exactly like
// .git/objects, with writes going through a temp file + rename so a
// crash never leaves a corrupt object. Dedup falls out of content
// addressing: an existing object is never rewritten.
//
// Self-contained SHA-256 (public-domain-style reference structure), no
// external deps. C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <sys/stat.h>
#ifndef _WIN32
#include <unistd.h>
#endif

namespace {

// ------------------------------------------------------------- sha-256
struct Sha256 {
    uint32_t h[8];
    uint64_t len = 0;
    uint8_t buf[64];
    size_t buflen = 0;

    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
        memcpy(h, init, sizeof(h));
    }

    static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

    void block(const uint8_t* p) {
        static const uint32_t K[64] = {
            0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
            0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
            0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
            0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
            0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
            0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
            0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
            0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
            0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
            0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
            0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
            0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
            0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
                   (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
                 g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t* p, size_t n) {
        len += n;
        while (n > 0) {
            size_t take = 64 - buflen < n ? 64 - buflen : n;
            memcpy(buf + buflen, p, take);
            buflen += take; p += take; n -= take;
            if (buflen == 64) { block(buf); buflen = 0; }
        }
    }

    void final_hex(char out[65]) {
        uint64_t bits = len * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t zero = 0;
        while (buflen != 56) update(&zero, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
        update(lenb, 8);
        static const char* hex = "0123456789abcdef";
        for (int i = 0; i < 8; i++)
            for (int j = 0; j < 4; j++) {
                uint8_t byte = (uint8_t)(h[i] >> (24 - 8 * j));
                out[i * 8 + j * 2] = hex[byte >> 4];
                out[i * 8 + j * 2 + 1] = hex[byte & 0xf];
            }
        out[64] = 0;
    }
};

struct Store {
    std::string dir;
};

bool valid_hash(const char* hash) {
    for (int i = 0; i < 64; i++) {
        char c = hash[i];
        if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f'))) return false;
    }
    return hash[64] == 0;
}

std::string object_path(const Store* s, const char* hash) {
    return s->dir + "/" + std::string(hash, 2) + "/" + std::string(hash + 2);
}

}  // namespace

extern "C" {

void* cas_open(const char* dir) {
    if (!dir) return nullptr;
    mkdir(dir, 0755);
    auto* s = new Store();
    s->dir = dir;
    return s;
}

void cas_close(void* handle) { delete static_cast<Store*>(handle); }

// Store a blob; writes its 64-hex-char sha256 into hash_out (65 bytes).
// Dedup: existing objects are not rewritten. Returns 0, or -1 on error.
int cas_put(void* handle, const void* data, int64_t len, char* hash_out) {
    auto* s = static_cast<Store*>(handle);
    if (!s || (!data && len > 0) || len < 0 || !hash_out) return -1;
    Sha256 sha;
    sha.update(static_cast<const uint8_t*>(data), (size_t)len);
    sha.final_hex(hash_out);

    std::string path = object_path(s, hash_out);
    struct stat st;
    if (stat(path.c_str(), &st) == 0) return 0;  // dedup hit

    std::string fan = s->dir + "/" + std::string(hash_out, 2);
    mkdir(fan.c_str(), 0755);
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    if (len > 0 && fwrite(data, 1, (size_t)len, f) != (size_t)len) {
        fclose(f);
        remove(tmp.c_str());
        return -1;
    }
    fflush(f);
#ifndef _WIN32
    fsync(fileno(f));
#endif
    fclose(f);
    if (rename(tmp.c_str(), path.c_str()) != 0) {
        remove(tmp.c_str());
        return -1;
    }
    return 0;
}

// Read a blob; returns its length. If it exceeds buflen the buffer is
// untouched and the needed size is returned. -1 if absent/bad hash.
int64_t cas_get(void* handle, const char* hash, void* buf, int64_t buflen) {
    auto* s = static_cast<Store*>(handle);
    if (!s || !hash || !valid_hash(hash)) return -1;
    FILE* f = fopen(object_path(s, hash).c_str(), "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    int64_t len = (int64_t)ftell(f);
    if (len > buflen) { fclose(f); return len; }
    fseek(f, 0, SEEK_SET);
    if (len > 0 && fread(buf, 1, (size_t)len, f) != (size_t)len) {
        fclose(f);
        return -1;
    }
    fclose(f);
    return len;
}

int cas_has(void* handle, const char* hash) {
    auto* s = static_cast<Store*>(handle);
    if (!s || !hash || !valid_hash(hash)) return 0;
    struct stat st;
    return stat(object_path(s, hash).c_str(), &st) == 0 ? 1 : 0;
}

}  // extern "C"
