// Durable append-only partitioned op log — the librdkafka-role component.
//
// Ref role: node-rdkafka/librdkafka carries the ordered, checkpointed
// message log between the reference's pipeline stages (SURVEY §2.9).
// Here: one directory per log, one (data, index) file pair per topic.
// Data file: length-prefixed records; index file: uint64 byte offsets,
// one per record, so offset->record lookup is O(1) and recovery is a
// single index scan. Appends are buffered by libc and made durable by
// oplog_sync (the checkpoint boundary deli/scribe flush on).
//
// C ABI (ctypes-friendly), no exceptions across the boundary.

#include <cstdint>
#include <cstdio>
#include <cctype>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#ifdef _WIN32
#include <io.h>
#else
#include <unistd.h>
#endif
#include <vector>

namespace {

// portable file truncation: recovery MUST be able to cut ragged tails on
// every platform, or a crash mid-write leaves misaligned index entries
// that silently corrupt later record ordinals
int truncate_file(FILE *f, uint64_t size) {
#ifdef _WIN32
    return _chsize_s(_fileno(f), (long long)size);
#else
    return ftruncate(fileno(f), (off_t)size);
#endif
}

struct Topic {
    FILE* data = nullptr;
    FILE* index = nullptr;
    std::vector<uint64_t> offsets;  // byte offset of each record
    uint64_t data_end = 0;
    bool dirty = false;  // appended-to since the last flush/sync
};

struct OpLog {
    std::string dir;
    std::map<std::string, Topic> topics;
    std::mutex mu;
    // consumer-process handles: never truncate (recovery is the single
    // writer's job — a reader truncating a live writer's ragged tail
    // would silently shift the writer's record ordinals)
    bool readonly = false;
};

bool valid_topic_name(const char* t) {
    for (const char* p = t; *p; ++p) {
        if (!(isalnum(*p) || *p == '-' || *p == '_' || *p == '.')) return false;
    }
    return *t != 0;
}

Topic* get_topic(OpLog* log, const char* name) {
    auto it = log->topics.find(name);
    if (it != log->topics.end()) return &it->second;
    if (!valid_topic_name(name)) return nullptr;

    Topic t;
    std::string base = log->dir + "/" + name;
    std::string dpath = base + ".data", ipath = base + ".idx";
    const char* mode = log->readonly ? "rb" : "ab+";
    t.data = fopen(dpath.c_str(), mode);
    t.index = fopen(ipath.c_str(), mode);
    if (!t.data || !t.index) {
        // readonly: the producer has not created this topic yet — the
        // caller (oplog_refresh) retries later; not cached as a failure
        if (t.data) fclose(t.data);
        if (t.index) fclose(t.index);
        return nullptr;
    }
    // recover the index
    fseek(t.index, 0, SEEK_SET);
    uint64_t off;
    while (fread(&off, sizeof(off), 1, t.index) == 1) t.offsets.push_back(off);
    // a torn trailing PARTIAL index entry (crash mid-index-write) must be
    // cut even when every complete entry validates against the data extent
    // below — otherwise the next append lands misaligned after the ragged
    // tail and silently corrupts the ordinals of later records
    fseek(t.index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(t.index);
    if (index_bytes != t.offsets.size() * sizeof(uint64_t) &&
        !log->readonly) {
        if (truncate_file(t.index,
                          t.offsets.size() * sizeof(uint64_t)) != 0) {
            fclose(t.data);
            fclose(t.index);
            return nullptr;
        }
    }
    fseek(t.data, 0, SEEK_END);
    t.data_end = (uint64_t)ftell(t.data);
    // drop torn trailing records (crash mid-append): index entries whose
    // record extends past the data end. The files MUST be truncated to the
    // validated extent too — an in-memory-only drop would let the next
    // append re-expose the stale index entry on a subsequent restart,
    // shifting every record ordinal.
    size_t valid = t.offsets.size();
    uint64_t valid_end = t.data_end;
    while (valid > 0) {
        uint64_t last = t.offsets[valid - 1];
        uint32_t len = 0;
        if (last + sizeof(len) <= t.data_end) {
            fseek(t.data, (long)last, SEEK_SET);
            if (fread(&len, sizeof(len), 1, t.data) == 1 &&
                last + sizeof(len) + len <= t.data_end) {
                valid_end = last + sizeof(len) + len;
                break;
            }
        }
        valid--;
        valid_end = last;
    }
    if (valid < t.offsets.size() || valid_end < t.data_end) {
        t.offsets.resize(valid);
        if (log->readonly) {
            // in-memory drop only: the tail may simply be mid-write by
            // the live producer; oplog_refresh re-admits it once whole
            t.data_end = valid_end;
        } else {
            fflush(t.index);
            fflush(t.data);
            if (truncate_file(t.index, valid * sizeof(uint64_t)) != 0 ||
                truncate_file(t.data, valid_end) != 0) {
                fclose(t.data);
                fclose(t.index);
                return nullptr;
            }
            t.data_end = valid_end;
        }
    }
    auto res = log->topics.emplace(name, std::move(t));
    return &res.first->second;
}

}  // namespace

extern "C" {

void* oplog_open(const char* dir) {
    if (!dir) return nullptr;
    mkdir(dir, 0755);  // EEXIST is fine
    auto* log = new OpLog();
    log->dir = dir;
    return log;
}

// Consumer-process handle: reads and tails topics another process
// writes; never creates or truncates files.
void* oplog_open_readonly(const char* dir) {
    if (!dir) return nullptr;
    auto* log = new OpLog();
    log->dir = dir;
    log->readonly = true;
    return log;
}

void oplog_close(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return;
    for (auto& kv : log->topics) {
        if (kv.second.data) fclose(kv.second.data);
        if (kv.second.index) fclose(kv.second.index);
    }
    delete log;
}

// Append one record; returns its offset (record ordinal), or -1 on error.
int64_t oplog_append(void* handle, const char* topic, const void* data,
                     int64_t len) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic || (!data && len > 0) || len < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t) return -1;
    uint64_t record_start = t->data_end;
    uint32_t len32 = (uint32_t)len;
    fseek(t->data, 0, SEEK_END);
    bool ok = fwrite(&len32, sizeof(len32), 1, t->data) == 1 &&
              (len == 0 || fwrite(data, 1, (size_t)len, t->data) == (size_t)len);
    if (ok) {
        fseek(t->index, 0, SEEK_END);
        ok = fwrite(&record_start, sizeof(record_start), 1, t->index) == 1;
    }
    if (!ok) {
        // roll the data file back to the last valid extent, or the next
        // append would index a record that starts inside garbage bytes
        fflush(t->data);
        truncate_file(t->data, t->data_end);  // portable rollback
        fseek(t->data, 0, SEEK_END);
        return -1;
    }
    t->data_end = record_start + sizeof(len32) + (uint64_t)len;
    t->offsets.push_back(record_start);
    t->dirty = true;
    return (int64_t)t->offsets.size() - 1;
}

int64_t oplog_length(void* handle, const char* topic) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    return t ? (int64_t)t->offsets.size() : -1;
}

// Read record `offset`; returns record length. If it exceeds buflen the
// buffer is untouched and the needed size is returned (call again).
// Returns -1 on bad args / unknown record.
int64_t oplog_read(void* handle, const char* topic, int64_t offset, void* buf,
                   int64_t buflen) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic || offset < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t || (uint64_t)offset >= t->offsets.size()) return -1;
    uint64_t start = t->offsets[(size_t)offset];
    uint32_t len = 0;
    fflush(t->data);
    fseek(t->data, (long)start, SEEK_SET);
    if (fread(&len, sizeof(len), 1, t->data) != 1) return -1;
    if ((int64_t)len > buflen) return (int64_t)len;
    if (len > 0 && fread(buf, 1, len, t->data) != len) return -1;
    return (int64_t)len;
}

// Push buffered appends into the OS page cache (fflush, no fsync) so a
// CONSUMER PROCESS sharing the directory can see them via oplog_refresh.
// The per-stage process composition (service/stage_runner.py) flushes at
// drain-batch boundaries: visibility, not durability — durability stays
// on oplog_sync at checkpoint boundaries.
int oplog_flush(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    for (auto& kv : log->topics) {
        if (!kv.second.dirty) continue;  // O(appended), not O(topics)
        fflush(kv.second.data);
        fflush(kv.second.index);
        kv.second.dirty = false;
    }
    return 0;
}

// Re-scan the on-disk index tail for records appended by ANOTHER process
// sharing this directory; returns the refreshed record count (or -1).
// Only COMPLETE records (index entry present AND the data extent covers
// the whole record) are admitted — a record mid-write by the producer
// stays invisible until its bytes land, so tailing never sees a torn
// record. Unlike recovery, nothing is truncated here.
int64_t oplog_refresh(void* handle, const char* topic) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t) return -1;
    fseek(t->index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(t->index);
    size_t disk_n = (size_t)(index_bytes / sizeof(uint64_t));
    size_t have = t->offsets.size();
    if (disk_n <= have) return (int64_t)have;
    fseek(t->data, 0, SEEK_END);
    uint64_t data_bytes = (uint64_t)ftell(t->data);
    fseek(t->index, (long)(have * sizeof(uint64_t)), SEEK_SET);
    uint64_t off;
    uint64_t new_end = t->data_end;
    while (t->offsets.size() < disk_n &&
           fread(&off, sizeof(off), 1, t->index) == 1) {
        uint32_t len = 0;
        if (off + sizeof(len) > data_bytes) break;
        fseek(t->data, (long)off, SEEK_SET);
        if (fread(&len, sizeof(len), 1, t->data) != 1) break;
        if (off + sizeof(len) + len > data_bytes) break;
        t->offsets.push_back(off);
        new_end = off + sizeof(len) + (uint64_t)len;
    }
    if (new_end > t->data_end) t->data_end = new_end;
    return (int64_t)t->offsets.size();
}

// Make everything appended so far durable (fflush + fsync).
int oplog_sync(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    for (auto& kv : log->topics) {
        fflush(kv.second.data);
        fflush(kv.second.index);
#ifndef _WIN32
        fsync(fileno(kv.second.data));
        fsync(fileno(kv.second.index));
#endif
    }
    return 0;
}

}  // extern "C"
