// Durable append-only partitioned op log — the librdkafka-role component.
//
// Ref role: node-rdkafka/librdkafka carries the ordered, checkpointed
// message log between the reference's pipeline stages (SURVEY §2.9).
// Here: one directory per log, one (data, index) file pair per topic.
// Data file: length-prefixed records; index file: uint64 byte offsets,
// one per record, so offset->record lookup is O(1) and recovery is a
// single index scan. Appends are buffered by libc and made durable by
// oplog_sync (the checkpoint boundary deli/scribe flush on).
//
// C ABI (ctypes-friendly), no exceptions across the boundary.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cctype>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#ifdef _WIN32
#include <io.h>
#else
#include <unistd.h>
#endif
#include <utility>
#include <vector>

namespace {

// portable file truncation: recovery MUST be able to cut ragged tails on
// every platform, or a crash mid-write leaves misaligned index entries
// that silently corrupt later record ordinals
int truncate_file(FILE *f, uint64_t size) {
#ifdef _WIN32
    return _chsize_s(_fileno(f), (long long)size);
#else
    return ftruncate(fileno(f), (off_t)size);
#endif
}

struct Topic {
    FILE* data = nullptr;
    FILE* index = nullptr;
    std::vector<uint64_t> offsets;  // byte offset of each record
    uint64_t data_end = 0;
    bool dirty = false;  // appended-to since the last flush/sync
    bool unsynced = false;  // appended-to since the last fsync
    uint64_t last_use = 0;  // handle-LRU stamp
};

// --------------------------------------------------------------- segments
// Columnar segment streams (the Kafka segment+index trick): block bytes
// are packed back to back into fixed-size segment files
// <stream>.seg<k>, and a flat side index <stream>.segidx holds one
// 32-byte entry per block:
//
//     entry := i64 first_seq, i64 last_seq,
//              u32 seg, u32 off, u32 len, u32 btype   (little-endian)
//
// first/last_seq are the block's sequence-number span (non-decreasing
// across entries — the deltas topic is appended in ticket order), so a
// [from_seq, to_seq) backfill is a binary search over two sorted i64
// columns plus raw byte-range reads. The Python side (service/
// segment_store.py) mmaps the index + segment files and reads with one
// np.frombuffer per file; this side owns appends, the segment roll, and
// the torn-tail scan.

struct SegEntry {
    int64_t first_seq;
    int64_t last_seq;
    uint32_t seg;
    uint32_t off;
    uint32_t len;
    uint32_t btype;
};
static_assert(sizeof(SegEntry) == 32, "segidx entry layout is on-disk ABI");

struct SegStream {
    FILE* index = nullptr;
    FILE* data = nullptr;       // tail segment (writer only)
    uint32_t cur_seg = 0;
    uint64_t cur_off = 0;       // validated byte extent of the tail segment
    std::vector<SegEntry> entries;
    bool dirty = false;
    bool unsynced = false;      // appended-to since the last fsync
    bool torn = false;          // deliberate torn bytes past cur_off on disk
    uint64_t last_use = 0;      // handle-LRU stamp
};

struct OpLog {
    std::string dir;
    std::map<std::string, Topic> topics;
    std::map<std::string, SegStream> segs;
    std::mutex mu;
    uint64_t seg_bytes = 4u << 20;  // segment roll threshold
    // consumer-process handles: never truncate (recovery is the single
    // writer's job — a reader truncating a live writer's ragged tail
    // would silently shift the writer's record ordinals)
    bool readonly = false;
    // ------------------------------------------------------ handle LRU
    // Topic/stream METADATA (offsets, seg entries, extents) stays
    // resident forever — it is what makes length/read O(1) — but the
    // FILE*s behind it are a bounded cache: a core holding 10k
    // rehydrated docs at ~8 handles each would blow any RLIMIT_NOFILE.
    // When open_files exceeds fd_cap (0 = unlimited), the
    // least-recently-used quarter is flushed and closed; a later touch
    // reopens on demand and trusts the in-memory metadata (single
    // writer — no re-scan, no truncation).
    uint64_t fd_cap = 0;
    uint64_t open_files = 0;
    uint64_t lru_clock = 0;
    // files with appends not yet fsync'd whose handles were evicted:
    // oplog_sync must cover them or the checkpoint-boundary durability
    // contract silently narrows to "whatever happened to still be open"
    std::vector<std::string> evicted_unsynced;
};

void evict_excess(OpLog* log) {
    if (log->fd_cap == 0 || log->open_files <= log->fd_cap) return;
    std::vector<std::pair<uint64_t, std::pair<bool, const std::string*>>> open_entries;
    for (auto& kv : log->topics)
        if (kv.second.data)
            open_entries.push_back({kv.second.last_use, {false, &kv.first}});
    for (auto& kv : log->segs)
        // a torn stream's on-disk residue is deliberate state the next
        // append must find exactly as left — never cycle its handles
        if ((kv.second.index || kv.second.data) && !kv.second.torn)
            open_entries.push_back({kv.second.last_use, {true, &kv.first}});
    std::sort(open_entries.begin(), open_entries.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    // close down to 3/4 of the cap so evictions amortize over many opens
    uint64_t target = log->fd_cap - log->fd_cap / 4;
    for (const auto& ent : open_entries) {
        if (log->open_files <= target) break;
        if (ent.first == log->lru_clock) continue;  // the entry in use now
        if (ent.second.first) {
            SegStream& s = log->segs[*ent.second.second];
            if (s.data) fflush(s.data);
            if (s.index) fflush(s.index);
            if (s.unsynced) {
                log->evicted_unsynced.push_back(
                    log->dir + "/" + *ent.second.second + ".segidx");
                log->evicted_unsynced.push_back(
                    log->dir + "/" + *ent.second.second + ".seg" +
                    std::to_string(s.cur_seg));
                s.unsynced = false;
            }
            if (s.data) { fclose(s.data); s.data = nullptr; log->open_files--; }
            if (s.index) { fclose(s.index); s.index = nullptr; log->open_files--; }
            s.dirty = false;
        } else {
            Topic& t = log->topics[*ent.second.second];
            fflush(t.data);
            fflush(t.index);
            if (t.unsynced) {
                log->evicted_unsynced.push_back(
                    log->dir + "/" + *ent.second.second + ".data");
                log->evicted_unsynced.push_back(
                    log->dir + "/" + *ent.second.second + ".idx");
                t.unsynced = false;
            }
            fclose(t.data);
            fclose(t.index);
            t.data = t.index = nullptr;
            t.dirty = false;
            log->open_files -= 2;
        }
    }
}

// reopen an evicted topic's handles, trusting the resident metadata
bool reopen_topic(OpLog* log, const std::string& name, Topic* t) {
    std::string base = log->dir + "/" + name;
    const char* mode = log->readonly ? "rb" : "ab+";
    t->data = fopen((base + ".data").c_str(), mode);
    t->index = fopen((base + ".idx").c_str(), mode);
    if (!t->data || !t->index) {
        if (t->data) fclose(t->data);
        if (t->index) fclose(t->index);
        t->data = t->index = nullptr;
        return false;
    }
    log->open_files += 2;
    return true;
}

bool valid_topic_name(const char* t) {
    for (const char* p = t; *p; ++p) {
        if (!(isalnum(*p) || *p == '-' || *p == '_' || *p == '.')) return false;
    }
    return *t != 0;
}

Topic* get_topic(OpLog* log, const char* name) {
    auto it = log->topics.find(name);
    if (it != log->topics.end()) {
        Topic* t = &it->second;
        t->last_use = ++log->lru_clock;
        if (!t->data) {  // evicted: reopen on demand
            if (!reopen_topic(log, it->first, t)) return nullptr;
            evict_excess(log);
        }
        return t;
    }
    if (!valid_topic_name(name)) return nullptr;

    Topic t;
    std::string base = log->dir + "/" + name;
    std::string dpath = base + ".data", ipath = base + ".idx";
    const char* mode = log->readonly ? "rb" : "ab+";
    t.data = fopen(dpath.c_str(), mode);
    t.index = fopen(ipath.c_str(), mode);
    if (!t.data || !t.index) {
        // readonly: the producer has not created this topic yet — the
        // caller (oplog_refresh) retries later; not cached as a failure
        if (t.data) fclose(t.data);
        if (t.index) fclose(t.index);
        return nullptr;
    }
    // recover the index
    fseek(t.index, 0, SEEK_SET);
    uint64_t off;
    while (fread(&off, sizeof(off), 1, t.index) == 1) t.offsets.push_back(off);
    // a torn trailing PARTIAL index entry (crash mid-index-write) must be
    // cut even when every complete entry validates against the data extent
    // below — otherwise the next append lands misaligned after the ragged
    // tail and silently corrupts the ordinals of later records
    fseek(t.index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(t.index);
    if (index_bytes != t.offsets.size() * sizeof(uint64_t) &&
        !log->readonly) {
        if (truncate_file(t.index,
                          t.offsets.size() * sizeof(uint64_t)) != 0) {
            fclose(t.data);
            fclose(t.index);
            return nullptr;
        }
    }
    fseek(t.data, 0, SEEK_END);
    t.data_end = (uint64_t)ftell(t.data);
    // drop torn trailing records (crash mid-append): index entries whose
    // record extends past the data end. The files MUST be truncated to the
    // validated extent too — an in-memory-only drop would let the next
    // append re-expose the stale index entry on a subsequent restart,
    // shifting every record ordinal.
    size_t valid = t.offsets.size();
    uint64_t valid_end = t.data_end;
    while (valid > 0) {
        uint64_t last = t.offsets[valid - 1];
        uint32_t len = 0;
        if (last + sizeof(len) <= t.data_end) {
            fseek(t.data, (long)last, SEEK_SET);
            if (fread(&len, sizeof(len), 1, t.data) == 1 &&
                last + sizeof(len) + len <= t.data_end) {
                valid_end = last + sizeof(len) + len;
                break;
            }
        }
        valid--;
        valid_end = last;
    }
    if (valid < t.offsets.size() || valid_end < t.data_end) {
        t.offsets.resize(valid);
        if (log->readonly) {
            // in-memory drop only: the tail may simply be mid-write by
            // the live producer; oplog_refresh re-admits it once whole
            t.data_end = valid_end;
        } else {
            fflush(t.index);
            fflush(t.data);
            if (truncate_file(t.index, valid * sizeof(uint64_t)) != 0 ||
                truncate_file(t.data, valid_end) != 0) {
                fclose(t.data);
                fclose(t.index);
                return nullptr;
            }
            t.data_end = valid_end;
        }
    }
    t.last_use = ++log->lru_clock;
    auto res = log->topics.emplace(name, std::move(t));
    log->open_files += 2;
    evict_excess(log);
    return &res.first->second;
}

std::string seg_path(OpLog* log, const char* name, uint32_t seg) {
    return log->dir + "/" + name + ".seg" + std::to_string(seg);
}

// physical size of segment file <name>.seg<k>, or 0 when absent
uint64_t seg_file_size(OpLog* log, const char* name, uint32_t seg) {
    FILE* f = fopen(seg_path(log, name, seg).c_str(), "rb");
    if (!f) return 0;
    fseek(f, 0, SEEK_END);
    uint64_t n = (uint64_t)ftell(f);
    fclose(f);
    return n;
}

// reopen an evicted stream's handles, trusting the resident metadata
// (the eviction flushed, so the tail segment's extent is authoritative)
bool reopen_seg(OpLog* log, const std::string& name, SegStream* s) {
    std::string ipath = log->dir + "/" + name + ".segidx";
    s->index = fopen(ipath.c_str(), log->readonly ? "rb" : "ab+");
    if (!s->index) return false;
    log->open_files += 1;
    if (!log->readonly) {
        s->data = fopen(seg_path(log, name.c_str(), s->cur_seg).c_str(),
                        "ab+");
        if (!s->data) {
            fclose(s->index);
            s->index = nullptr;
            log->open_files -= 1;
            return false;
        }
        log->open_files += 1;
    }
    return true;
}

SegStream* get_seg(OpLog* log, const char* name) {
    auto it = log->segs.find(name);
    if (it != log->segs.end()) {
        SegStream* s = &it->second;
        s->last_use = ++log->lru_clock;
        if (!s->index) {  // evicted: reopen on demand
            if (!reopen_seg(log, it->first, s)) return nullptr;
            evict_excess(log);
        }
        return s;
    }
    if (!valid_topic_name(name)) return nullptr;

    SegStream s;
    std::string ipath = log->dir + "/" + name + ".segidx";
    s.index = fopen(ipath.c_str(), log->readonly ? "rb" : "ab+");
    if (!s.index) return nullptr;  // readonly: producer not there yet
    fseek(s.index, 0, SEEK_SET);
    SegEntry e;
    while (fread(&e, sizeof(e), 1, s.index) == 1) s.entries.push_back(e);
    fseek(s.index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(s.index);
    // torn-tail scan, index side: cut a partial trailing entry (crash
    // mid-index-write), then walk back entries whose block bytes never
    // fully landed in the segment file (crash mid-block-write)
    bool ragged = index_bytes != s.entries.size() * sizeof(SegEntry);
    while (!s.entries.empty()) {
        const SegEntry& last = s.entries.back();
        if ((uint64_t)last.off + last.len <=
            seg_file_size(log, name, last.seg)) break;
        s.entries.pop_back();
        ragged = true;
    }
    if (ragged && !log->readonly) {
        if (truncate_file(s.index, s.entries.size() * sizeof(SegEntry)) != 0) {
            fclose(s.index);
            return nullptr;
        }
    }
    if (!s.entries.empty()) {
        s.cur_seg = s.entries.back().seg;
        s.cur_off = (uint64_t)s.entries.back().off + s.entries.back().len;
    }
    if (!log->readonly) {
        // writer owns the tail segment: open it and cut any bytes past the
        // validated extent (torn block data with no surviving index entry)
        s.data = fopen(seg_path(log, name, s.cur_seg).c_str(), "ab+");
        if (!s.data) {
            fclose(s.index);
            return nullptr;
        }
        fseek(s.data, 0, SEEK_END);
        if ((uint64_t)ftell(s.data) != s.cur_off &&
            truncate_file(s.data, s.cur_off) != 0) {
            fclose(s.index);
            fclose(s.data);
            return nullptr;
        }
    }
    s.last_use = ++log->lru_clock;
    auto res = log->segs.emplace(name, std::move(s));
    log->open_files += res.first->second.data ? 2 : 1;
    evict_excess(log);
    return &res.first->second;
}

// drop in-process knowledge of deliberate torn bytes (oplog_seg_tear) by
// truncating the files back to the validated extent — the same cut the
// open-time scan would make after a real crash
bool seg_untear(SegStream* s) {
    fflush(s->data);
    fflush(s->index);
    if (truncate_file(s->index, s->entries.size() * sizeof(SegEntry)) != 0 ||
        truncate_file(s->data, s->cur_off) != 0)
        return false;
    s->torn = false;
    return true;
}

}  // namespace

extern "C" {

void* oplog_open(const char* dir) {
    if (!dir) return nullptr;
    mkdir(dir, 0755);  // EEXIST is fine
    auto* log = new OpLog();
    log->dir = dir;
    return log;
}

// Consumer-process handle: reads and tails topics another process
// writes; never creates or truncates files.
void* oplog_open_readonly(const char* dir) {
    if (!dir) return nullptr;
    auto* log = new OpLog();
    log->dir = dir;
    log->readonly = true;
    return log;
}

void oplog_close(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return;
    for (auto& kv : log->topics) {
        if (kv.second.data) fclose(kv.second.data);
        if (kv.second.index) fclose(kv.second.index);
    }
    for (auto& kv : log->segs) {
        if (kv.second.data) fclose(kv.second.data);
        if (kv.second.index) fclose(kv.second.index);
    }
    delete log;
}

// Segment roll threshold for every stream of this handle (testing knob;
// production leaves the 4 MiB default). Affects future appends only.
int oplog_seg_config(void* handle, int64_t seg_bytes) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || seg_bytes <= 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    log->seg_bytes = (uint64_t)seg_bytes;
    return 0;
}

// Append one column block spanning sequence numbers [first, last] to the
// segment stream; returns its block ordinal, or -1 on error. Rolls to a
// fresh segment file when the block would overflow the current one.
int64_t oplog_seg_append(void* handle, const char* stream, int64_t first,
                         int64_t last, const void* data, int64_t len,
                         int64_t btype) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || log->readonly || !stream || !data || len <= 0 ||
        (uint64_t)len > 0xffffffffu)
        return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    if (!s) return -1;
    if (s->torn && !seg_untear(s)) return -1;
    if (s->cur_off > 0 && s->cur_off + (uint64_t)len > log->seg_bytes) {
        // roll: "wb+" truncates any stale bytes a crashed roll left behind
        fclose(s->data);
        s->cur_seg += 1;
        s->cur_off = 0;
        s->data = fopen(seg_path(log, stream, s->cur_seg).c_str(), "wb+");
        if (!s->data) {
            log->open_files -= 1;  // the closed tail; index stays open
            return -1;
        }
    }
    fseek(s->data, 0, SEEK_END);
    if (fwrite(data, 1, (size_t)len, s->data) != (size_t)len) {
        fflush(s->data);
        truncate_file(s->data, s->cur_off);
        return -1;
    }
    SegEntry e;
    e.first_seq = first;
    e.last_seq = last;
    e.seg = s->cur_seg;
    e.off = (uint32_t)s->cur_off;
    e.len = (uint32_t)len;
    e.btype = (uint32_t)btype;
    fseek(s->index, 0, SEEK_END);
    if (fwrite(&e, sizeof(e), 1, s->index) != 1) {
        fflush(s->data);
        truncate_file(s->data, s->cur_off);
        return -1;
    }
    s->entries.push_back(e);
    s->cur_off += (uint64_t)len;
    s->dirty = true;
    s->unsynced = true;
    return (int64_t)s->entries.size() - 1;
}

int64_t oplog_seg_count(void* handle, const char* stream) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !stream) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    return s ? (int64_t)s->entries.size() : -1;
}

// Read block `ordinal`; same contract as oplog_read (returns the needed
// size when buflen is too small; -1 on bad args / unknown block). Cold
// path — the hot read path is the Python-side mmap of the segment files.
int64_t oplog_seg_read(void* handle, const char* stream, int64_t ordinal,
                       void* buf, int64_t buflen) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !stream || ordinal < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    if (!s || (uint64_t)ordinal >= s->entries.size()) return -1;
    const SegEntry& e = s->entries[(size_t)ordinal];
    if ((int64_t)e.len > buflen) return (int64_t)e.len;
    if (s->data) fflush(s->data);
    FILE* f = fopen(seg_path(log, stream, e.seg).c_str(), "rb");
    if (!f) return -1;
    fseek(f, (long)e.off, SEEK_SET);
    bool ok = fread(buf, 1, e.len, f) == e.len;
    fclose(f);
    return ok ? (int64_t)e.len : -1;
}

// Block metadata for ordinal -> (first, last, seg, off, len, btype).
int oplog_seg_entry(void* handle, const char* stream, int64_t ordinal,
                    int64_t* first, int64_t* last, int64_t* seg, int64_t* off,
                    int64_t* len, int64_t* btype) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !stream || ordinal < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    if (!s || (uint64_t)ordinal >= s->entries.size()) return -1;
    const SegEntry& e = s->entries[(size_t)ordinal];
    if (first) *first = e.first_seq;
    if (last) *last = e.last_seq;
    if (seg) *seg = (int64_t)e.seg;
    if (off) *off = (int64_t)e.off;
    if (len) *len = (int64_t)e.len;
    if (btype) *btype = (int64_t)e.btype;
    return 0;
}

// Tail the stream for blocks appended by ANOTHER process; admits only
// complete entries whose block bytes fully landed (cf. oplog_refresh).
int64_t oplog_seg_refresh(void* handle, const char* stream) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !stream) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    if (!s) return -1;
    fseek(s->index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(s->index);
    size_t disk_n = (size_t)(index_bytes / sizeof(SegEntry));
    size_t have = s->entries.size();
    if (disk_n <= have) return (int64_t)have;
    fseek(s->index, (long)(have * sizeof(SegEntry)), SEEK_SET);
    SegEntry e;
    uint32_t sized_seg = 0;
    uint64_t sized_bytes = 0;
    bool sized = false;
    while (s->entries.size() < disk_n &&
           fread(&e, sizeof(e), 1, s->index) == 1) {
        if (!sized || e.seg != sized_seg) {
            sized_seg = e.seg;
            sized_bytes = seg_file_size(log, stream, e.seg);
            sized = true;
        }
        if ((uint64_t)e.off + e.len > sized_bytes) break;  // mid-write tail
        s->entries.push_back(e);
        s->cur_seg = e.seg;
        s->cur_off = (uint64_t)e.off + e.len;
    }
    return (int64_t)s->entries.size();
}

// Chaos-plane seam: leave a deliberately torn tail on disk, exactly the
// residue of a crash mid-append, WITHOUT admitting the block.
//   mode 0: half the block bytes land, no index entry (crash mid-block)
//   mode 1: all block bytes land, half an index entry (crash mid-index)
// The stream stays usable: the next append (or a reopen) runs the
// torn-tail scan and cuts the residue before writing.
int oplog_seg_tear(void* handle, const char* stream, int64_t first,
                   int64_t last, const void* data, int64_t len, int64_t btype,
                   int64_t mode) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || log->readonly || !stream || !data || len <= 0 ||
        (uint64_t)len > 0xffffffffu)
        return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    SegStream* s = get_seg(log, stream);
    if (!s) return -1;
    if (s->torn && !seg_untear(s)) return -1;
    if (s->cur_off > 0 && s->cur_off + (uint64_t)len > log->seg_bytes) {
        fclose(s->data);
        s->cur_seg += 1;
        s->cur_off = 0;
        s->data = fopen(seg_path(log, stream, s->cur_seg).c_str(), "wb+");
        if (!s->data) {
            log->open_files -= 1;  // the closed tail; index stays open
            return -1;
        }
    }
    size_t nbytes = mode == 0 ? (size_t)(len / 2 ? len / 2 : 1) : (size_t)len;
    fseek(s->data, 0, SEEK_END);
    if (fwrite(data, 1, nbytes, s->data) != nbytes) return -1;
    if (mode != 0) {
        SegEntry e;
        e.first_seq = first;
        e.last_seq = last;
        e.seg = s->cur_seg;
        e.off = (uint32_t)s->cur_off;
        e.len = (uint32_t)len;
        e.btype = (uint32_t)btype;
        fseek(s->index, 0, SEEK_END);
        if (fwrite(&e, 1, sizeof(e) / 2, s->index) != sizeof(e) / 2)
            return -1;
    }
    // flush so the residue is really on disk for a reopen to find
    fflush(s->data);
    fflush(s->index);
    s->torn = true;
    return 0;
}

// Append one record; returns its offset (record ordinal), or -1 on error.
int64_t oplog_append(void* handle, const char* topic, const void* data,
                     int64_t len) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic || (!data && len > 0) || len < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t) return -1;
    uint64_t record_start = t->data_end;
    uint32_t len32 = (uint32_t)len;
    fseek(t->data, 0, SEEK_END);
    bool ok = fwrite(&len32, sizeof(len32), 1, t->data) == 1 &&
              (len == 0 || fwrite(data, 1, (size_t)len, t->data) == (size_t)len);
    if (ok) {
        fseek(t->index, 0, SEEK_END);
        ok = fwrite(&record_start, sizeof(record_start), 1, t->index) == 1;
    }
    if (!ok) {
        // roll the data file back to the last valid extent, or the next
        // append would index a record that starts inside garbage bytes
        fflush(t->data);
        truncate_file(t->data, t->data_end);  // portable rollback
        fseek(t->data, 0, SEEK_END);
        return -1;
    }
    t->data_end = record_start + sizeof(len32) + (uint64_t)len;
    t->offsets.push_back(record_start);
    t->dirty = true;
    t->unsynced = true;
    return (int64_t)t->offsets.size() - 1;
}

int64_t oplog_length(void* handle, const char* topic) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    return t ? (int64_t)t->offsets.size() : -1;
}

// Read record `offset`; returns record length. If it exceeds buflen the
// buffer is untouched and the needed size is returned (call again).
// Returns -1 on bad args / unknown record.
int64_t oplog_read(void* handle, const char* topic, int64_t offset, void* buf,
                   int64_t buflen) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic || offset < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t || (uint64_t)offset >= t->offsets.size()) return -1;
    uint64_t start = t->offsets[(size_t)offset];
    uint32_t len = 0;
    fflush(t->data);
    fseek(t->data, (long)start, SEEK_SET);
    if (fread(&len, sizeof(len), 1, t->data) != 1) return -1;
    if ((int64_t)len > buflen) return (int64_t)len;
    if (len > 0 && fread(buf, 1, len, t->data) != len) return -1;
    return (int64_t)len;
}

// Push buffered appends into the OS page cache (fflush, no fsync) so a
// CONSUMER PROCESS sharing the directory can see them via oplog_refresh.
// The per-stage process composition (service/stage_runner.py) flushes at
// drain-batch boundaries: visibility, not durability — durability stays
// on oplog_sync at checkpoint boundaries.
int oplog_flush(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    for (auto& kv : log->topics) {
        if (!kv.second.dirty || !kv.second.data) continue;  // O(appended)
        fflush(kv.second.data);
        fflush(kv.second.index);
        kv.second.dirty = false;
    }
    for (auto& kv : log->segs) {
        if (!kv.second.dirty || !kv.second.index) continue;
        // block bytes before index entry: a reader that sees the entry
        // must find the bytes (mmap validation re-checks anyway)
        if (kv.second.data) fflush(kv.second.data);
        fflush(kv.second.index);
        kv.second.dirty = false;
    }
    return 0;
}

// Re-scan the on-disk index tail for records appended by ANOTHER process
// sharing this directory; returns the refreshed record count (or -1).
// Only COMPLETE records (index entry present AND the data extent covers
// the whole record) are admitted — a record mid-write by the producer
// stays invisible until its bytes land, so tailing never sees a torn
// record. Unlike recovery, nothing is truncated here.
int64_t oplog_refresh(void* handle, const char* topic) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || !topic) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    Topic* t = get_topic(log, topic);
    if (!t) return -1;
    fseek(t->index, 0, SEEK_END);
    uint64_t index_bytes = (uint64_t)ftell(t->index);
    size_t disk_n = (size_t)(index_bytes / sizeof(uint64_t));
    size_t have = t->offsets.size();
    if (disk_n <= have) return (int64_t)have;
    fseek(t->data, 0, SEEK_END);
    uint64_t data_bytes = (uint64_t)ftell(t->data);
    fseek(t->index, (long)(have * sizeof(uint64_t)), SEEK_SET);
    uint64_t off;
    uint64_t new_end = t->data_end;
    while (t->offsets.size() < disk_n &&
           fread(&off, sizeof(off), 1, t->index) == 1) {
        uint32_t len = 0;
        if (off + sizeof(len) > data_bytes) break;
        fseek(t->data, (long)off, SEEK_SET);
        if (fread(&len, sizeof(len), 1, t->data) != 1) break;
        if (off + sizeof(len) + len > data_bytes) break;
        t->offsets.push_back(off);
        new_end = off + sizeof(len) + (uint64_t)len;
    }
    if (new_end > t->data_end) t->data_end = new_end;
    return (int64_t)t->offsets.size();
}

// Make everything appended so far durable (fflush + fsync).
int oplog_sync(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    for (auto& kv : log->topics) {
        if (!kv.second.data) continue;  // evicted: covered below
        fflush(kv.second.data);
        fflush(kv.second.index);
#ifndef _WIN32
        fsync(fileno(kv.second.data));
        fsync(fileno(kv.second.index));
#endif
        kv.second.unsynced = false;
    }
    for (auto& kv : log->segs) {
        if (!kv.second.index) continue;  // evicted: covered below
        if (kv.second.data) fflush(kv.second.data);
        fflush(kv.second.index);
#ifndef _WIN32
        if (kv.second.data) fsync(fileno(kv.second.data));
        fsync(fileno(kv.second.index));
#endif
        kv.second.unsynced = false;
    }
    // files whose handles were LRU-evicted after un-fsync'd appends:
    // already in the page cache (eviction flushed), so a brief
    // open+fsync+close keeps the durability contract whole
    for (const std::string& path : log->evicted_unsynced) {
        FILE* f = fopen(path.c_str(), "rb");
        if (!f) continue;  // e.g. a rolled-away tail segment
#ifndef _WIN32
        fsync(fileno(f));
#endif
        fclose(f);
    }
    log->evicted_unsynced.clear();
    return 0;
}

// Cap on concurrently open FILE*s across this handle's topics and
// segment streams (0 = unlimited). Metadata stays resident; cold
// handles are flushed, closed, and reopened on demand.
int oplog_fd_cap(void* handle, int64_t cap) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log || cap < 0) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    log->fd_cap = (uint64_t)cap;
    evict_excess(log);
    return 0;
}

// Currently open FILE*s (introspection for tests and fd budgeting).
int64_t oplog_open_files(void* handle) {
    auto* log = static_cast<OpLog*>(handle);
    if (!log) return -1;
    std::lock_guard<std::mutex> lk(log->mu);
    return (int64_t)log->open_files;
}

}  // extern "C"
