"""table-doc: a table document composing THREE DDS types in one container.

Ref: examples/data-objects/table-document (src/document.ts) — the
reference's instructive composition: a SharedMatrix holds the cells
while sequence/map structures carry the surrounding document state, all
in one data store, all converging through the same total order.

Composition here:
- ``grid``    SharedMatrix — the cell values (row/col inserts survive
              concurrent edits via the permutation vectors);
- ``headers`` SharedMap — column labels keyed by column index;
- ``notes``   SharedString — free-text commentary under the table.

Run the full demo (server process + two editor processes editing the
SAME table concurrently, then both replicas' rendered tables printed):

    python -m examples.table_doc

Or by hand against a live front end:

    python -m fluidframework_tpu.service.front_end --port 8123 &
    python -m examples.table_doc --connect 8123 --name ana --script a
    python -m examples.table_doc --connect 8123 --name raj --script b
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.framework.data_object import (
    DataObject,
    DataObjectFactory,
)
from fluidframework_tpu.loader import Loader

DOC_ID = "table-doc-demo"


class TableDocument(DataObject):
    """A spreadsheet-shaped document: matrix cells + map headers +
    string notes, one data store."""

    def initializing_first_time(self) -> None:
        self.create_channel("grid", "shared-matrix")
        self.create_channel("headers", "shared-map")
        self.create_channel("notes", "shared-string")
        grid = self.grid
        grid.insert_rows(0, 3)
        grid.insert_cols(0, 3)

    @property
    def grid(self):
        return self.get_channel("grid")

    @property
    def headers(self):
        return self.get_channel("headers")

    @property
    def notes(self):
        return self.get_channel("notes")

    def render(self) -> str:
        grid = self.grid
        cols = grid.col_count
        labels = [str(self.headers.get(str(c)) or f"col{c}")
                  for c in range(cols)]
        widths = [max(len(labels[c]), 6) for c in range(cols)]
        lines = [" | ".join(l.ljust(w) for l, w in zip(labels, widths))]
        lines.append("-+-".join("-" * w for w in widths))
        for r in range(grid.row_count):
            cells = [str(grid.get_cell(r, c) if grid.get_cell(r, c)
                         is not None else "")
                     for c in range(cols)]
            lines.append(" | ".join(v.ljust(w)
                                    for v, w in zip(cells, widths)))
        return "\n".join(lines) + f"\nnotes: {self.notes.get_text()}"


FACTORY = DataObjectFactory("table-doc", TableDocument)


def wait_until(cond, timeout=90.0):  # 1-CPU host: full-suite contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


def open_document(port: int, creator: bool) -> tuple[object, TableDocument]:
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if not creator:
        wait_until(lambda: "default" in container.runtime.data_stores)
    return container, FACTORY.create_or_load(container)


# ------------------------------------------------------------- edit scripts

def script_a(doc: TableDocument) -> None:
    """Ana: labels the columns, fills the first data row, starts notes."""
    for c, label in enumerate(("region", "q1", "q2")):
        doc.headers.set(str(c), label)
    for c, v in enumerate(("north", 41, 37)):
        doc.grid.set_cell(0, c, v)
    doc.notes.insert_text(0, "Q1 dip explained by the launch slip. ")


def script_b(doc: TableDocument) -> None:
    """Raj: fills another row, inserts a TOTALS row concurrently with
    Ana's cell edits (the permutation vectors keep her writes anchored),
    and appends to the notes."""
    for c, v in enumerate(("south", 22, 58)):
        doc.grid.set_cell(1, c, v)
    doc.grid.insert_rows(doc.grid.row_count, 1)
    doc.grid.set_cell(doc.grid.row_count - 1, 0, "TOTAL")
    # wait until ana's WHOLE row landed before totalling — summing after
    # only part of it arrived would converge both replicas on a wrong
    # total (the wait must succeed, not time out)
    assert wait_until(lambda: all(
        doc.grid.get_cell(0, c) is not None for c in (1, 2)))
    for c in (1, 2):
        vals = [doc.grid.get_cell(r, c) for r in range(2)]
        doc.grid.set_cell(doc.grid.row_count - 1, c,
                          sum(v for v in vals if isinstance(v, int)))
    doc.notes.insert_text(len(doc.notes.get_text()),
                          "South beat forecast in Q2. ")


SCRIPTS = {"a": script_a, "b": script_b}


# --------------------------------------------------------------- processes

def run_editor(port: int, name: str, script: str) -> None:
    container, doc = open_document(port, creator=script == "a")
    if script == "a":
        print("READY", flush=True)
    if not wait_until(lambda: container.connected):
        raise SystemExit(f"{name}: never connected")
    SCRIPTS[script](doc)
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit(f"{name}: ops never acked")
    # converged = both scripts' sentinel edits visible
    wait_until(lambda: "launch slip" in doc.notes.get_text()
               and "forecast" in doc.notes.get_text()
               and doc.grid.get_cell(doc.grid.row_count - 1, 0) == "TOTAL")
    time.sleep(0.3)
    print(json.dumps({
        "name": name,
        "render": doc.render(),
        "rows": doc.grid.row_count,
        "cols": doc.grid.col_count,
        "notes": doc.notes.get_text(),
    }))


def run_clients(port: int) -> int:
    """Drive the two editors against an ALREADY-RUNNING service on
    ``port`` (any topology — the dev host owns the deployment shape)."""
    def spawn(name, s):
        return subprocess.Popen(
            [sys.executable, "-m", "examples.table_doc",
             "--connect", str(port), "--name", name, "--script", s],
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True)

    ana = spawn("ana", "a")
    assert ana.stdout.readline().strip() == "READY"
    editors = [ana, spawn("raj", "b")]
    results = []
    try:
        for p in editors:
            out, _ = p.communicate(timeout=220)
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in editors:  # a hung editor must not outlive the demo
            if p.poll() is None:
                p.kill()
    for r in results:
        print(f"--- {r['name']} ---")
        print(r["render"])
    a, b = results
    assert a["render"] == b["render"], "replicas diverged!"
    assert a["rows"] == 4 and a["cols"] == 3
    print("CONVERGED: both replicas render the same table")
    return 0


def run_demo() -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="table-doc demo")
    p.add_argument("--connect", type=int, default=None)
    p.add_argument("--name", default="editor")
    p.add_argument("--script", choices=sorted(SCRIPTS), default="a")
    args = p.parse_args()
    if args.connect is None:
        raise SystemExit(run_demo())
    run_editor(args.connect, args.name, args.script)


if __name__ == "__main__":
    main()
