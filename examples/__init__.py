"""Runnable example applications (the reference's examples/ role)."""
