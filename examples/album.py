"""album: a collaborative image collection over blobs + a shared map.

Ref: examples/data-objects/image-collection — the reference's image
collection data object keeps an ordered set of image components whose
payloads ride STORAGE (attachment blobs), not the op stream. Here the
same split: each photographer process uploads image bytes as
content-addressed attachment blobs (loader/blob_manager.py,
blobManager.ts role) and publishes only the handle + caption into a
``shared-map``; viewers resolve handles back to the exact bytes. The
convergence check proves every replica sees every entry AND that the
payloads round-trip bit-exact through the blob path — op-stream
convergence alone would not catch a storage-side corruption.

    python -m examples.album                    # demo: 3 photographers
    python -m examples.album --connect PORT [--create] --name N
"""

from __future__ import annotations

import argparse
import hashlib
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.loader.blob_manager import BlobHandle

DOC_ID = "album-demo"
PHOTOS_PER_CLIENT = 3


def wait_until(cond, timeout=90.0):  # 1-CPU host: contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def fake_image(name: str, i: int) -> bytes:
    """Deterministic pseudo-image payload (a few KB, binary)."""
    seed = f"{name}-{i}".encode()
    out = bytearray(b"\x89PNG\r\n\x1a\n")
    block = seed
    while len(out) < 4096:
        block = hashlib.sha256(block).digest()
        out.extend(block)
    return bytes(out)


def open_album(port: int, creator: bool):
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if creator:
        ds = container.runtime.create_data_store("default")
        album = ds.create_channel("album", "shared-map")
    else:
        if not wait_until(
                lambda: "default" in container.runtime.data_stores
                and "album" in container.runtime
                .get_data_store("default").channels):
            raise SystemExit("album never replicated")
        album = container.runtime.get_data_store(
            "default").get_channel("album")
    return container, album


def run_photographer(port: int, name: str, creator: bool) -> None:
    container, album = open_album(port, creator)
    if creator:
        print("READY", flush=True)
    wait_until(lambda: container.connected)
    for i in range(PHOTOS_PER_CLIENT):
        payload = fake_image(name, i)
        handle = container.blob_manager.create_blob(payload,
                                                    mime="image/png")
        album.set(f"{name}-{i}", {
            "caption": f"{name}'s photo {i}",
            "blob": handle.to_value(),
            "sha": hashlib.sha256(payload).hexdigest(),
        })
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit("album entries never acked")
    print(json.dumps({"name": name, "uploaded": PHOTOS_PER_CLIENT}))


def run_clients(port: int, n_procs: int = 3) -> int:
    """Drive the photographers against an ALREADY-RUNNING service on
    ``port`` (any topology — the dev host owns the deployment shape)."""
    def spawn(name, creator):
        args = [sys.executable, "-m", "examples.album",
                "--connect", str(port), "--name", name]
        if creator:
            args.append("--create")
        return subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True)

    names = ["ana", "bo", "chi", "dee"][:n_procs]
    first = spawn(names[0], True)
    assert first.stdout.readline().strip() == "READY"
    procs = [first] + [spawn(n, False) for n in names[1:]]
    try:
        for p in procs:
            p.communicate(timeout=220)
            if p.returncode != 0:
                print(f"photographer failed rc={p.returncode}")
                return 1
    finally:
        for p in procs:  # a hung photographer must not outlive the run
            if p.poll() is None:
                p.kill()

    # a fresh viewer: every entry present, every payload bit-exact
    container, album = open_album(port, creator=False)
    want = n_procs * PHOTOS_PER_CLIENT
    if not wait_until(lambda: len(list(album.keys())) >= want):
        print(f"DIVERGED: {len(list(album.keys()))} of {want} entries")
        return 1
    for key in sorted(album.keys()):
        entry = album.get(key)
        handle = BlobHandle.from_value(entry["blob"])
        payload = container.blob_manager.get_blob(handle)
        if hashlib.sha256(payload).hexdigest() != entry["sha"]:
            print(f"DIVERGED: blob {key} corrupt")
            return 1
        name, i = key.rsplit("-", 1)
        if payload != fake_image(name, int(i)):
            print(f"DIVERGED: blob {key} wrong content")
            return 1
    print(f"CONVERGED: {want} photos, all payloads bit-exact "
          f"through the blob path")
    return 0


def run_demo(n_procs: int = 3) -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port, n_procs)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="album demo")
    p.add_argument("--connect", type=int)
    p.add_argument("--name", default="solo")
    p.add_argument("--create", action="store_true")
    args = p.parse_args()
    if args.connect:
        run_photographer(args.connect, args.name, args.create)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
