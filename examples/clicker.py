"""clicker: the canonical SharedCounter example (BASELINE config 2).

Ref: examples/data-objects/clicker — the simplest real collaborative
app: a counter every client increments concurrently; commutative
increments mean no conflicts, just convergence. This version runs N
clicker PROCESSES hammering the same counter through the network driver
and proves the total.

    python -m examples.clicker                 # demo: 4 processes x 25 clicks
    python -m examples.clicker --connect PORT --clicks N   # one clicker
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader

DOC_ID = "clicker-demo"


def wait_until(cond, timeout=90.0):  # 1-CPU host: full-suite contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def open_counter(port: int, creator: bool):
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if creator:
        ds = container.runtime.create_data_store("default")
        counter = ds.create_channel("clicks", "shared-counter")
    else:
        if not wait_until(
                lambda: "default" in container.runtime.data_stores
                and "clicks" in container.runtime
                .get_data_store("default").channels):
            raise SystemExit("counter never replicated")
        counter = container.runtime.get_data_store("default") \
            .get_channel("clicks")
    return container, counter


def run_clicker(port: int, clicks: int, creator: bool) -> None:
    container, counter = open_counter(port, creator)
    if creator:
        print("READY", flush=True)
    wait_until(lambda: container.connected)
    for _ in range(clicks):
        counter.increment(1)
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit("clicks never acked")
    print(json.dumps({"clicked": clicks, "sees": counter.value}))


def run_clients(port: int, n_procs: int = 4, clicks: int = 25) -> int:
    """Drive N clicker processes against an ALREADY-RUNNING service on
    ``port`` (any topology — the dev host owns the deployment shape)."""
    def spawn(creator):
        args = [sys.executable, "-m", "examples.clicker",
                "--connect", str(port), "--clicks", str(clicks)]
        if creator:
            args.append("--create")
        return subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True)

    first = spawn(True)
    assert first.stdout.readline().strip() == "READY"
    procs = [first] + [spawn(False) for _ in range(n_procs - 1)]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=220)
            if p.returncode != 0:
                print(f"clicker failed rc={p.returncode}")
                return 1
    finally:
        for p in procs:  # a hung clicker must not outlive the run
            if p.poll() is None:
                p.kill()

    # an observer verifies the converged total
    _, counter = open_counter(port, creator=False)
    want = n_procs * clicks
    if not wait_until(lambda: counter.value == want):
        print(f"DIVERGED: {counter.value} != {want}")
        return 1
    print(f"CONVERGED: {n_procs} processes x {clicks} clicks "
          f"= {counter.value}")
    return 0


def run_demo(n_procs: int = 4, clicks: int = 25) -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port, n_procs, clicks)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="clicker demo")
    p.add_argument("--connect", type=int)
    p.add_argument("--clicks", type=int, default=25)
    p.add_argument("--create", action="store_true")
    args = p.parse_args()
    if args.connect:
        run_clicker(args.connect, args.clicks, args.create)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
