"""canvas: a collaborative ink surface over the Ink DDS.

Ref: examples/data-objects/canvas — the reference's drawing surface over
the Ink DDS (append-only stroke streams, dds/ink). N painter PROCESSES
draw concurrent strokes into one document; append-only semantics mean
strokes interleave but never conflict, and every replica converges to
the same stroke set and point counts.

    python -m examples.canvas                  # demo: 3 painters
    python -m examples.canvas --connect PORT [--create] --painter NAME
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader

DOC_ID = "canvas-demo"
POINTS_PER_STROKE = 16


def wait_until(cond, timeout=90.0):  # 1-CPU host: full-suite contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def open_canvas(port: int, creator: bool):
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if creator:
        ds = container.runtime.create_data_store("default")
        ink = ds.create_channel("ink", "ink")
    else:
        if not wait_until(
                lambda: "default" in container.runtime.data_stores
                and "ink" in container.runtime
                .get_data_store("default").channels):
            raise SystemExit("ink channel never replicated")
        ink = container.runtime.get_data_store("default").get_channel("ink")
    return container, ink


def run_painter(port: int, painter: str, strokes: int,
                creator: bool) -> None:
    container, ink = open_canvas(port, creator)
    if creator:
        print("READY", flush=True)
    wait_until(lambda: container.connected)
    for s in range(strokes):
        stroke_id = ink.create_stroke(
            pen={"color": painter, "thickness": 1 + s % 3})
        for i in range(POINTS_PER_STROKE):
            ink.append_point(stroke_id, x=float(i), y=float(s),
                             pressure=0.5)
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit("strokes never acked")
    print(json.dumps({"painter": painter, "strokes": strokes}))


def run_clients(port: int, n_procs: int = 3, strokes: int = 4) -> int:
    def spawn(painter, creator):
        args = [sys.executable, "-m", "examples.canvas",
                "--connect", str(port), "--painter", painter,
                "--strokes", str(strokes)]
        if creator:
            args.append("--create")
        return subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True)

    first = spawn("red", True)
    assert first.stdout.readline().strip() == "READY"
    names = ["red", "green", "blue", "violet"][:n_procs]
    procs = [first] + [spawn(n, False) for n in names[1:]]
    try:
        for p in procs:
            p.communicate(timeout=220)
            if p.returncode != 0:
                print(f"painter failed rc={p.returncode}")
                return 1
    finally:
        for p in procs:  # a hung painter must not outlive the run
            if p.poll() is None:
                p.kill()

    _, ink = open_canvas(port, creator=False)
    want = n_procs * strokes

    def converged():
        got = ink.get_strokes()
        return (len(got) == want
                and all(len(s["points"]) == POINTS_PER_STROKE
                        for s in got))
    if not wait_until(converged):
        got = ink.get_strokes()
        print(f"DIVERGED: {len(got)} strokes "
              f"{[len(s['points']) for s in got]}")
        return 1
    by_pen = {}
    for s in ink.get_strokes():
        by_pen[s["pen"]["color"]] = by_pen.get(s["pen"]["color"], 0) + 1
    print(f"CONVERGED: {want} strokes x {POINTS_PER_STROKE} points, "
          f"by painter {by_pen}")
    return 0


def run_demo(n_procs: int = 3, strokes: int = 4) -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port, n_procs, strokes)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="ink canvas demo")
    p.add_argument("--connect", type=int)
    p.add_argument("--painter", default="red")
    p.add_argument("--strokes", type=int, default=4)
    p.add_argument("--create", action="store_true")
    args = p.parse_args()
    if args.connect:
        run_painter(args.connect, args.painter, args.strokes, args.create)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
