"""todo: a collaborative task list over map + string channels.

Ref: examples/data-objects/todo/src/Todo.ts — the reference's todo data
object keeps an order-preserving collection of TodoItem components, each
pairing editable SharedString text with checkbox state. Here the same
shape: a ``shared-map`` holds item metadata (``done`` flags, creation
order), and every item's text is its own ``shared-string`` channel —
concurrent text edits merge character-wise while concurrent checks are
last-writer-wins, exercising BOTH merge disciplines in one app.

    python -m examples.todo                    # demo: 3 processes
    python -m examples.todo --connect PORT [--create] --actor NAME
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader

DOC_ID = "todo-demo"


def wait_until(cond, timeout=90.0):  # 1-CPU host: full-suite contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


class TodoApp:
    """The app facade over the container (the data-object role)."""

    def __init__(self, port: int, creator: bool):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        self.container = loader.resolve("demo", DOC_ID)
        if creator:
            ds = self.container.runtime.create_data_store("default")
            self.items = ds.create_channel("items", "shared-map")
        else:
            if not wait_until(
                    lambda: "default" in self.container.runtime.data_stores
                    and "items" in self.container.runtime
                    .get_data_store("default").channels):
                raise SystemExit("todo map never replicated")
            self.items = self.container.runtime.get_data_store(
                "default").get_channel("items")
        self.ds = self.container.runtime.get_data_store("default")

    def add_item(self, item_id: str, text: str) -> None:
        s = self.ds.create_channel(f"text-{item_id}", "shared-string")
        s.insert_text(0, text)
        self.items.set(item_id, {"done": False})

    def text_of(self, item_id: str):
        name = f"text-{item_id}"
        if name not in self.ds.channels:
            return None
        return self.ds.get_channel(name)

    def set_done(self, item_id: str, done: bool) -> None:
        meta = dict(self.items.get(item_id) or {})
        meta["done"] = done
        self.items.set(item_id, meta)

    def snapshot(self) -> dict:
        out = {}
        for item_id in sorted(self.items.keys()):
            s = self.text_of(item_id)
            out[item_id] = {
                "text": s.get_text() if s is not None else None,
                "done": (self.items.get(item_id) or {}).get("done"),
            }
        return out


def run_actor(port: int, actor: str, creator: bool) -> None:
    app = TodoApp(port, creator)
    if creator:
        print("READY", flush=True)
    wait_until(lambda: app.container.connected)
    # every actor adds two items, marks one of them done, and decorates
    # the shared first item's text (concurrent inserts on one string)
    app.add_item(f"{actor}-a", f"task {actor}-a")
    app.add_item(f"{actor}-b", f"task {actor}-b")
    app.set_done(f"{actor}-a", True)
    if creator:
        app.add_item("shared", "shared: ")
    else:
        if not wait_until(lambda: app.text_of("shared") is not None):
            raise SystemExit("shared item never replicated")
    shared = app.text_of("shared")
    if not wait_until(lambda: "shared: " in shared.get_text()):
        raise SystemExit("shared text never replicated")
    shared.insert_text(len(shared.get_text()), f"[{actor}]")
    if not wait_until(lambda: app.container.runtime.pending.count == 0):
        raise SystemExit("todo edits never acked")
    print(json.dumps({"actor": actor, "items": len(list(app.items.keys()))}))


def run_clients(port: int, n_procs: int = 3) -> int:
    """Drive the scenario against an already-running service on PORT
    (the dev-host seam: ``python -m fluidframework_tpu.host todo``)."""
    def spawn(actor, creator):
        args = [sys.executable, "-m", "examples.todo",
                "--connect", str(port), "--actor", actor]
        if creator:
            args.append("--create")
        return subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True)

    first = spawn("p0", True)
    assert first.stdout.readline().strip() == "READY"
    procs = [first] + [spawn(f"p{i}", False) for i in range(1, n_procs)]
    try:
        for p in procs:
            p.communicate(timeout=220)
            if p.returncode != 0:
                print(f"todo actor failed rc={p.returncode}")
                return 1
    finally:
        for p in procs:  # a hung/failed run must not orphan actors
            if p.poll() is None:
                p.kill()

    # an observer checks full convergence
    app = TodoApp(port, creator=False)
    want_items = 2 * n_procs + 1

    def settled():
        snap = app.snapshot()
        if len(snap) != want_items:
            return False
        shared = snap.get("shared", {}).get("text") or ""
        return all(f"[p{i}]" in shared for i in range(n_procs))
    if not wait_until(settled):
        print(f"DIVERGED: {json.dumps(app.snapshot(), indent=1)}")
        return 1
    snap = app.snapshot()
    done = sum(1 for v in snap.values() if v["done"])
    print(f"CONVERGED: {want_items} items, {done} done, "
          f"shared text {snap['shared']['text']!r}")
    return 0


def run_demo(n_procs: int = 3) -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port, n_procs)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="todo demo")
    p.add_argument("--connect", type=int)
    p.add_argument("--actor", default="p0")
    p.add_argument("--create", action="store_true")
    args = p.parse_args()
    if args.connect:
        run_actor(args.connect, args.actor, args.create)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
