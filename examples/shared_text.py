"""shared-text: the canonical collaborative-text example application.

Ref: examples/data-objects/shared-text (src/document.ts + component.ts)
— the reference's flagship SharedString app: rich text with markers,
bold/style annotations, and comment ranges that stay anchored as the
text changes around them.

This is the developer-surface proof: everything below uses only the
public framework API (DataObject + DDS channels) over the network
driver — the same stack an application author would ship.

Run the full demo (spawns a server process + two editor processes that
edit CONCURRENTLY, then prints both replicas' rendered documents):

    python -m examples.shared_text

Or the pieces by hand:

    python -m fluidframework_tpu.service.front_end --port 8123 &
    python -m examples.shared_text --connect 8123 --name alice --script a
    python -m examples.shared_text --connect 8123 --name bob   --script b
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.framework.data_object import (
    DataObject,
    DataObjectFactory,
)
from fluidframework_tpu.loader import Loader

DOC_ID = "shared-text-demo"
COMMENTS = "comments"


class SharedTextDocument(DataObject):
    """The shared-text data object: a title cell, the text body, and a
    comment interval collection anchored to the body."""

    def initializing_first_time(self) -> None:
        self.create_channel("title", "shared-cell")
        self.create_channel("body", "shared-string")
        self.get_channel("title").set("Untitled document")

    @property
    def title(self):
        return self.get_channel("title")

    @property
    def body(self):
        return self.get_channel("body")

    @property
    def comments(self):
        return self.body.get_interval_collection(COMMENTS)

    # ------------------------------------------------------------- render

    def render(self) -> str:
        """Plain-terminal rendering: **bold** runs, ¶ markers, and
        [comment: …] ranges resolved to live positions."""
        body = self.body
        text = body.get_text()
        # character-level style lookup via the merge-tree client
        marks = []
        for start, end in self._bold_runs(text):
            marks.append((start, "**"))
            marks.append((end, "**"))
        for ival in self.comments:
            s, e = self.comments.position(ival)
            label = (ival.properties or {}).get("text", "?")
            marks.append((s, "["))
            marks.append((e, f" ⟦{label}⟧]"))
        out = []
        last = 0
        for pos, tag in sorted(marks, key=lambda m: m[0]):
            out.append(text[last:pos])
            out.append(tag)
            last = pos
        out.append(text[last:])
        rendered = "".join(out)
        return f"# {self.title.get()}\n{rendered}"

    def _bold_runs(self, text: str) -> list[tuple[int, int]]:
        runs = []
        start = None
        for i in range(len(text)):
            props = self.body.client.get_properties_at(i)
            bold = bool(props.get("bold"))
            if bold and start is None:
                start = i
            elif not bold and start is not None:
                runs.append((start, i))
                start = None
        if start is not None:
            runs.append((start, len(text)))
        return runs


FACTORY = DataObjectFactory("shared-text", SharedTextDocument)


def open_document(port: int,
                  creator: bool = False) -> tuple[object, SharedTextDocument]:
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if not creator:
        # the default store's attach op travels through the total order;
        # a joiner waits for it instead of racing the creator
        wait_until(lambda: "default" in container.runtime.data_stores)
    doc = FACTORY.create_or_load(container)
    return container, doc


def wait_until(cond, timeout=90.0):  # 1-CPU host: full-suite contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


# ------------------------------------------------------------- edit scripts

def script_a(doc: SharedTextDocument) -> None:
    """Alice: writes the opening, titles the doc, bolds the greeting."""
    doc.title.set("Collaborative design notes")
    body = doc.body
    body.insert_text(0, "Welcome to the TPU fluid framework. ")
    body.annotate_range(0, 7, {"bold": True})
    body.insert_marker(len(body.get_text()), {"kind": "para"})
    body.insert_text(len(body.get_text()),
                     "The server only sequences; clients merge. ")


def script_b(doc: SharedTextDocument) -> None:
    """Bob: appends a section and leaves a comment on 'sequences' — the
    comment range keeps tracking the word as concurrent edits move it."""
    body = doc.body
    body.insert_text(len(body.get_text()),
                     "Summaries ride the same total order. ")
    # wait until alice's sentence shows up, then annotate a word of HERS
    wait_until(lambda: "sequences" in body.get_text())
    at = body.get_text().find("sequences")
    if at >= 0:
        doc.comments.add(at, at + len("sequences"),
                         {"text": "verify deli ordering claim"})


SCRIPTS = {"a": script_a, "b": script_b}


# --------------------------------------------------------------- processes

def run_editor(port: int, name: str, script: str) -> None:
    container, doc = open_document(port, creator=script == "a")
    if script == "a":
        # the orchestrator starts the second editor only after the doc
        # exists — concurrent first-creation is not part of this demo
        print("READY", flush=True)
    if not wait_until(lambda: container.connected):
        raise SystemExit(f"{name}: never connected")
    SCRIPTS[script](doc)
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit(f"{name}: ops never acked")
    # wait for the OTHER script's edits too, so the printed render is the
    # converged document (both scripts' sentinel text present)
    wait_until(lambda: "total order" in doc.body.get_text()
               and "clients merge" in doc.body.get_text())
    time.sleep(0.3)  # let the tail of remote ops drain
    print(json.dumps({"name": name, "render": doc.render(),
                      "text": doc.body.get_text()}))


def run_clients(port: int) -> int:
    """Drive the two editors against an ALREADY-RUNNING service on
    ``port`` (any topology — the dev host owns the deployment shape)."""
    def spawn(name, s):
        return subprocess.Popen(
            [sys.executable, "-m", "examples.shared_text",
             "--connect", str(port), "--name", name, "--script", s],
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True)

    alice = spawn("alice", "a")
    assert alice.stdout.readline().strip() == "READY"
    editors = [alice, spawn("bob", "b")]
    results = []
    try:
        for e in editors:
            out, _ = e.communicate(timeout=220)
            if e.returncode != 0:
                print(f"editor failed rc={e.returncode}")
                return 1
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for e in editors:  # a hung editor must not outlive the run
            if e.poll() is None:
                e.kill()
    texts = {r["text"] for r in results}
    print(f"\n=== {results[0]['name']}'s replica ===")
    print(results[0]["render"])
    print(f"\n=== {results[1]['name']}'s replica ===")
    print(results[1]["render"])
    if len(texts) == 1:
        print("\nCONVERGED: both replicas render identical documents")
        return 0
    print("\nDIVERGED!")
    return 1


def run_demo() -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="shared-text demo")
    p.add_argument("--connect", type=int, help="front-end port")
    p.add_argument("--name", default="editor")
    p.add_argument("--script", choices=sorted(SCRIPTS), default="a")
    args = p.parse_args()
    if args.connect:
        run_editor(args.connect, args.name, args.script)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
