"""sudoku: a collaborative puzzle grid over a shared map.

Ref: examples/data-objects/sudoku — the reference's sudoku data object
keys a SharedMap by "row,col" coordinate strings; every client writes
cell values into the same map and conflicting entries resolve
last-writer-wins. Here the same shape: three solver PROCESSES each fill
one band of a known solution concurrently, two of them deliberately
fight over one cell, and an observer proves every replica converged to
the identical board (including an identical winner for the contested
cell — LWW must pick the SAME writer everywhere).

    python -m examples.sudoku                   # demo: 3 solver processes
    python -m examples.sudoku --connect PORT [--create] --band K
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader

DOC_ID = "sudoku-demo"

# a solved 9x9 grid (rows); bands of 3 rows per solver
SOLUTION = [
    "534678912",
    "672195348",
    "198342567",
    "859761423",
    "426853791",
    "713924856",
    "961537284",
    "287419635",
    "345286179",
]
CONTESTED = "4,4"  # both solver 0 and solver 2 write this cell


def wait_until(cond, timeout=90.0):  # 1-CPU host: contention stretches acks
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def open_board(port: int, creator: bool):
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
    container = loader.resolve("demo", DOC_ID)
    if creator:
        ds = container.runtime.create_data_store("default")
        board = ds.create_channel("board", "shared-map")
    else:
        if not wait_until(
                lambda: "default" in container.runtime.data_stores
                and "board" in container.runtime
                .get_data_store("default").channels):
            raise SystemExit("board never replicated")
        board = container.runtime.get_data_store(
            "default").get_channel("board")
    return container, board


def run_solver(port: int, band: int, creator: bool) -> None:
    container, board = open_board(port, creator)
    if creator:
        print("READY", flush=True)
    wait_until(lambda: container.connected)
    # fill this solver's 3-row band cell by cell (the contested cell is
    # left to the two fighters — its band owner writing it too would
    # make the LWW winner depend on gateway/scheduling timing)
    for r in range(band * 3, band * 3 + 3):
        for c in range(9):
            if f"{r},{c}" != CONTESTED:
                board.set(f"{r},{c}", int(SOLUTION[r][c]))
    # solvers 0 and 2 both write the contested cell (different values):
    # LWW must converge to ONE of them identically on every replica
    if band in (0, 2):
        board.set(CONTESTED, 100 + band)
    # the done marker is set AFTER every write: map ops from one client
    # apply in submission order, so seeing done-K proves K's contested
    # write (if any) is visible too — the snapshot below is
    # deterministic, not a race with in-flight writes
    board.set(f"done-{band}", 1)
    if not wait_until(lambda: container.runtime.pending.count == 0):
        raise SystemExit("cell writes never acked")
    if not wait_until(lambda: all(
            board.get(f"done-{k}") for k in range(3))):
        raise SystemExit("peer solvers never finished")
    cells = {k: board.get(k) for k in board.keys() if "," in k}
    print(json.dumps({"band": band, "contested": board.get(CONTESTED),
                      "cells": len(cells),
                      "sum": sum(cells.values())}))


def run_clients(port: int) -> int:
    """Drive the three solvers against an ALREADY-RUNNING service on
    ``port`` (any topology — the dev host owns the deployment shape)."""
    def spawn(band, creator):
        args = [sys.executable, "-m", "examples.sudoku",
                "--connect", str(port), "--band", str(band)]
        if creator:
            args.append("--create")
        return subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True)

    first = spawn(0, True)
    assert first.stdout.readline().strip() == "READY"
    procs = [first, spawn(1, False), spawn(2, False)]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=220)
            if p.returncode != 0:
                print(f"solver failed rc={p.returncode}")
                return 1
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:  # a hung solver must not outlive the run
            if p.poll() is None:
                p.kill()

    # every replica saw the same contested winner and the same board sum
    winners = {r["contested"] for r in results}
    sums = {r["sum"] for r in results}
    if len(winners) != 1 or len(sums) != 1:
        print(f"DIVERGED: winners {winners} sums {sums}")
        return 1

    # an observer checks the final board against the solution
    _, board = open_board(port, creator=False)
    if not wait_until(lambda: all(
            board.get(f"done-{k}") for k in range(3))):
        print("DIVERGED: observer board incomplete")
        return 1
    wrong = [
        (r, c) for r in range(9) for c in range(9)
        if f"{r},{c}" != CONTESTED
        and board.get(f"{r},{c}") != int(SOLUTION[r][c])
    ]
    if wrong:
        print(f"DIVERGED: wrong cells {wrong[:5]}")
        return 1
    winner = board.get(CONTESTED)
    if winner not in (100, 102) or {winner} != winners:
        print(f"DIVERGED: contested cell {winner} vs replicas {winners}")
        return 1
    print(f"CONVERGED: 81 cells, contested cell won by solver "
          f"{winner - 100}")
    return 0


def run_demo() -> int:
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = server.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        return run_clients(port)
    finally:
        server.terminate()
        server.wait(timeout=10)


def main() -> None:
    p = argparse.ArgumentParser(description="sudoku demo")
    p.add_argument("--connect", type=int)
    p.add_argument("--band", type=int, default=0)
    p.add_argument("--create", action="store_true")
    args = p.parse_args()
    if args.connect:
        run_solver(args.connect, args.band, args.create)
    else:
        raise SystemExit(run_demo())


if __name__ == "__main__":
    main()
