"""Intelligence runner: the canonical attached agent.

Ref: packages/agents/intelligence-runner-agent (+ the clicker/shared-text
intel agents, server/headless-agent) — an agent attaches to a document,
wins the "intel" task through the agent scheduler, and continuously
publishes derived analytics (text statistics, translations, …) back INTO
the document as shared state, so every client sees the analysis converge
through the same total order as the data.
"""

from __future__ import annotations

from .agent_scheduler import AgentScheduler

INTEL_TASK = "intel"
INTEL_CHANNEL = "intel-results"


class IntelRunner:
    """Maintains a shared-map of text statistics for one shared-string.

    Exactly one runner per document does the work (scheduler-elected);
    the rest stay hot standbys and take over on departure.
    """

    def __init__(self, container, ds_id: str = "default",
                 text_channel: str = "text"):
        self.container = container
        self._ds = container.runtime.get_data_store(ds_id)
        self._text = self._ds.get_channel(text_channel)
        if INTEL_CHANNEL in self._ds.channels:
            self.results = self._ds.get_channel(INTEL_CHANNEL)
        else:
            self.results = self._ds.create_channel(INTEL_CHANNEL,
                                                   "shared-map")
        self.scheduler = AgentScheduler(container, ds_id)
        self.runs = 0
        self.scheduler.pick(INTEL_TASK, self._on_ownership)
        self._text.on("sequenceDelta", self._on_delta)
        if self.scheduler.owns(INTEL_TASK):
            self._analyze()

    @property
    def is_running(self) -> bool:
        return self.scheduler.owns(INTEL_TASK)

    def _on_ownership(self, owned: bool) -> None:
        if owned:
            self._analyze()

    def _on_delta(self, *args) -> None:
        if self.is_running:
            self._analyze()

    def _analyze(self) -> None:
        text = self._text.get_text()
        words = [w for w in text.split() if w]
        self.results.set("chars", len(text))
        self.results.set("words", len(words))
        self.results.set("longest_word",
                         max(words, key=len) if words else "")
        self.results.set("analyzed_by", self.container.client_id)
        self.runs += 1
