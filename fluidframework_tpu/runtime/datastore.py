"""DataStoreRuntime: a named collection of channels (DDS instances).

Ref: runtime/datastore/src/dataStoreRuntime.ts:81 — routes channel ops to
channel contexts (:462,718); channel creation travels as a chanattach op
with the channel's snapshot (localChannelContext → attach). The channel
talks back through a ChannelDeltaConnection adapter
(channelDeltaConnection.ts:10), here a bound submit closure.

Inner envelope format (contents of a "chanop" runtime envelope):

- {"address": channel_id, "contents": wire_op}                channel op
- {"address": channel_id, "attach": {"type", "snapshot"}}     channel attach
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..dds.registry import create_channel, load_channel
from ..protocol.messages import SequencedDocumentMessage


class DataStoreRuntime:
    def __init__(self, runtime, ds_id: str, pkg: str = "default"):
        self.runtime = runtime
        self.id = ds_id
        self.pkg = pkg
        self.channels: dict[str, object] = {}

    # ------------------------------------------------------------ channels

    def create_channel(self, channel_id: str, channel_type: str):
        """Create a channel locally and announce it (attach op)."""
        if channel_id in self.channels:
            raise KeyError(f"channel {channel_id} exists")
        channel = create_channel(channel_type, channel_id)
        self._connect_channel(channel)
        self.channels[channel_id] = channel
        self.runtime.submit_channel_op(
            self.id,
            {
                "address": channel_id,
                "attach": {"type": channel_type, "snapshot": channel.snapshot()},
            },
        )
        return channel

    def get_channel(self, channel_id: str):
        return self.channels[channel_id]

    def _connect_channel(self, channel) -> None:
        channel._bind(
            submit=lambda contents: self.runtime.submit_channel_op(
                self.id, {"address": channel.id, "contents": contents}
            ),
            is_connected=lambda: self.runtime.connected,
        )
        if self.runtime.connected:
            channel.set_connection_state(True, self.runtime.client_id)

    # ------------------------------------------------------------- op flow

    def process(self, msg: SequencedDocumentMessage, local: bool) -> None:
        inner = msg.contents
        channel_id = inner["address"]
        if "attach" in inner:
            if channel_id not in self.channels:
                attach = inner["attach"]
                channel = load_channel(attach["type"], channel_id, attach["snapshot"])
                self._connect_channel(channel)
                self.channels[channel_id] = channel
            return
        channel = self.channels.get(channel_id)
        if channel is None:
            raise KeyError(f"op for unknown channel {channel_id} in store {self.id}")
        channel.process(replace(msg, contents=inner["contents"]), local)

    def resubmit_channel(self, channel_id: str) -> None:
        self.channels[channel_id].resubmit_pending()

    def set_connection_state(self, connected: bool, client_id: Optional[str]) -> None:
        for channel in self.channels.values():
            channel.set_connection_state(connected, client_id)

    def on_member_removed(self, client_id: str) -> None:
        for channel in self.channels.values():
            handler = getattr(channel, "on_member_removed", None)
            if handler:
                handler(client_id)

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        return {
            "channels": {
                cid: {"type": ch.channel_type, "snapshot": ch.snapshot()}
                for cid, ch in self.channels.items()
            }
        }

    def load_snapshot(self, snap: dict) -> None:
        for cid, entry in snap.get("channels", {}).items():
            channel = load_channel(entry["type"], cid, entry["snapshot"])
            self._connect_channel(channel)
            self.channels[cid] = channel
