"""DataStoreRuntime: a named collection of channels (DDS instances).

Ref: runtime/datastore/src/dataStoreRuntime.ts:81 — routes channel ops to
channel contexts (:462,718); channel creation travels as a chanattach op
with the channel's snapshot (localChannelContext → attach). The channel
talks back through a ChannelDeltaConnection adapter
(channelDeltaConnection.ts:10), here a bound submit closure.

Inner envelope format (contents of a "chanop" runtime envelope):

- {"address": channel_id, "contents": wire_op}                channel op
- {"address": channel_id, "attach": {"type", "snapshot"}}     channel attach
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..dds.registry import create_channel, load_channel
from ..protocol.messages import SequencedDocumentMessage


class DataStoreRuntime:
    def __init__(self, runtime, ds_id: str, pkg: str = "default"):
        self.runtime = runtime
        self.id = ds_id
        self.pkg = pkg
        self.channels: dict[str, object] = {}

    # ------------------------------------------------------------ channels

    def create_channel(self, channel_id: str, channel_type: str):
        """Create a channel locally and announce it (attach op)."""
        if channel_id in self.channels:
            raise KeyError(f"channel {channel_id} exists")
        channel = create_channel(channel_type, channel_id)
        self._connect_channel(channel)
        self.channels[channel_id] = channel
        self.runtime.submit_channel_op(
            self.id,
            {
                "address": channel_id,
                "attach": {"type": channel_type, "snapshot": channel.snapshot()},
            },
        )
        return channel

    def get_channel(self, channel_id: str):
        return self.channels[channel_id]

    def _connect_channel(self, channel) -> None:
        channel._bind(
            submit=lambda contents: self.runtime.submit_channel_op(
                self.id, {"address": channel.id, "contents": contents}
            ),
            is_connected=lambda: self.runtime.connected,
        )
        # stream-head accessor for channels whose state changes without
        # ops (shared-summary-block dirty tracking)
        channel._head_fn = (
            lambda: self.runtime.container.delta_manager.last_processed_seq)
        if self.runtime.connected:
            channel.set_connection_state(True, self.runtime.client_id)

    # ------------------------------------------------------------- op flow

    def process(self, msg: SequencedDocumentMessage, local: bool) -> None:
        inner = msg.contents
        channel_id = inner["address"]
        if "attach" in inner:
            if channel_id not in self.channels:
                attach = inner["attach"]
                channel = load_channel(attach["type"], channel_id, attach["snapshot"])
                self._connect_channel(channel)
                self.channels[channel_id] = channel
            # stamp on the creator too (the skip branch): a channel born
            # after the parent summary must never summarize as a handle
            self.channels[channel_id].last_changed_seq = msg.sequence_number
            return
        channel = self.channels.get(channel_id)
        if channel is None:
            raise KeyError(f"op for unknown channel {channel_id} in store {self.id}")
        channel.process(replace(msg, contents=inner["contents"]), local)

    def resubmit_channel(self, channel_id: str) -> None:
        self.channels[channel_id].resubmit_pending()

    def set_connection_state(self, connected: bool, client_id: Optional[str]) -> None:
        for channel in self.channels.values():
            channel.set_connection_state(connected, client_id)

    def on_member_removed(self, client_id: str, seq: int = 0) -> None:
        for channel in self.channels.values():
            handler = getattr(channel, "on_member_removed", None)
            if handler:
                # a sequenced leave can mutate the channel (consensus
                # collections requeue the leaver's holdings) — it must
                # disqualify handle reuse like any other sequenced change
                channel.last_changed_seq = max(channel.last_changed_seq, seq)
                handler(client_id)

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        return {
            "channels": {
                cid: {"type": ch.channel_type, "snapshot": ch.snapshot()}
                for cid, ch in self.channels.items()
            }
        }

    def summarize(self, path: str, parent_capture_seq=None):
        """Summary subtree mirroring ``snapshot()``'s dict shape, with
        per-channel handle reuse (ref: FluidDataStoreRuntime summarize →
        channel contexts)."""
        import json as _json

        from ..protocol.summary import SummaryBlob, SummaryTree

        return SummaryTree(tree={
            "pkg": SummaryBlob(_json.dumps(self.pkg).encode()),
            "snapshot": SummaryTree(tree={
                "channels": SummaryTree(tree={
                    cid: ch.summarize(
                        f"{path}/snapshot/channels/{cid}", parent_capture_seq)
                    for cid, ch in self.channels.items()
                })
            }),
        })

    def load_snapshot(self, snap: dict, base_seq: int = 0) -> None:
        for cid, entry in snap.get("channels", {}).items():
            channel = load_channel(entry["type"], cid, entry["snapshot"])
            self._connect_channel(channel)
            # the boot summary captured this channel at base_seq: that is
            # its change floor, and (being > 0 for any real summary) it
            # keeps never-touched channels ELIGIBLE for handle reuse
            channel.last_changed_seq = base_seq
            self.channels[cid] = channel
