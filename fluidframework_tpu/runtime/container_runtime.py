"""ContainerRuntime: op multiplexer + pending-state replay.

Ref: runtime/container-runtime/src/containerRuntime.ts — process (:1094)
routes envelopes to data stores; submit batches local ops; the
PendingStateManager (pendingStateManager.ts:69) records every local
submission and replays it through ``reSubmit`` after reconnect (:301 →
SharedObject.reSubmit, sharedObject.ts:398). Data-store creation travels
as an attach op carrying the store's initial snapshot (:1451).

Envelope format on the wire (contents of a MessageType.OPERATION):

- {"kind": "attach", "id", "pkg", "snapshot"}            create data store
- {"kind": "chanop", "address", "contents": {
       "address": channel_id, "contents": dds_wire_op}}  channel op
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, replace
from enum import Enum
from typing import Any, Optional

from ..protocol.messages import MessageType, SequencedDocumentMessage
from .datastore import DataStoreRuntime


class FlushMode(Enum):
    """Ref: containerRuntime.ts FlushMode — IMMEDIATE sends every op as
    its own submission; TURN_BASED coalesces until flush()."""

    IMMEDIATE = 0
    TURN_BASED = 1


@dataclass
class PendingEntry:
    client_seq: int
    envelope: dict


class PendingStateManager:
    """Local ops awaiting server ack; the replay source after reconnect.

    Ref: pendingStateManager.ts:69 — entries are appended on submit,
    matched FIFO against our own sequenced messages (the server preserves
    per-client FIFO), and replayed through the runtime on reconnect (:301).
    """

    def __init__(self):
        self._pending: list[PendingEntry] = []

    def record_entry(self, entry: PendingEntry) -> None:
        self._pending.append(entry)

    def ack(self, msg: SequencedDocumentMessage) -> Optional[PendingEntry]:
        if not self._pending:
            raise RuntimeError(
                f"own op {msg.client_sequence_number} sequenced with no pending state"
            )
        head = self._pending.pop(0)
        return head

    def take_all(self) -> list[PendingEntry]:
        pending, self._pending = self._pending, []
        return pending

    @property
    def count(self) -> int:
        return len(self._pending)


class ContainerRuntime:
    def __init__(self, container):
        self.container = container
        self.data_stores: dict[str, DataStoreRuntime] = {}
        self.pending = PendingStateManager()
        self.connected = False
        self.client_id: Optional[str] = None
        # op batching (ref: containerRuntime.ts:1207-1271 FlushMode +
        # orderSequentially): entries held here are already recorded as
        # pending; flush() ships them as ONE batch submission
        self.flush_mode = FlushMode.IMMEDIATE
        self._batch: list[PendingEntry] = []
        self._order_depth = 0

    # --------------------------------------------------------- data stores

    def create_data_store(self, ds_id: str, pkg: str = "default") -> DataStoreRuntime:
        """Create locally and announce via an attach op carrying the
        initial snapshot (ref: containerRuntime.ts:1451 attach flow)."""
        if ds_id in self.data_stores:
            raise KeyError(f"data store {ds_id} exists")
        ds = DataStoreRuntime(self, ds_id, pkg)
        self.data_stores[ds_id] = ds
        self._submit({"kind": "attach", "id": ds_id, "pkg": pkg,
                      "snapshot": ds.snapshot()})
        return ds

    def get_data_store(self, ds_id: str) -> DataStoreRuntime:
        return self.data_stores[ds_id]

    # ------------------------------------------------------------- op flow

    def process(self, msg: SequencedDocumentMessage, local: bool) -> None:
        envelope = msg.contents
        if local:
            self.pending.ack(msg)
        kind = envelope.get("kind")
        if kind == "attach":
            if envelope["id"] not in self.data_stores:
                ds = DataStoreRuntime(self, envelope["id"], envelope["pkg"])
                ds.load_snapshot(envelope["snapshot"])
                self.data_stores[envelope["id"]] = ds
            return
        if kind == "chanop":
            ds = self.data_stores.get(envelope["address"])
            if ds is None:
                raise KeyError(f"op for unknown data store {envelope['address']}")
            inner = replace(msg, contents=envelope["contents"])
            ds.process(inner, local)
            return
        raise ValueError(f"unknown envelope kind {kind!r}")

    def submit_channel_op(self, ds_id: str, contents: dict) -> None:
        self._submit({"kind": "chanop", "address": ds_id, "contents": contents})

    def _submit(self, envelope: dict) -> None:
        """Record locally; send only while connected. Disconnected
        submissions replay on the next connect (the reference queues at the
        DeltaManager + replays via PendingStateManager; state here lives in
        one place). Recording MUST precede the send: with a synchronous
        in-proc service the ack can arrive inside the submit call."""
        if getattr(self.container, "readonly", False):
            # the DDS already applied the edit optimistically; a replica
            # holding a mutation that can never be submitted is corrupt,
            # so close it (the reference's readonly assert likewise kills
            # the container) — apps must gate editing on container.readonly
            self.container.close()
            raise PermissionError(
                "container is readonly: local edits are disabled")
        entry = PendingEntry(-1, envelope)
        self.pending.record_entry(entry)
        if not self.connected:
            return
        if self.flush_mode is FlushMode.TURN_BASED or self._order_depth:
            self._batch.append(entry)
        else:
            entry.client_seq = self.container.delta_manager.submit(
                MessageType.OPERATION, envelope
            )

    # ----------------------------------------------------------- batching

    def set_flush_mode(self, mode: FlushMode) -> None:
        if mode is FlushMode.IMMEDIATE:
            self.flush()  # pending batch must not straddle the switch
        self.flush_mode = mode

    def flush(self) -> None:
        """Ship the accumulated batch as one contiguous submission — one
        boxcar on the raw log, sequenced without interleaving."""
        if self._order_depth:
            return  # orderSequentially flushes at its own close
        batch, self._batch = self._batch, []
        if not batch:
            return
        seqs = self.container.delta_manager.submit_batch(
            MessageType.OPERATION, [e.envelope for e in batch])
        for entry, seq in zip(batch, seqs):
            entry.client_seq = seq

    @contextlib.contextmanager
    def order_sequentially(self):
        """Everything submitted inside runs as ONE atomic batch (ref:
        orderSequentially containerRuntime.ts:1207). An exception closes
        the container — partially-applied optimistic local state cannot
        be rolled back, so the replica must not keep talking."""
        self._order_depth += 1
        try:
            yield
        except BaseException:
            self._order_depth -= 1
            self._batch.clear()
            self.container.close()
            raise
        self._order_depth -= 1
        if self._order_depth == 0 and self.flush_mode is FlushMode.IMMEDIATE:
            self.flush()

    def on_member_removed(self, client_id: str, seq: int = 0) -> None:
        for ds in self.data_stores.values():
            ds.on_member_removed(client_id, seq)

    # ----------------------------------------------------------- reconnect

    def set_connection_state(self, connected: bool, client_id: Optional[str]) -> None:
        self.connected = connected
        if connected:
            old_client_id, self.client_id = self.client_id, client_id
            for ds in self.data_stores.values():
                ds.set_connection_state(connected, client_id)
            self._replay_pending()
        else:
            self.client_id = None
            # unflushed batch entries were never sent; they stay recorded
            # as pending and regenerate through the reconnect replay
            self._batch.clear()
            for ds in self.data_stores.values():
                ds.set_connection_state(connected, None)

    def _replay_pending(self) -> None:
        """Rebase + resubmit everything unacked (ref: replayPendingStates
        pendingStateManager.ts:301).

        Channel ops route to the channel's ``resubmit`` so the DDS can
        regenerate against current state (merge-tree rebases positions);
        attach ops resubmit verbatim. Each resubmission re-records itself
        via the normal submit path.
        """
        regenerated: set[tuple[str, str]] = set()
        for entry in self.pending.take_all():
            env = entry.envelope
            if env["kind"] == "attach" or "attach" in env.get("contents", {}):
                # data-store and channel attach ops resubmit verbatim: the
                # original (empty-state) snapshot plus the regenerated
                # content ops that follow rebuild remote replicas exactly
                self._submit(env)
            elif env["kind"] == "chanop":
                key = (env["address"], env["contents"]["address"])
                if key in regenerated:
                    continue  # this channel already regenerated all pending
                regenerated.add(key)
                ds = self.data_stores[env["address"]]
                ds.resubmit_channel(env["contents"]["address"])

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        return {
            "dataStores": {
                ds_id: {"pkg": ds.pkg, "snapshot": ds.snapshot()}
                for ds_id, ds in self.data_stores.items()
            }
        }

    def summarize(self, parent_capture_seq=None):
        """Recursive SummaryTree over stores → channels with handle reuse
        (ref: ContainerRuntime.summarize containerRuntime.ts:1424). The
        tree materializes back into exactly ``snapshot()``'s dict shape,
        so boot needs no incremental-aware path."""
        from ..protocol.summary import SummaryTree

        return SummaryTree(tree={
            "dataStores": SummaryTree(tree={
                ds_id: ds.summarize(
                    f"runtime/dataStores/{ds_id}", parent_capture_seq)
                for ds_id, ds in self.data_stores.items()
            })
        })

    def load_snapshot(self, snap: dict, base_seq: int = 0) -> None:
        for ds_id, entry in snap.get("dataStores", {}).items():
            ds = DataStoreRuntime(self, ds_id, entry["pkg"])
            ds.load_snapshot(entry["snapshot"], base_seq)
            self.data_stores[ds_id] = ds
