"""Runtime layer: routes the op stream into data stores and channels.

Ref: packages/runtime (SURVEY §2.3) — ContainerRuntime multiplexes ops to
data stores and owns pending-op replay on reconnect; each data store hosts
named channels (the DDS instances); channels talk back through a delta
connection adapter.
"""

from .container_runtime import ContainerRuntime, PendingStateManager
from .datastore import DataStoreRuntime

__all__ = ["ContainerRuntime", "PendingStateManager", "DataStoreRuntime"]
