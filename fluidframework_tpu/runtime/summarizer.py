"""Client-side summarizer: election + attempt heuristics + ack tracking.

Ref: runtime/container-runtime summarizer subsystem — SummaryManager
elects the summarizer from the OLDEST quorum member (summaryManager.ts:
139,269); RunningSummarizer drives attempts off ops-since-last-ack
heuristics (summarizer.ts:232,403); SummaryCollection correlates the
broadcast summarize op with its ack/nack (summaryCollection.ts).

Differences from the reference, by design: the reference spawns a hidden
"/_summarizer" container so the summarizing replica never holds pending
local ops; here the elected client summarizes in-process and simply
defers while it has unacked ops (same invariant — summaries capture only
acked state — without the second container).
"""

from __future__ import annotations

from typing import Optional

from ..protocol.messages import MessageType, SequencedDocumentMessage

DEFAULT_MAX_OPS = 100  # ops since last acked summary that trigger an attempt


class SummaryManager:
    """Attach one per container (`SummaryManager(container)`); it watches
    the quorum, self-elects when oldest, and summarizes on the heuristics.
    """

    def __init__(
        self,
        container,
        max_ops: int = DEFAULT_MAX_OPS,
    ):
        self.container = container
        self.max_ops = max_ops
        self.last_acked_handle: Optional[str] = None
        self._pending_handle: Optional[str] = None
        self._ops_since_ack = 0
        self.summaries_acked = 0
        self.summaries_nacked = 0
        # seed the head from storage: a manager attached after boot missed
        # the SUMMARY_ACKs already in the op tail, and proposing
        # parent=None against an existing chain would nack-loop forever
        versions = container.storage.get_versions(1)
        if versions:
            self.last_acked_handle = versions[0]["id"]
        container.add_message_observer(self._observe)

    # ------------------------------------------------------------ election

    @property
    def elected_summarizer(self) -> Optional[str]:
        """Oldest quorum member = lowest join sequence number
        (ref: summaryManager electing via quorum join order)."""
        members = self.container.quorum.members
        if not members:
            return None
        return min(members.items(), key=lambda kv: kv[1].sequence_number)[0]

    @property
    def is_summarizer(self) -> bool:
        return (
            self.container.client_id is not None
            and self.elected_summarizer == self.container.client_id
        )

    # ------------------------------------------------------------ observer

    def _observe(self, msg: SequencedDocumentMessage) -> None:
        if msg.type == MessageType.SUMMARY_ACK:
            handle = (msg.contents or {}).get("handle")
            self.last_acked_handle = handle
            self._ops_since_ack = 0
            if handle == self._pending_handle:
                self._pending_handle = None
                self.summaries_acked += 1
            return
        if msg.type == MessageType.SUMMARY_NACK:
            # correlate by handle: another client's nack must not clear
            # OUR in-flight attempt
            if (msg.contents or {}).get("handle") == self._pending_handle \
                    and self._pending_handle is not None:
                self._pending_handle = None
                self.summaries_nacked += 1
            return
        if msg.type == MessageType.OPERATION:
            self._ops_since_ack += 1
            self._maybe_summarize()

    def _maybe_summarize(self) -> None:
        if (
            self._ops_since_ack < self.max_ops
            or not self.is_summarizer
            or self._pending_handle is not None
            or not self.container.connected
            # only acked state may be summarized (the reference gets this
            # invariant from the hidden summarizer container)
            or self.container.runtime.pending.count > 0
        ):
            return
        self.summarize_now()

    # ------------------------------------------------------------- attempt

    def summarize_now(self) -> Optional[str]:
        """Generate, upload, and propose a summary (ref:
        ContainerRuntime.generateSummary containerRuntime.ts:1631 +
        summarize op submission §3.4)."""
        if self.container.runtime.pending.count > 0:
            raise RuntimeError("cannot summarize with pending local ops")
        summary = {
            "protocol": self.container.protocol.snapshot(),
            "runtime": self.container.runtime.snapshot(),
            "sequence_number": self.container.delta_manager.last_processed_seq,
        }
        handle = self.container.storage.upload_summary(
            summary, parent=self.last_acked_handle)
        self._pending_handle = handle
        self.container.delta_manager.submit(
            MessageType.SUMMARIZE,
            {"handle": handle, "parent": self.last_acked_handle,
             "head": summary["sequence_number"]},
        )
        return handle
