"""Client-side summarizer: election + attempt heuristics + ack tracking.

Ref: runtime/container-runtime summarizer subsystem — SummaryManager
elects the summarizer from the OLDEST quorum member (summaryManager.ts:
139,269); RunningSummarizer drives attempts off ops-since-last-ack
heuristics (summarizer.ts:232,403); SummaryCollection correlates the
broadcast summarize op with its ack/nack (summaryCollection.ts).

Differences from the reference, by design: the reference spawns a hidden
"/_summarizer" container so the summarizing replica never holds pending
local ops; here the elected client summarizes in-process and simply
defers while it has unacked ops (same invariant — summaries capture only
acked state — without the second container).
"""

from __future__ import annotations

from typing import Optional

from ..config import DEFAULT as _CFG
from ..protocol.messages import MessageType, SequencedDocumentMessage

# ops since last acked summary that trigger an attempt (config registry)
DEFAULT_MAX_OPS = _CFG.summary_max_ops


class SummaryManager:
    """Attach one per container (`SummaryManager(container)`); it watches
    the quorum, self-elects when oldest, and summarizes on the heuristics.
    """

    def __init__(
        self,
        container,
        max_ops: Optional[int] = None,
    ):
        max_ops = max_ops if max_ops is not None else _CFG.summary_max_ops
        self.container = container
        self.max_ops = max_ops
        self.last_acked_handle: Optional[str] = None
        # capture seq of the last ACKED summary — the threshold for
        # per-channel handle reuse. Learned from broadcast SUMMARIZE ops
        # (anyone's), correlated on ack; None (e.g. storage-seeded head
        # whose proposal predates us) forces a full upload.
        self.last_acked_capture_seq: Optional[int] = None
        self._proposal_heads: dict[str, int] = {}  # handle → capture seq
        self._pending_handle: Optional[str] = None
        self._ops_since_ack = 0
        self._nack_retries = 0
        self.summaries_acked = 0
        self.summaries_nacked = 0
        # seed the head from storage: a manager attached after boot missed
        # the SUMMARY_ACKs already in the op tail, and proposing
        # parent=None against an existing chain would nack-loop forever
        versions = container.storage.get_versions(1)
        if versions:
            self.last_acked_handle = versions[0]["id"]
        container.add_message_observer(self._observe)

    # ------------------------------------------------------------ election

    @property
    def elected_summarizer(self) -> Optional[str]:
        """Oldest quorum member = lowest join sequence number
        (ref: summaryManager electing via quorum join order)."""
        members = self.container.quorum.members
        if not members:
            return None
        return min(members.items(), key=lambda kv: kv[1].sequence_number)[0]

    @property
    def is_summarizer(self) -> bool:
        return (
            self.container.client_id is not None
            and self.elected_summarizer == self.container.client_id
        )

    # ------------------------------------------------------------ observer

    def _observe(self, msg: SequencedDocumentMessage) -> None:
        if msg.type == MessageType.SUMMARIZE:
            # remember every proposal's capture seq so an eventual ack
            # (ours or another client's) sets the handle-reuse threshold
            c = msg.contents or {}
            if c.get("handle") is not None and c.get("head") is not None:
                self._proposal_heads[c["handle"]] = c["head"]
            return
        if msg.type == MessageType.SUMMARY_ACK:
            handle = (msg.contents or {}).get("handle")
            self.last_acked_handle = handle
            self.last_acked_capture_seq = self._proposal_heads.pop(handle, None)
            self._proposal_heads.clear()  # older proposals can never ack now
            self._ops_since_ack = 0
            self._nack_retries = 0
            if handle == self._pending_handle:
                self._pending_handle = None
                self.summaries_acked += 1
            return
        if msg.type == MessageType.SUMMARY_NACK:
            # correlate by handle: another client's nack must not clear
            # OUR in-flight attempt
            if (msg.contents or {}).get("handle") == self._pending_handle \
                    and self._pending_handle is not None:
                self._pending_handle = None
                self.summaries_nacked += 1
                # safe retry (ref: summaryNack → retry, summarizer.ts:
                # 403-428): without it a transient nack (e.g. a parent
                # raced another client's ack) strands the attempt until
                # the next op — which may never come on an idle doc.
                # Refresh the head from storage first so a parent-
                # mismatch retry proposes against the REAL chain instead
                # of failing identically.
                if self._nack_retries < 2:
                    self._nack_retries += 1
                    versions = self.container.storage.get_versions(1)
                    if versions:
                        self.last_acked_handle = versions[0]["id"]
                        self.last_acked_capture_seq = None
                    self._maybe_summarize(force=True)
            return
        if msg.type == MessageType.OPERATION:
            self._ops_since_ack += 1
            self._maybe_summarize()

    def _maybe_summarize(self, force: bool = False) -> None:
        if (
            (self._ops_since_ack < self.max_ops and not force)
            or not self.is_summarizer
            or self._pending_handle is not None
            or not self.container.connected
            # only acked state may be summarized (the reference gets this
            # invariant from the hidden summarizer container)
            or self.container.runtime.pending.count > 0
        ):
            return
        self.summarize_now()

    # ------------------------------------------------------------- attempt

    def summarize_now(self) -> Optional[str]:
        """Generate, upload, and propose an INCREMENTAL summary (ref:
        ContainerRuntime.generateSummary containerRuntime.ts:1631 +
        summarize op submission §3.4): a recursive SummaryTree where
        channels untouched since the parent's capture seq ride as
        SummaryHandles and re-upload nothing."""
        import json

        from ..protocol.summary import SummaryBlob, SummaryTree

        if self.container.runtime.pending.count > 0:
            raise RuntimeError("cannot summarize with pending local ops")
        seq = self.container.delta_manager.last_processed_seq
        cap = (self.last_acked_capture_seq
               if self.last_acked_handle is not None else None)
        root = SummaryTree(tree={
            "protocol": SummaryBlob(json.dumps(
                self.container.protocol.snapshot(),
                separators=(",", ":")).encode()),
            "sequence_number": SummaryBlob(json.dumps(seq).encode()),
            "runtime": self.container.runtime.summarize(cap),
        })
        handle = self.container.storage.upload_summary(
            root, parent=self.last_acked_handle)
        self._pending_handle = handle
        self._proposal_heads[handle] = seq
        self.container.delta_manager.submit(
            MessageType.SUMMARIZE,
            {"handle": handle, "parent": self.last_acked_handle,
             "head": seq},
        )
        return handle
