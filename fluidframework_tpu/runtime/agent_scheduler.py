"""AgentScheduler: distributed exclusive task ownership.

Ref: packages/runtime/agent-scheduler (scheduler.ts:34 pick/release,
TaskManager :366) — tasks like "summarizer"/"intel" must run on exactly
one client; ownership is decided through a ConsensusRegisterCollection
(volunteers write their clientId; the register's atomic read — earliest
surviving version — is the winner), and reassignment on owner departure
rides the sequenced CLIENT_LEAVE every replica sees at the same point in
the total order.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..protocol.messages import MessageType, SequencedDocumentMessage

SCHEDULER_CHANNEL = "agent-scheduler"


class AgentScheduler:
    """Attach one per container; ``pick(task, cb)`` volunteers this
    client. ``cb(owned: bool)`` fires on ownership changes."""

    def __init__(self, container, ds_id: str = "default"):
        self.container = container
        ds = container.runtime.get_data_store(ds_id)
        if SCHEDULER_CHANNEL in ds.channels:
            self.registers = ds.get_channel(SCHEDULER_CHANNEL)
        else:
            self.registers = ds.create_channel(
                SCHEDULER_CHANNEL, "consensus-register-collection")
        self._wanted: dict[str, Callable[[bool], None]] = {}
        self._owned: set[str] = set()
        # bids written but not yet resolved — guards against re-bidding
        # on every observed message while our own write is in flight
        self._bid_pending: set[str] = set()
        container.add_message_observer(self._observe)

    # ---------------------------------------------------------------- api

    def pick(self, task: str, cb: Optional[Callable[[bool], None]] = None
             ) -> None:
        """Volunteer for a task (ref: scheduler.ts pick). Ownership is
        decided by the register consensus; losers stay volunteers and
        take over if the owner leaves."""
        self._wanted[task] = cb or (lambda owned: None)
        self._maybe_bid(task)
        self._refresh()

    def release(self, task: str) -> None:
        """Stop volunteering; an owned task is handed off by writing a
        vacancy every volunteer observes (ref: scheduler.ts release)."""
        self._wanted.pop(task, None)
        if task in self._owned:
            self.registers.write(task, None)
        self._refresh()

    def owner(self, task: str) -> Optional[str]:
        """The LIVE owner: the register winner if still in the quorum."""
        winner = self.registers.read(task, policy="atomic")
        members = self.container.quorum.members
        if winner is not None and winner in members:
            return winner
        return None

    def owns(self, task: str) -> bool:
        return self.owner(task) == self.container.client_id \
            and self.container.client_id is not None

    @property
    def tasks(self) -> list[str]:
        return self.registers.keys()

    # ------------------------------------------------------------ internal

    def _maybe_bid(self, task: str) -> None:
        if self.owner(task) is None and task not in self._bid_pending:
            self._bid_pending.add(task)
            self.registers.write(task, self.container.client_id)

    def _observe(self, msg: SequencedDocumentMessage) -> None:
        # vacancies appear on owner CLIENT_LEAVE or an explicit release
        # write; every volunteer re-bids at the same total-order point
        # and the register consensus picks one winner
        for task in list(self._wanted):
            if self.owner(task) is not None:
                self._bid_pending.discard(task)  # race resolved
            else:
                self._maybe_bid(task)
        self._refresh()

    def _refresh(self) -> None:
        # snapshot: callbacks may pick()/release() (one-shot tasks)
        for task, cb in list(self._wanted.items()):
            owned_now = self.owns(task)
            was = task in self._owned
            if owned_now and not was:
                self._owned.add(task)
                cb(True)
            elif not owned_now and was:
                self._owned.discard(task)
                cb(False)
