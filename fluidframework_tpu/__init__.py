"""fluidframework_tpu — a TPU-native framework for real-time collaborative data.

Provides the capabilities of Microsoft's Fluid Framework (reference:
/root/reference, see SURVEY.md) re-designed TPU-first:

- ``protocol``  — wire contract: message types, quorum consensus, summary trees
                  (ref: server/routerlicious/packages/protocol-definitions,
                  protocol-base).
- ``mergetree`` — the core sequence CRDT ("merge tree"): scalar reference
                  implementation used as the oracle for the TPU kernels
                  (ref: packages/dds/merge-tree).
- ``ops``       — tensor encodings and JAX/Pallas kernels for the hot paths:
                  batched (refSeq, clientId) position resolution and
                  segment-merge apply across thousands of documents.
- ``dds``       — distributed data structures: SharedString, SharedMap,
                  SharedDirectory, SharedMatrix, SharedCell, SharedCounter,
                  consensus collections, Ink (ref: packages/dds/*).
- ``runtime``   — container runtime: op routing, batching, pending-state
                  replay, summarizer (ref: packages/runtime/*).
- ``loader``    — container loading and the delta manager op pump
                  (ref: packages/loader/container-loader).
- ``driver``    — service adapters (ref: packages/drivers/*).
- ``service``   — the ordering service: deli sequencer, scribe, broadcaster,
                  scriptorium lambdas and their in-process host
                  (ref: server/routerlicious/packages/lambdas, memory-orderer).
- ``storage``   — content-addressed snapshot store (git analog; ref:
                  server/gitrest, services-client GitManager).
- ``parallel``  — device-mesh sharding for the sequencer and kernel batch
                  (jax.sharding over docs/sequence axes).
- ``utils``     — telemetry, tracing, config registry, small collections.
"""

__version__ = "0.1.0"
