"""Canary prober: synthetic blackbox probes through the REAL doors.

The SLO engine only sees tenant traffic — a component that drops no
real request is invisible to it until a user arrives (the gray-failure
trap, Huang et al. HotOS'17). The prober closes that gap the way
Dapper closes the tracing gap: observe the system from its OWN doors.
A ticker thread per core dials the core's own listening socket like
any client and walks the doors end to end every tick on a reserved
``__canary__`` tenant:

``connect``   fresh TCP dial + connect frame → ``connected`` reply
              (auth, routing, session setup — the whole front door)
``submit``    one op on that session → its own broadcast push (the
              full submit → admit → deli → fanout round trip)
``history``   ``history_log`` on the canary doc (the history plane's
              read door)
``snapshot``  ``get_versions`` (the storage/boot read door; armed only
              when the core has a storage tier attached)
``route``     ping → pong against peer cores from the placement
              membership, cross-host peers FIRST on multi-host
              topologies (the door a gateway would route through)

Each door records ``health.probe.ms{door=...}`` into the windowed
registry and ``health.probe.failures{door=...}`` on error; door state
CHANGES (ok→fail, fail→ok) journal a ``health.probe`` entry. Peer
reachability rows feed the HealthEngine's placement component — three
dead peers on one host id IS the doctor's unreachable-host-group rule,
evaluated live.

Isolation: ``__canary__`` traffic is excluded at the admission seams
(service/front_end.py, service/admission.py) from placement heat,
tenant token buckets, and SLO hop accounting — probing can never
trigger rebalancing or shedding (tests/test_health_plane.py pins
this).

Layering: obs imports nothing above utils, so the transport is an
injected ``dial(host, port) -> channel`` factory (the service wiring
passes the driver's ``_Transport``) and ops ride as plain dict frames.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils.affinity import ticker_thread
from .journal import get_journal
from .metrics import get_registry

#: the reserved synthetic tenant; every isolation seam keys on this
CANARY_TENANT = "__canary__"
#: canary doc name prefix — the wiring picks a suffix this core owns
CANARY_DOC = "__probe__"


class CanaryProber:
    """Drives the doors once per tick; see the module docstring.

    ``dial(host, port)`` must return a channel with ``request_rid``,
    ``send``, ``on_push``, and ``close`` (the driver ``_Transport``
    contract). ``doc_fn`` returns a canary doc name routed to THIS
    core (or None while the core owns no partitions — the session
    doors then idle without counting failures). ``peers_fn`` returns
    ``owner -> {"addr": .., "host": ..}`` for the route door.
    ``token_fn(tenant, doc)`` mints a canary token on enforcing
    deployments (None in dev mode).
    """

    def __init__(self, dial: Callable, host: str, port: int,
                 core: str = "",
                 doc_fn: Optional[Callable] = None,
                 peers_fn: Optional[Callable] = None,
                 token_fn: Optional[Callable] = None,
                 registry=None, journal=None,
                 tick_s: float = 2.0, timeout: float = 5.0,
                 snapshot: bool = False, max_route_peers: int = 2):
        self._dial = dial
        self.host = host
        self.port = port
        self.core = core
        self._doc_fn = doc_fn
        self._peers_fn = peers_fn
        self._token_fn = token_fn
        self._reg = registry or get_registry()
        self.journal = journal if journal is not None else get_journal()
        self.tick_s = tick_s
        self.timeout = timeout
        self.snapshot = snapshot
        self.max_route_peers = max(0, int(max_route_peers))
        self._doors: dict = {}
        self._peer_rows: dict = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- verdicts

    def _record(self, door: str, ok: bool, ms: float,
                error: Optional[str] = None) -> None:
        self._reg.observe_windowed("health.probe.ms", ms, door=door)
        with self._lock:
            d = self._doors.setdefault(
                door, {"ok": True, "consec_failures": 0, "probes": 0,
                       "last_ms": 0.0, "last_error": None})
            d["probes"] += 1
            d["last_ms"] = round(ms, 3)
            was_ok = d["ok"]
            if ok:
                d["ok"] = True
                d["consec_failures"] = 0
                d["last_error"] = None
            else:
                d["ok"] = False
                d["consec_failures"] += 1
                d["last_error"] = error
                self._reg.inc("health.probe.failures", door=door)
        if ok is not was_ok:
            self.journal.emit(
                "health.probe", door=door,
                state="ok" if ok else "fail", error=error,
                ms=round(ms, 3))

    def status(self) -> dict:
        with self._lock:
            return {"doors": {k: dict(v)
                              for k, v in sorted(self._doors.items())},
                    "peers": {k: dict(v)
                              for k, v in self._peer_rows.items()}}

    def peer_rows(self) -> dict:
        """owner → manifest-shaped row (``error`` set when the route
        probe can't reach it) — the HealthEngine's ``cores_fn``."""
        with self._lock:
            return {k: dict(v) for k, v in self._peer_rows.items()}

    # ------------------------------------------------------------- doors

    def _timed(self, door: str, fn: Callable) -> bool:
        t0 = time.monotonic()
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — a failed door is data
            self._record(door, False,
                         (time.monotonic() - t0) * 1000.0, str(e))
            return False
        self._record(door, True, (time.monotonic() - t0) * 1000.0)
        return True

    def _probe_session_doors(self) -> None:
        """connect → submit/ack → history → snapshot, one fresh
        session through the real front door."""
        doc = self._doc_fn() if self._doc_fn is not None else CANARY_DOC
        if doc is None:
            return  # no owned partitions yet: nothing routes here
        token = (self._token_fn(CANARY_TENANT, doc)
                 if self._token_fn is not None else None)
        chan = None
        try:
            state: dict = {}

            def connect():
                state["chan"] = self._dial(self.host, self.port)
                _, reply = state["chan"].request_rid(
                    {"t": "connect", "tenant": CANARY_TENANT,
                     "doc": doc, "token": token, "bin": 0})
                state["client_id"] = reply.get("clientId")
                # the doc's current sequence number: a fresh session
                # must reference it or deli nacks the op ("refSeq below
                # msn") once an earlier probe advanced the MSN
                state["seq"] = int(reply.get("seq") or 0)

            if not self._timed("connect", connect):
                return
            chan = state["chan"]

            def submit():
                cid = state["client_id"]
                got = threading.Event()

                def seen(frame):
                    return any(m.get("client_id") == cid
                               for m in frame.get("msgs", []))

                chan.on_push("ops", lambda f: seen(f) and got.set())
                # one op in the driver's wire encoding
                # (protocol/serialization.py message_to_dict shape) —
                # a fresh session, so clientSeq starts at 1
                chan.send({"t": "submit", "ops": [{
                    "_kind": "doc",
                    "client_sequence_number": 1,
                    "reference_sequence_number": state["seq"],
                    "type": "op",
                    "contents": {"canary": self.core},
                    "metadata": None, "traces": []}]})
                if not got.wait(self.timeout):
                    raise TimeoutError(
                        f"own broadcast not seen in {self.timeout}s")

            self._timed("submit", submit)

            def history():
                chan.request_rid({"t": "history_log",
                                  "tenant": CANARY_TENANT, "doc": doc,
                                  "token": token})

            self._timed("history", history)

            if self.snapshot:
                def snapshot():
                    chan.request_rid({"t": "get_versions",
                                      "tenant": CANARY_TENANT,
                                      "doc": doc, "token": token,
                                      "count": 1})

                self._timed("snapshot", snapshot)

            try:
                chan.send({"t": "disconnect"})
            except Exception:
                pass
        finally:
            if chan is not None:
                try:
                    chan.close()
                except Exception:
                    pass

    def _probe_route(self) -> None:
        """ping → pong against peer cores, cross-host first: the leg a
        gateway (or a migrating partition) would actually traverse."""
        peers = dict(self._peers_fn() or {}) if self._peers_fn else {}
        with self._lock:
            for owner in list(self._peer_rows):
                if owner not in peers:
                    del self._peer_rows[owner]
        if not peers:
            return
        my_host = peers.pop(self.core, {}).get("host")
        ranked = sorted(
            peers.items(),
            key=lambda kv: (kv[1].get("host") == my_host, kv[0]))
        for owner, row in ranked[:self.max_route_peers]:
            addr = row.get("addr") or ""
            host, _, port = addr.rpartition(":")

            def route(owner=owner, addr=addr, host=host, port=port):
                try:
                    chan = self._dial(host or "127.0.0.1", int(port))
                except Exception as e:
                    raise ConnectionError(
                        f"{owner} ({addr}): {e}") from None
                try:
                    ev = threading.Event()
                    chan.on_push("pong", lambda f: ev.set())
                    chan.send({"t": "ping"})
                    if not ev.wait(self.timeout):
                        raise TimeoutError(
                            f"no pong from {owner} ({addr}) within "
                            f"{self.timeout}s")
                finally:
                    try:
                        chan.close()
                    except Exception:
                        pass

            ok = self._timed("route", route)
            with self._lock:
                prow = {"addr": addr, "host": row.get("host")}
                if not ok:
                    prow["error"] = (self._doors.get("route") or {}).get(
                        "last_error") or "unreachable"
                self._peer_rows[owner] = prow

    def probe_once(self) -> dict:
        """One full pass over every armed door; returns status()."""
        self._probe_session_doors()
        self._probe_route()
        return self.status()

    # ------------------------------------------------------------ thread

    def start(self) -> "CanaryProber":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fluid-probe-ticker",
                daemon=True)
            self._thread.start()
        return self

    @ticker_thread("probe")
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.probe_once()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
