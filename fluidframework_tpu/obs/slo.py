"""Windowed SLO engine: declarative p99 budgets over the hop taxonomy.

PR 5 gave the process windowed per-hop observations; this module reads
them. An :class:`SloSpec` names a hop pair (and optionally a tenant)
and a p99 budget in milliseconds; the :class:`SloEngine` evaluates
every spec against the registry's windowed series
(``obs.hop.window_ms``) on a ticker thread — or via direct
``evaluate(now)`` calls under a frozen clock in tests — and drives:

- ``obs.slo.state{slo=...}`` gauges (0=ok, 1=warn, 2=violated): a spec
  goes ``warn`` the first over-budget tick and ``violated`` after
  ``burn_ticks`` consecutive over-budget ticks (one hot sample is
  noise; a sustained burn is an incident);
- ``obs.slo.violations{slo=...}`` counting ok→violated transitions,
  with a flight-recorder dump on each (the ring holds the frames that
  led up to the burn);
- ``shed_signal`` / ``violated_pairs``, read lock-free by the front
  end's admission controller to arm SLO-burn shedding (see
  service/admission.py). The useful pair under ingress overload is
  ``submit_to_admit``: admit→deli happens inside one event-loop
  iteration and stays flat, while the submit→admit leg carries the
  kernel/loop queueing that overload actually inflates.

Spec string form (CLI ``--slo``)::

    name=pair:budget_ms[:window_s[:burn_ticks]]
    ingest=submit_to_admit:25:5:2
    tenant scoping: name=pair@tenant:budget_ms[...]
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from .flight import get_recorder
from ..utils.affinity import ticker_thread
from .journal import get_journal
from .metrics import get_registry

STATE_OK = 0
STATE_WARN = 1
STATE_VIOLATED = 2
_STATE_NAMES = {STATE_OK: "ok", STATE_WARN: "warn",
                STATE_VIOLATED: "violated"}

#: The windowed twin of ``obs.hop.ms`` the engine evaluates against.
WINDOWED_HOP_METRIC = "obs.hop.window_ms"


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective: pair (± tenant) → p99 budget."""

    name: str
    pair: str
    p99_budget_ms: float
    tenant: Optional[str] = None
    #: evaluation window in seconds (clamped to the registry ring span)
    window_s: float = 10.0
    #: consecutive over-budget ticks before ``violated``
    burn_ticks: int = 2
    #: below this many windowed samples the spec reads ok — a single
    #: hot sample in an idle window is noise, not an incident
    min_count: int = 8


def parse_slo_spec(text: str) -> SloSpec:
    """``name=pair[@tenant]:budget_ms[:window_s[:burn_ticks]]`` → spec."""
    try:
        name, rest = text.split("=", 1)
        parts = rest.split(":")
        pair, tenant = parts[0], None
        if "@" in pair:
            pair, tenant = pair.split("@", 1)
        budget = float(parts[1])
        window_s = float(parts[2]) if len(parts) > 2 else 10.0
        burn = int(parts[3]) if len(parts) > 3 else 2
    except (ValueError, IndexError):
        raise ValueError(
            f"bad --slo spec {text!r} "
            "(want name=pair[@tenant]:budget_ms[:window_s[:burn_ticks]])")
    return SloSpec(name=name, pair=pair, p99_budget_ms=budget,
                   tenant=tenant, window_s=window_s, burn_ticks=burn)


class SloEngine:
    """Evaluates specs against the windowed registry; see module doc.

    ``evaluate`` runs on the ticker thread (or a test caller); the
    front end's event loop only ever reads ``shed_signal`` and
    ``violated_pairs``, both swapped atomically."""

    def __init__(self, specs, registry=None, tick_s: float = 0.5,
                 recorder=None, journal=None):
        self.specs = list(specs)
        self.tick_s = tick_s
        self._reg = registry or get_registry()
        self._recorder = recorder
        self.journal = journal if journal is not None else get_journal()
        self._burn = {s.name: 0 for s in self.specs}
        self._state = {s.name: STATE_OK for s in self.specs}
        self._last: dict[str, tuple[int, float]] = {}
        self.violated_pairs: frozenset = frozenset()
        self.shed_signal = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        for s in self.specs:
            self._reg.set_gauge("obs.slo.state", STATE_OK, slo=s.name)

    # --------------------------------------------------------------- ticking

    def evaluate(self, now: Optional[float] = None) -> list[dict]:
        """One evaluation tick; returns :meth:`status`.

        ``now`` must be on the same monotonic clock the windowed
        observations were stamped with (tests inject both)."""
        violated = set()
        for s in self.specs:
            labels = {"pair": s.pair}
            if s.tenant is not None:
                labels["tenant"] = s.tenant
            count, q = self._reg.window_stats(
                WINDOWED_HOP_METRIC, now=now, window_s=s.window_s,
                **labels)
            p99 = q.get(0.99, 0.0)
            self._last[s.name] = (count, p99)
            over = count >= s.min_count and p99 > s.p99_budget_ms
            prev = self._state[s.name]
            if over:
                self._burn[s.name] += 1
                state = (STATE_VIOLATED
                         if self._burn[s.name] >= s.burn_ticks
                         else STATE_WARN)
            else:
                self._burn[s.name] = 0
                state = STATE_OK
            dump_id = None
            if state == STATE_VIOLATED:
                violated.add(s.pair)
                if prev != STATE_VIOLATED:
                    self._reg.inc("obs.slo.violations", slo=s.name)
                    try:
                        rec = self._recorder or get_recorder()
                        path = rec.dump(
                            "slo_violation", slo=s.name, pair=s.pair,
                            tenant=s.tenant, p99_ms=round(p99, 3),
                            budget_ms=s.p99_budget_ms, count=count)
                        # the dump is the violation's evidence: journal
                        # it, then link the state transition to it so
                        # the bundle can join incident → frames
                        dump_id = self.journal.emit(
                            "flight.dump", reason="slo_violation",
                            path=path, slo=s.name)
                    except Exception:
                        pass
            if state != prev:
                self._state[s.name] = state
                self._reg.set_gauge("obs.slo.state", state, slo=s.name)
                self.journal.emit(
                    "slo.state", cause=dump_id, slo=s.name, pair=s.pair,
                    tenant=s.tenant, state=_STATE_NAMES[state],
                    prev=_STATE_NAMES[prev], p99_ms=round(p99, 3),
                    budget_ms=s.p99_budget_ms, count=count)
        self.violated_pairs = frozenset(violated)
        self.shed_signal = bool(violated)
        return self.status()

    def status(self) -> list[dict]:
        """Per-spec health rows (the ``admin slo`` payload)."""
        out = []
        for s in self.specs:
            count, p99 = self._last.get(s.name, (0, 0.0))
            out.append({
                "slo": s.name, "pair": s.pair, "tenant": s.tenant,
                "state": _STATE_NAMES[self._state[s.name]],
                "p99_ms": round(p99, 3), "budget_ms": s.p99_budget_ms,
                "window_s": s.window_s, "count": count,
                "burn": self._burn[s.name], "burn_ticks": s.burn_ticks,
            })
        return out

    # ---------------------------------------------------------------- thread

    def start(self) -> "SloEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fluid-slo-ticker", daemon=True)
            self._thread.start()
        return self

    @ticker_thread("slo")
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.evaluate()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
