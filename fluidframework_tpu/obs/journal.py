"""Control-plane audit journal: the fleet's durable "why did that
happen" log.

Ref: Routerlicious funnels every service decision through the
Lumberjack structured logger precisely because a no-merge-logic-
on-the-server design pushes all debugging onto observability (SURVEY
§2). Our control plane (PRs 10-13) acts — bumps an epoch, transfers a
lease, seals a partition, suppresses a rebalance — but until now only
counters recorded THAT something happened, never WHY. This module is
the audit spine: every control-plane event appends one structured
JSONL entry to a per-core journal file on the shard dir, and entries
link to the event that caused them, so ``admin journal --fleet``
reconstructs causal chains across cores ("partition 3 moved at 14:02
because rebalance plan core0:41 saw heat 12k ops/s").

Entry schema (one JSON object per line, schema documented in
ARCHITECTURE.md "Fleet observability"):

    id     "<core>:<seq>" — globally unique, the cause-link target
    seq    per-core monotonic (recovered from the file tail on restart,
           so restarts never reuse ids)
    ts     wall-clock seconds (time.time) — human-correlatable
    core   emitting core id
    epoch  placement epoch at emit time (None when no table is bound)
    kind   one key of :data:`KINDS` — the closed registry fluidlint's
           ``journal-kind`` check enforces at lint time
    cause  the ``id`` of the triggering entry (or an opaque string such
           as a flight-dump path), None for root events
    labels free-form JSON-safe details (doc, part, reason, heat, ...)

Armament: the journal is DISARMED by default — ``emit`` on a disarmed
journal is one attribute test and a return (the bench A/B requirement:
disarmed overhead ~0). A core arms the process singleton when it has a
shard dir to persist on (``arm_journal``); in-process multi-core tests
construct private :class:`Journal` instances and inject them instead.

Durability: entries are flushed per write (control-plane events are
rare — never on the op hot path); the file rotates at ``max_bytes``
into a single ``.1`` generation, and readers tolerate torn tails (a
crash mid-write loses at most the last line).
"""

from __future__ import annotations

import io
import json
import os
import threading
from ..utils.affinity import any_thread
import time
from typing import Callable, Iterable, Optional

#: kind → one-line meaning. THE closed registry: every ``emit(kind)``
#: literal in the tree must be a key here — fluidlint's journal-kind
#: pass parses this table (a pure literal, keep it that way) and fails
#: the build on an undeclared kind, so the journal's vocabulary can
#: never drift silently.
KINDS = {
    "core.start": "core process started serving",
    "core.recover": "core recovered state after restart/crash",
    "core.stop": "core stopped serving (clean shutdown)",
    "lease.claim": "core claimed a partition lease",
    "lease.release": "core released a partition lease",
    "lease.takeover": "core revoked a peer's expired lease",
    "epoch.bump": "placement epoch advanced",
    "core.state": "core membership state changed (active/draining/drained)",
    "migration.seal": "partition sealed for migration (submits bounced)",
    "migration.fence": "migration fenced the partition's final seq",
    "migration.checkpoint": "sealed partition checkpointed + flushed",
    "migration.ship": "sealed log dir shipped cross-host via storage",
    "migration.adopt": "target core adopted the partition",
    "migration.commit": "migration committed (lease transferred)",
    "migration.fail": "migration failed and the source reclaimed",
    "rebalance.plan": "rebalancer produced an actionable plan",
    "rebalance.suppressed": "rebalancer suppressed a plan (with reason)",
    "rebalance.actuate": "rebalancer actuated one planned move",
    "slo.state": "SLO state transition (ok/warn/violated)",
    "summary.commit": "summarizer committed a summary",
    "flight.dump": "flight recorder wrote a dump",
    "operator.command": "operator-issued admin command",
    "history.commit": "history plane recorded a commit (ref advanced)",
    "history.fork": "doc forked from a parent commit",
    "history.integrate": "fork tail integrated back into its parent",
    "history.ref.recover": "recovery adopted/discarded a pending fork",
    "history.gc": "chunk GC swept unreferenced snapshot chunks",
    "core.cold_boot": "cold core armed lazy rehydration over its claims",
    "part.rehydrated": "partition served its first lazy doc boot",
    "part.checkpoint_fail": "one doc's checkpoint raised (others kept going)",
    "health.state": "health engine component transition (ok/degraded/critical)",
    "health.probe": "canary probe door failed or recovered",
}


class Journal:
    """Per-core durable audit journal (see module docstring).

    Disarmed (``path=None``) every method is a cheap no-op; ``arm``
    binds a file and recovers the monotonic seq from its tail.
    """

    def __init__(self, path: Optional[str] = None, core: str = "",
                 epoch_fn: Optional[Callable[[], Optional[int]]] = None,
                 max_bytes: int = 4 << 20):
        self._lock = threading.Lock()
        self._fh: Optional[io.TextIOWrapper] = None
        self._registry = None
        self.path: Optional[str] = None
        self.core = core
        self.epoch_fn = epoch_fn
        self.max_bytes = max_bytes
        self.seq = 0
        if path is not None:
            self.arm(path, core=core, epoch_fn=epoch_fn)

    @property
    def armed(self) -> bool:
        return self._fh is not None

    def arm(self, path: str, core: str = "",
            epoch_fn: Optional[Callable[[], Optional[int]]] = None) -> None:
        """Bind the journal to ``path`` and recover seq from its tail
        (a restarted core continues the id space instead of reusing
        ids, which would corrupt cause links in merged views)."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self.path = path
            if core:
                self.core = core
            if epoch_fn is not None:
                self.epoch_fn = epoch_fn
            last = 0
            for entry in _read_file(path):
                if entry.get("seq", 0) > last:
                    last = entry["seq"]
            self.seq = last
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")

    def disarm(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._fh = None
            self.path = None

    close = disarm

    def _metrics(self):
        if self._registry is None:
            from .metrics import get_registry

            self._registry = get_registry()
        return self._registry

    @any_thread
    def emit(self, kind: str, cause: Optional[str] = None,
             epoch: Optional[int] = None, **labels) -> Optional[str]:
        """Append one entry; returns its id (the cause link for
        downstream events), or None when disarmed."""
        if self._fh is None:
            return None
        if kind not in KINDS:
            raise ValueError(f"undeclared journal kind {kind!r} "
                             f"(add it to obs.journal.KINDS)")
        if epoch is None and self.epoch_fn is not None:
            try:
                epoch = self.epoch_fn()
            except Exception:
                epoch = None
        with self._lock:
            if self._fh is None:
                return None
            self.seq += 1
            entry = {
                "id": f"{self.core}:{self.seq}",
                "seq": self.seq,
                "ts": time.time(),
                "core": self.core,
                "epoch": epoch,
                "kind": kind,
                "cause": cause,
                "labels": labels,
            }
            try:
                line = json.dumps(entry, separators=(",", ":"),
                                  default=str)
                self._fh.write(line + "\n")
                self._fh.flush()
                reg = self._metrics()
                reg.inc("obs.journal.entries", kind=kind)
                reg.inc("obs.journal.bytes", len(line) + 1)
                if self._fh.tell() >= self.max_bytes:
                    self._rotate_locked()
            except OSError:
                self._metrics().inc("obs.journal.errors")
                return None
            return entry["id"]

    def _rotate_locked(self) -> None:
        """One-generation rotation: current → ``.1`` (replacing the
        previous generation), fresh current. seq continues."""
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            self._metrics().inc("obs.journal.errors")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._metrics().inc("obs.journal.rotations")

    def tail(self, n: int = 100, kind: Optional[str] = None,
             doc: Optional[str] = None,
             part: Optional[int] = None) -> list[dict]:
        """The last ``n`` entries (rotated generation included),
        optionally filtered — the ``admin_journal`` read path."""
        if self.path is None:
            return []
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
        entries = read_journal(self.path)
        return filter_entries(entries, kind=kind, doc=doc, part=part)[-n:]


def _read_file(path: str) -> Iterable[dict]:
    """Entries of one JSONL file; corrupt/torn lines are skipped (a
    crash mid-write must not poison every later read)."""
    try:
        f = open(path, encoding="utf-8")
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if isinstance(entry, dict) and "kind" in entry:
                yield entry


def read_journal(path: str) -> list[dict]:
    """Entries of a journal file, rotated generation first."""
    out = list(_read_file(path + ".1"))
    out.extend(_read_file(path))
    return out


def filter_entries(entries, kind: Optional[str] = None,
                   doc: Optional[str] = None,
                   part: Optional[int] = None) -> list[dict]:
    """Filter by kind prefix (``migration.`` matches every phase) and
    by the doc/part labels."""
    out = []
    for e in entries:
        if kind and not e.get("kind", "").startswith(kind):
            continue
        labels = e.get("labels") or {}
        if doc is not None and str(labels.get("doc")) != str(doc):
            continue
        if part is not None and str(labels.get("part")) != str(part):
            continue
        out.append(e)
    return out


def merge_entries(per_core: Iterable[list]) -> list[dict]:
    """Fleet merge: entries from many cores ordered by (epoch, ts,
    core, seq).

    Epoch leads wall time deliberately — the epoch table is the
    fleet's shared logical clock, so cross-core causality (seal on the
    source, adopt on the target) orders correctly even under wall-clock
    skew between hosts; ts only breaks ties within an epoch."""
    merged = [e for entries in per_core for e in entries]
    merged.sort(key=lambda e: (
        e.get("epoch") if isinstance(e.get("epoch"), (int, float)) else -1,
        e.get("ts", 0.0), str(e.get("core", "")), e.get("seq", 0)))
    return merged


def causal_chain(entries: list[dict], entry_id: str,
                 max_depth: int = 32) -> list[dict]:
    """Walk ``cause`` links backwards from ``entry_id`` → the chain
    root-first. Opaque causes (flight-dump paths) terminate the walk;
    cycles are cut by ``max_depth``."""
    by_id = {e["id"]: e for e in entries if "id" in e}
    chain: list[dict] = []
    seen: set[str] = set()
    cur = by_id.get(entry_id)
    while cur is not None and len(chain) < max_depth:
        if cur["id"] in seen:
            break
        seen.add(cur["id"])
        chain.append(cur)
        cause = cur.get("cause")
        cur = by_id.get(cause) if cause else None
    chain.reverse()
    return chain


_journal = Journal()


def get_journal() -> Journal:
    """The process-wide journal — disarmed (free) until a core with a
    shard dir arms it. Module-held singleton: the control-plane
    components that emit into it hold it for the process lifetime."""
    return _journal


def arm_journal(path: str, core: str = "",
                epoch_fn: Optional[Callable[[], Optional[int]]] = None
                ) -> Journal:
    """Arm the process singleton (idempotent re-arm rebinds)."""
    _journal.arm(path, core=core, epoch_fn=epoch_fn)
    return _journal


def reset_journal() -> None:
    """Disarm and reset the singleton IN PLACE (test isolation only) —
    components hold the object, so identity must survive the reset."""
    _journal.disarm()
    _journal.seq = 0
    _journal.core = ""
    _journal.epoch_fn = None
