"""Streaming doctor: the bundle triage rules, evaluated live.

``tools/doctor.py`` answers "what went wrong" from a bundle AFTER an
incident. This engine answers "is this core healthy RIGHT NOW" by
running the SAME rule code (``tools/doctor_rules.py`` — shared
verbatim, never re-derived) continuously against the live process:
the registry's own Prometheus scrape, the journal tail, the placement
table, the SLO engine's status rows, the boot surface, and the canary
prober's door verdicts (obs/probe.py). A gray failure — a component
that drops no tenant request but fails its own doors (Huang et al.,
HotOS'17) — surfaces here minutes before a user hits it, and the
rolling-upgrade loop's ``Fleet.wait_healthy`` gate keys on the verdict.

Per-component state machine, SloEngine-shaped:

- ``ok`` (0) → ``degraded`` (1) on the first tick a component's rules
  return anomalies — one bad tick is a fact worth a gauge, not yet an
  incident;
- ``degraded`` → ``critical`` (2) after ``critical_ticks`` consecutive
  anomalous ticks, or immediately on a HARD signal (a canary door past
  ``probe_fail_critical`` consecutive failures, an unreachable host
  group);
- transitions set ``health.engine.state{component=...}`` gauges and
  journal a ``health.state`` entry; entering ``critical`` arms a
  flight-recorder
  dump first and links the transition to it (the SLO engine's
  evidence-chain pattern).

``evaluate(now)`` is callable under a frozen clock for tests; the
ticker thread drives it in production. All sources are injected
callables returning bundle-shaped artifacts, so the offline/live
equivalence test can feed one fixture through BOTH consumers.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable, Optional

from .flight import get_recorder
from ..utils.affinity import ticker_thread
from .journal import get_journal, merge_entries
from .metrics import get_registry

try:
    from tools import doctor_rules as rules
except ImportError:  # package imported without the repo root on path
    _REPO = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    from tools import doctor_rules as rules

STATE_OK = 0
STATE_DEGRADED = 1
STATE_CRITICAL = 2
_STATE_NAMES = {STATE_OK: "ok", STATE_DEGRADED: "degraded",
                STATE_CRITICAL: "critical"}


class HealthEngine:
    """Continuous triage over injected live sources (see module doc).

    Every source is optional — a component with no source contributes
    no rules (an in-proc test fleet without a boot surface just has no
    ``boot`` component). Sources return the same artifact shapes the
    doctor reads out of a bundle:

    ``scrape_fn``     () -> Prometheus text (the registry's scrape)
    ``journal_fn``    () -> journal entry list (the live tail)
    ``placement_fn``  () -> admin_placement-shaped dict (parts/cores)
    ``cores_fn``      () -> owner -> capture row (``error`` key read;
                      live: the prober's peer-reachability rows)
    ``slo_fn``        () -> {"slos": [rows]} (the SLO engine status)
    ``boot_fn``       () -> admin_boot_status-shaped dict
    ``lint_fn``       () -> fluidlint --json dict (offline fixtures)
    ``probe_fn``      () -> the prober's status() dict
    ``self_row_fn``   () -> this core's manifest-shaped row
    """

    def __init__(self, core: str = "",
                 scrape_fn: Optional[Callable] = None,
                 journal_fn: Optional[Callable] = None,
                 placement_fn: Optional[Callable] = None,
                 cores_fn: Optional[Callable] = None,
                 slo_fn: Optional[Callable] = None,
                 boot_fn: Optional[Callable] = None,
                 lint_fn: Optional[Callable] = None,
                 probe_fn: Optional[Callable] = None,
                 self_row_fn: Optional[Callable] = None,
                 registry=None, journal=None, recorder=None,
                 tick_s: float = 1.0, critical_ticks: int = 3,
                 probe_fail_critical: int = 3):
        self.core = core
        self.tick_s = tick_s
        self.critical_ticks = max(1, int(critical_ticks))
        self.probe_fail_critical = max(1, int(probe_fail_critical))
        self._scrape_fn = scrape_fn
        self._journal_fn = journal_fn
        self._placement_fn = placement_fn
        self._cores_fn = cores_fn
        self._slo_fn = slo_fn
        self._boot_fn = boot_fn
        self._lint_fn = lint_fn
        self._probe_fn = probe_fn
        self._self_row_fn = self_row_fn
        self._reg = registry or get_registry()
        self.journal = journal if journal is not None else get_journal()
        self._recorder = recorder
        self._state: dict = {}
        self._streak: dict = {}
        self._reasons: dict = {}
        self._probes: Optional[dict] = None
        self.slo_burn: list = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- rules

    def _collect(self) -> tuple:
        """One pass over every source → ({component: [reasons]},
        {component: hard_critical}). The rule calls are the doctor's,
        in the doctor's per-artifact grouping."""
        comp: dict = {}
        hard: dict = {}

        if self._lint_fn is not None:
            comp["build"] = rules.lint_anomalies(self._lint_fn())

        if self._scrape_fn is not None:
            comp["scrape"] = rules.scrape_anomalies(
                self.core, self._scrape_fn() or "")

        if self._journal_fn is not None:
            tail = merge_entries([list(self._journal_fn() or [])])
            row = (self._self_row_fn() or {}) if self._self_row_fn \
                else {}
            r = rules.journal_disarmed_anomalies(self.core, row, tail)
            r += rules.suppression_storm_anomalies(self.core, tail)
            r += rules.epoch_regression_anomalies(tail)
            r += rules.fence_without_commit_anomalies(tail)
            r += [rules.migration_fail_anomaly(e) for e in tail
                  if e.get("kind") == "migration.fail"]
            comp["journal"] = r
            # a regressed epoch is split-brain evidence, not a blip
            hard["journal"] = any("epoch regressed" in a for a in r)

        if self._boot_fn is not None:
            comp["boot"] = rules.boot_anomalies(
                self.core, self._boot_fn())

        if self._placement_fn is not None or self._cores_fn is not None:
            rows = dict(self._cores_fn() or {}) if self._cores_fn \
                else {}
            r = []
            for owner in sorted(rows):
                r += rules.capture_error_anomalies(owner, rows[owner])
            placement = self._placement_fn() if self._placement_fn \
                else None
            r += rules.placement_anomalies(placement, rows)
            comp["placement"] = r
            hard["placement"] = any("unreachable" in a for a in r)

        if self._slo_fn is not None:
            self.slo_burn = rules.slo_burn_rows(
                self.core, self._slo_fn() or {})
            comp["slo"] = [
                f"slo {r['slo']} {r['state']}: p99 {r['p99_ms']}ms / "
                f"budget {r['budget_ms']}ms (burn {r['burn']}/"
                f"{r['burn_ticks']})" for r in self.slo_burn]

        if self._probe_fn is not None:
            status = self._probe_fn() or {}
            self._probes = status
            r = []
            hard_probe = False
            for door, d in sorted((status.get("doors") or {}).items()):
                n = d.get("consec_failures", 0)
                if n:
                    r.append(
                        f"canary probe {door} failing ({n} "
                        f"consecutive): {d.get('last_error')}")
                    if n >= self.probe_fail_critical:
                        hard_probe = True
            comp["probe"] = r
            hard["probe"] = hard_probe

        return comp, hard

    # ----------------------------------------------------------- ticking

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation tick; returns :meth:`status`. ``now`` is
        unused by the rules (they are clock-free over the artifacts)
        but kept for ticker-template symmetry with the SLO engine."""
        comp, hard = self._collect()
        for name in comp:
            reasons = comp[name]
            prev = self._state.get(name, STATE_OK)
            if reasons:
                self._streak[name] = self._streak.get(name, 0) + 1
                state = (STATE_CRITICAL
                         if (hard.get(name)
                             or self._streak[name] >= self.critical_ticks)
                         else STATE_DEGRADED)
            else:
                self._streak[name] = 0
                state = STATE_OK
            self._reasons[name] = reasons
            if state == prev and name in self._state:
                continue
            self._state[name] = state
            self._reg.set_gauge("health.engine.state", state,
                                component=name)
            if state == prev:
                continue  # first tick of a fresh component, still ok
            dump_id = None
            if state == STATE_CRITICAL:
                # evidence first, verdict second: the ring holds the
                # frames that led here — dump, journal the dump, then
                # link the transition to it (the SLO engine's pattern)
                try:
                    rec = self._recorder or get_recorder()
                    path = rec.dump(
                        "health_critical", component=name,
                        reasons=reasons[:3])
                    dump_id = self.journal.emit(
                        "flight.dump", reason="health_critical",
                        path=path, component=name)
                except Exception:
                    pass
            self.journal.emit(
                "health.state", cause=dump_id, component=name,
                state=_STATE_NAMES[state], prev=_STATE_NAMES[prev],
                reason=reasons[0] if reasons else None,
                n_reasons=len(reasons))
        return self.status()

    def anomalies(self) -> list:
        """Every rule-derived anomaly string, all components, in the
        doctor's grouping order (SLO burn stays separate, exactly as
        ``diagnose`` keeps ``slo_burn`` out of ``anomalies``)."""
        out = []
        for name in ("build", "scrape", "journal", "boot",
                     "placement", "probe"):
            out.extend(self._reasons.get(name, []))
        return out

    def verdict(self) -> str:
        worst = max(self._state.values(), default=STATE_OK)
        return _STATE_NAMES[worst]

    def status(self) -> dict:
        """The ``admin_health`` payload: one verdict, per-component
        states with their reasons, and the prober's door stats."""
        return {
            "core": self.core,
            "verdict": self.verdict(),
            "components": {
                name: {"state": _STATE_NAMES[state],
                       "streak": self._streak.get(name, 0),
                       "reasons": list(self._reasons.get(name, []))}
                for name, state in sorted(self._state.items())},
            "slo_burn": list(self.slo_burn),
            "probes": self._probes,
        }

    # ------------------------------------------------------------ thread

    def start(self) -> "HealthEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fluid-health-ticker",
                daemon=True)
            self._thread.start()
        return self

    @ticker_thread("health")
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.evaluate()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
