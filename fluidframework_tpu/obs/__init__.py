"""Observability plane (SURVEY §telemetry): labeled metrics registry
with a Prometheus scrape, and the crash flight recorder.

Hot paths keep their per-instance ``Counters``; this package is the
process-wide aggregation and post-mortem layer over them.
"""

from .flight import (  # noqa: F401
    FlightRecorder,
    get_recorder,
    reset_recorder,
)
from .journal import (  # noqa: F401
    KINDS as JOURNAL_KINDS,
    Journal,
    arm_journal,
    causal_chain,
    filter_entries,
    get_journal,
    merge_entries,
    read_journal,
    reset_journal,
)
from .metrics import (  # noqa: F401
    MetricsRegistry,
    WindowedSeries,
    get_registry,
    parse_prometheus,
    reset_registry,
    sum_counter_snapshots,
    tier_counters,
    tier_snapshot,
)
from .slo import (  # noqa: F401
    SloEngine,
    SloSpec,
    parse_slo_spec,
)
