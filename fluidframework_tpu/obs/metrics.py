"""Process-wide metrics registry with labels and a Prometheus scrape.

Ref: services/src/metricClient.ts ships counters to an external
telegraf; SURVEY §telemetry prescribes labeled series. Our tiers until
now each held a private :class:`~fluidframework_tpu.utils.telemetry.
Counters` surfaced ad hoc through ``admin_counters`` — attribution
stopped at whichever instance a test or bench happened to hold. This
module is the process-wide aggregation point:

- ``tier_counters(tier)`` hands a tier a FRESH ``Counters`` instance
  (hot paths keep their lock-free dict increments — nothing on the op
  path touches the registry) and registers it, weakly, under the tier
  label; the scrape sums same-named counters across live instances.
- ``inc``/``set_gauge``/``observe`` are the labeled direct API
  (``tenant``/``doc``/``pair``/``tier`` label keys) for the few cold
  call sites that want per-entity series.
- Label-set cardinality is BOUNDED per metric name: past ``max_series``
  distinct label sets, samples land in a single overflow bucket
  (``overflow="true"``) and ``obs.series.dropped`` counts the spills —
  a hostile tenant-id stream cannot grow the scrape without bound.
- ``scrape()`` renders Prometheus text exposition (counters as
  ``counter``, gauges as ``gauge``, observations as ``summary`` with
  p50/p99 quantile labels); :func:`parse_prometheus` is the matching
  reader used by tools/net_smoke.py and bench.py.

Dotted metric names (``tier.noun.verb`` — enforced by the fluidlint
``metric-name`` pass) map to Prometheus by ``.`` → ``_`` with a
``fluid_`` prefix.
"""

from __future__ import annotations

import math
import random
import threading
import time
import weakref
from typing import Optional

from ..utils.affinity import holds_lock
from ..utils.telemetry import Counters, percentile

#: Distinct label sets allowed per metric name before overflow.
DEFAULT_MAX_SERIES = 256

#: Windowed-series defaults: ten one-second buckets per series.
DEFAULT_WINDOW_S = 10.0
DEFAULT_WINDOW_BUCKETS = 10

#: History-ring defaults: ~15 min retained at 10 s resolution. Memory
#: is bounded per series at horizon/resolution slots of 4 numbers.
DEFAULT_HISTORY_S = 900.0
DEFAULT_HISTORY_RES_S = 10.0

_PREFIX = "fluid_"


def _prom_name(name: str) -> str:
    return _PREFIX + name.replace(".", "_").replace("-", "_")


def _prom_labels(labels: tuple) -> str:
    if not labels:
        return ""
    # exposition-spec label escaping: backslash, double quote, and
    # newline (a raw \n would split the sample across two lines and
    # corrupt the whole line-oriented scrape)
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in labels)
    return "{" + inner + "}"


class _Series:
    """One observation series: true count + bounded uniform reservoir
    (seeded, same scheme as ``Counters.observe``) — lifetime quantiles
    keep representing the whole stream instead of the first 4096
    warmup samples."""

    __slots__ = ("count", "samples", "_rng")

    def __init__(self):
        self.count = 0
        self.samples: list[float] = []
        self._rng = random.Random(0)

    def add(self, value: float, max_samples: int = 4096) -> None:
        self.count += 1
        if len(self.samples) < max_samples:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < max_samples:
                self.samples[j] = value


class WindowedSeries:
    """Epoch-ring windowed observations: ``buckets`` fixed-width time
    buckets spanning the trailing ``window_s`` seconds.

    ``observe`` is O(1): a value lands in the bucket indexed by its
    epoch (``now // width``) modulo the ring size, and a bucket whose
    stored epoch went stale is reset in place — that lazy reset IS the
    rotation, so an idle series costs nothing. Reads merge the samples
    of every bucket still inside the window, so quantiles reflect the
    last window, not process lifetime (the cumulative ``_Series``
    keeps that role). Per-bucket samples are a seeded reservoir with
    the true count kept separately.

    History ring (PR 14): a bucket expiring out of the live window is
    RETIRED — its (count, sum, max) folds into a coarse history slot
    (``history_res_s`` wide, default 10 s) retained for ``history_s``
    (default ~15 min), so a blip's before/after survives long past the
    live window at bounded memory (no samples are retained — count/
    sum/max only). ``history()`` merges retained slots with the live
    buckets, so the newest points appear immediately."""

    __slots__ = ("width", "buckets", "max_per_bucket", "_epochs",
                 "_counts", "_sums", "_maxs", "_samples", "_rng",
                 "history_res", "_hist_slots", "_history")

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 buckets: int = DEFAULT_WINDOW_BUCKETS,
                 max_per_bucket: int = 512,
                 history_s: float = DEFAULT_HISTORY_S,
                 history_res_s: float = DEFAULT_HISTORY_RES_S):
        self.width = window_s / buckets
        self.buckets = buckets
        self.max_per_bucket = max_per_bucket
        self._epochs = [-1] * buckets
        self._counts = [0] * buckets
        self._sums = [0.0] * buckets
        self._maxs = [0.0] * buckets
        self._samples: list[list[float]] = [[] for _ in range(buckets)]
        self._rng = random.Random(0)
        self.history_res = max(history_res_s, self.width)
        self._hist_slots = max(1, int(history_s / self.history_res))
        # slot index (monotonic // history_res) → [count, sum, max];
        # a dict (not a deque) because lazy retirement delivers buckets
        # out of order by up to a ring span
        self._history: dict[int, list[float]] = {}

    def _retire(self, epoch: int, count: int, vsum: float,
                vmax: float) -> None:
        """Fold an expiring live bucket into its history slot and
        prune slots past the horizon — bounded memory by construction."""
        slot = int(epoch * self.width / self.history_res)
        h = self._history.get(slot)
        if h is None:
            self._history[slot] = [count, vsum, vmax]
            if len(self._history) > self._hist_slots:
                lo = slot - self._hist_slots
                for s in [s for s in self._history if s <= lo]:
                    del self._history[s]
        else:
            h[0] += count
            h[1] += vsum
            if vmax > h[2]:
                h[2] = vmax

    def observe(self, value: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        e = int(now / self.width)
        i = e % self.buckets
        if self._epochs[i] != e:
            if self._counts[i]:
                self._retire(self._epochs[i], self._counts[i],
                             self._sums[i], self._maxs[i])
            self._epochs[i] = e
            self._counts[i] = 0
            self._sums[i] = 0.0
            self._maxs[i] = 0.0
            self._samples[i] = []
        self._sums[i] += value
        if value > self._maxs[i]:
            self._maxs[i] = value
        n = self._counts[i] = self._counts[i] + 1
        s = self._samples[i]
        if len(s) < self.max_per_bucket:
            s.append(value)
        else:
            j = self._rng.randrange(n)
            if j < self.max_per_bucket:
                s[j] = value

    def history(self, now: Optional[float] = None) -> list[dict]:
        """Retained + live points, oldest first, one per history slot:
        ``{"t": slot start (monotonic s), "count", "sum", "max"}``.
        Live buckets (not yet retired) merge in on read, so the series
        is current without waiting for expiry."""
        now = time.monotonic() if now is None else now
        lo = int(now / self.history_res) - self._hist_slots
        merged: dict[int, list[float]] = {
            s: list(v) for s, v in self._history.items() if s > lo}
        for i in range(self.buckets):
            if self._epochs[i] < 0 or not self._counts[i]:
                continue
            slot = int(self._epochs[i] * self.width / self.history_res)
            if slot <= lo:
                continue
            h = merged.get(slot)
            if h is None:
                merged[slot] = [self._counts[i], self._sums[i],
                                self._maxs[i]]
            else:
                h[0] += self._counts[i]
                h[1] += self._sums[i]
                if self._maxs[i] > h[2]:
                    h[2] = self._maxs[i]
        return [{"t": slot * self.history_res, "count": int(c),
                 "sum": s, "max": m}
                for slot, (c, s, m) in sorted(merged.items())]

    def stats(self, now: Optional[float] = None,
              window_s: Optional[float] = None) -> tuple[int, list]:
        """(true count, merged samples) over the live window — or over
        the trailing ``window_s`` seconds when narrower than the ring."""
        now = time.monotonic() if now is None else now
        e = int(now / self.width)
        span = self.buckets
        if window_s is not None:
            span = max(1, min(span, math.ceil(window_s / self.width)))
        lo = e - span + 1
        count = 0
        merged: list[float] = []
        for i in range(self.buckets):
            if self._epochs[i] >= lo:
                count += self._counts[i]
                merged.extend(self._samples[i])
        return count, merged

    def sum(self, now: Optional[float] = None,
            window_s: Optional[float] = None) -> float:
        """EXACT sum of every value observed inside the window. The
        quantile reads above ride a bounded reservoir, but each bucket
        also keeps a running sum, so rate reads (the placement heat
        planner's ops/s and bytes/s) never lose mass to sampling."""
        now = time.monotonic() if now is None else now
        e = int(now / self.width)
        span = self.buckets
        if window_s is not None:
            span = max(1, min(span, math.ceil(window_s / self.width)))
        lo = e - span + 1
        return sum(self._sums[i] for i in range(self.buckets)
                   if self._epochs[i] >= lo)

    def quantile(self, p: float, now: Optional[float] = None) -> float:
        _, merged = self.stats(now)
        return percentile(sorted(merged), p)


class MetricsRegistry:
    """The process-wide labeled metric store (see module docstring)."""

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES):
        self._lock = threading.Lock()
        self._max_series = max_series
        # name -> {sorted-label-tuple -> value}
        self._counters: dict[str, dict[tuple, float]] = {}
        self._gauges: dict[str, dict[tuple, float]] = {}
        self._observations: dict[str, dict[tuple, _Series]] = {}
        self._windows: dict[str, dict[tuple, WindowedSeries]] = {}
        # (tier, weakref-to-Counters) — scrape aggregates the live ones
        self._tiers: list[tuple[str, weakref.ref]] = []
        self.series_dropped = 0

    # ------------------------------------------------------------ write API

    @holds_lock("MetricsRegistry._lock")
    def _labelset(self, table: dict, name: str, labels: dict) -> tuple:
        """The bounded label key for (name, labels) — the overflow
        bucket once the name's cardinality budget is spent. Caller must
        hold ``self._lock`` (every public writer does)."""
        key = tuple(sorted(labels.items()))
        series = table.setdefault(name, {})
        if key not in series and len(series) >= self._max_series:
            self.series_dropped += 1
            return (("overflow", "true"),)
        return key

    def inc(self, name: str, by: float = 1, **labels) -> None:
        with self._lock:
            key = self._labelset(self._counters, name, labels)
            table = self._counters[name]
            table[key] = table.get(key, 0) + by

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            key = self._labelset(self._gauges, name, labels)
            self._gauges[name][key] = value

    def observe(self, name: str, value: float, **labels) -> None:
        with self._lock:
            key = self._labelset(self._observations, name, labels)
            series = self._observations[name].setdefault(key, _Series())
            series.add(value)

    def observe_windowed(self, name: str, value: float,
                         now: Optional[float] = None, **labels) -> None:
        """Record into the windowed twin of a summary series.

        Called per sampled boxcar / batch, never per op — the registry
        lock stays off the op hot path. ``now`` (monotonic seconds) is
        injectable so SLO tests can drive a frozen clock."""
        with self._lock:
            key = self._labelset(self._windows, name, labels)
            series = self._windows[name].setdefault(key, WindowedSeries())
            series.observe(value, now)

    def window_stats(self, name: str, now: Optional[float] = None,
                     window_s: Optional[float] = None,
                     quantiles: tuple = (0.5, 0.99),
                     **labels) -> tuple[int, dict]:
        """(count, {q: value}) over the live window, merged across every
        label set matching the (subset) filter — e.g. ``pair=...`` alone
        merges all tenants of that pair."""
        want = [(k, str(v)) for k, v in labels.items()]
        with self._lock:
            table = self._windows.get(name, {})
            matched = [ws for key, ws in table.items()
                       if all(kv in key for kv in want)]
        count = 0
        merged: list[float] = []
        for ws in matched:
            c, s = ws.stats(now, window_s)
            count += c
            merged.extend(s)
        merged.sort()
        return count, {q: percentile(merged, q) for q in quantiles}

    def window_sum(self, name: str, now: Optional[float] = None,
                   window_s: Optional[float] = None, **labels) -> float:
        """Exact windowed sum merged across every label set matching
        the (subset) filter — the rate read behind the per-partition
        heat signal (``window_stats`` answers "how slow", this answers
        "how much")."""
        want = [(k, str(v)) for k, v in labels.items()]
        with self._lock:
            table = self._windows.get(name, {})
            matched = [ws for key, ws in table.items()
                       if all(kv in key for kv in want)]
        return sum(ws.sum(now, window_s) for ws in matched)

    def window_sums_by(self, name: str, label: str,
                       now: Optional[float] = None,
                       window_s: Optional[float] = None
                       ) -> dict[str, float]:
        """``{label value: exact windowed sum}`` grouped over one label
        key in a single registry pass — the whole per-partition heat
        table (``label="part"``) without one lock round per
        partition."""
        with self._lock:
            table = self._windows.get(name, {})
            matched = [(dict(key).get(label), ws)
                       for key, ws in table.items()]
        out: dict[str, float] = {}
        for lv, ws in matched:
            if lv is None:
                continue
            out[lv] = out.get(lv, 0.0) + ws.sum(now, window_s)
        return out

    def window_history(self, name: Optional[str] = None,
                       now: Optional[float] = None, **labels) -> dict:
        """Retained history of every windowed series (or just
        ``name``), label-filtered by subset match — the read behind
        ``admin_metrics_history``:

            {name: [{"labels": {...}, "points": [...]}]}

        Points are :meth:`WindowedSeries.history` dicts; ``t`` is
        process-monotonic seconds (the RPC layer ships ``now_mono`` +
        ``now_wall`` alongside so clients can rebase to wall time)."""
        want = [(k, str(v)) for k, v in labels.items()]
        with self._lock:
            names = [name] if name is not None else list(self._windows)
            matched = [
                (n, key, ws)
                for n in names
                for key, ws in self._windows.get(n, {}).items()
                if all(kv in key for kv in want)]
        out: dict[str, list] = {}
        for n, key, ws in matched:
            points = ws.history(now)
            if points:
                out.setdefault(n, []).append(
                    {"labels": dict(key), "points": points})
        return out

    def register_tier(self, tier: str, counters: Counters) -> None:
        """Track a tier's Counters weakly: the hot path keeps writing
        its private instance, the scrape reads whatever is still
        alive."""
        with self._lock:
            self._tiers = [(t, r) for t, r in self._tiers
                           if r() is not None]
            self._tiers.append((tier, weakref.ref(counters)))

    # ------------------------------------------------------------- read API

    def _tier_snapshot(self) -> tuple[dict, dict]:
        """Aggregate registered tier Counters → (counts, observations),
        both keyed (name, (("tier", t),))."""
        counts: dict[tuple, float] = {}
        obs: dict[tuple, _Series] = {}
        with self._lock:
            live = [(t, r()) for t, r in self._tiers]
        for tier, c in live:
            if c is None:
                continue
            key = (("tier", tier),)
            # list() the views: the owning tier keeps mutating its
            # instance while we read
            for name, v in list(c._counts.items()):
                counts[(name, key)] = counts.get((name, key), 0) + v
            for name, vals in list(c._values.items()):
                s = obs.setdefault((name, key), _Series())
                s.count += c._observed[name]
                s.samples.extend(list(vals))
        return counts, obs

    def scrape(self) -> str:
        """Prometheus text exposition of everything the process knows."""
        tier_counts, tier_obs = self._tier_snapshot()
        with self._lock:
            counters = {n: dict(t) for n, t in self._counters.items()}
            gauges = {n: dict(t) for n, t in self._gauges.items()}
            observations = {n: dict(t)
                            for n, t in self._observations.items()}
            # snapshot windowed stats under the lock: (count, samples)
            # per live window, rendered as summaries below
            windows = {
                n: {key: ws.stats() for key, ws in t.items()}
                for n, t in self._windows.items()}
            dropped = self.series_dropped
        for (name, key), v in tier_counts.items():
            counters.setdefault(name, {})
            counters[name][key] = counters[name].get(key, 0) + v
        for (name, key), s in tier_obs.items():
            observations.setdefault(name, {})
            have = observations[name].setdefault(key, _Series())
            have.count += s.count
            have.samples.extend(s.samples)
        counters.setdefault("obs.series.dropped", {})[()] = (
            counters.get("obs.series.dropped", {}).get((), 0) + dropped)

        lines: list[str] = []
        for name in sorted(counters):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            for key in sorted(counters[name]):
                lines.append(
                    f"{pn}{_prom_labels(key)} {counters[name][key]:g}")
        for name in sorted(gauges):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            for key in sorted(gauges[name]):
                lines.append(
                    f"{pn}{_prom_labels(key)} {gauges[name][key]:g}")
        for name in sorted(observations):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            for key in sorted(observations[name]):
                s = observations[name][key]
                vals = sorted(s.samples)
                for q in (0.5, 0.99):
                    lines.append(
                        f"{pn}{_prom_labels(key + (('quantile', q),))} "
                        f"{percentile(vals, q):g}")
                lines.append(
                    f"{pn}_count{_prom_labels(key)} {s.count:g}")
                lines.append(
                    f"{pn}_sum{_prom_labels(key)} {sum(s.samples):g}")
        for name in sorted(windows):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            for key in sorted(windows[name]):
                count, samples = windows[name][key]
                vals = sorted(samples)
                for q in (0.5, 0.99):
                    lines.append(
                        f"{pn}{_prom_labels(key + (('quantile', q),))} "
                        f"{percentile(vals, q):g}")
                lines.append(f"{pn}_count{_prom_labels(key)} {count:g}")
                lines.append(
                    f"{pn}_sum{_prom_labels(key)} {sum(samples):g}")
        return "\n".join(lines) + "\n"


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (lazily constructed singleton)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry


def reset_registry() -> None:
    """Drop the singleton (test isolation only)."""
    global _registry
    with _registry_lock:
        _registry = None


def tier_counters(tier: str) -> Counters:
    """A fresh per-instance ``Counters`` registered under ``tier``.

    THE way production code obtains a Counters (the fluidlint
    ``metric-name`` pass bans bare ``Counters()`` construction outside
    this module): call sites keep their instance semantics and their
    lock-free hot path, and the process scrape sees every live
    instance, summed per (name, tier).
    """
    c = Counters()
    get_registry().register_tier(tier, c)
    return c


def tier_snapshot(tier: str) -> dict:
    """Summed counter snapshot across every live Counters instance
    registered under ``tier`` (``tier_counters`` hands out per-instance
    objects; this is the process-wide read the admin plane and the
    chaos verdicts use)."""
    counts, _ = get_registry()._tier_snapshot()
    key = (("tier", tier),)
    return {name: v for (name, k), v in counts.items() if k == key}


def sum_counter_snapshots(snaps) -> dict:
    """Sum same-named counters across per-process snapshot dicts.

    ``tier_snapshot`` covers exactly ONE process's registry; a sharded
    deployment runs one core per OS process, so a fleet total (the
    rebalancer's and the operator's view of ``placement.rebalance.*``)
    must sum the per-core snapshots fetched over their admin doors
    (``admin_tier_snapshot``). This is the pure summing half; the RPC
    fan-out lives in service/rebalancer.py.
    """
    out: dict = {}
    for snap in snaps:
        for name, v in snap.items():
            out[name] = out.get(name, 0) + v
    return out


def parse_prometheus(text: str) -> dict:
    """Parse text exposition → {name: {label-tuple: value}}.

    The reader half of :meth:`MetricsRegistry.scrape` (quantile labels
    included verbatim), used by tools/net_smoke.py and bench.py to
    consume ``admin_metrics_scrape`` output without a client library.
    """
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, sval = line.rsplit(None, 1)
            value = float(sval)
        except ValueError:
            raise ValueError(f"unparseable prometheus sample: {line!r}")
        if "{" in metric:
            name, rest = metric.split("{", 1)
            if not rest.endswith("}"):
                raise ValueError(f"unterminated label set: {line!r}")
            labels = []
            body = rest[:-1]
            while body:
                k, body = body.split("=", 1)
                if not body.startswith('"'):
                    raise ValueError(f"unquoted label value: {line!r}")
                # find the closing quote, honoring backslash escapes
                i, esc, out_chars = 1, False, []
                while i < len(body):
                    ch = body[i]
                    if esc:
                        # exposition escapes: \\ \" and \n (the writer
                        # half in _prom_labels emits exactly these)
                        out_chars.append("\n" if ch == "n" else ch)
                        esc = False
                    elif ch == "\\":
                        esc = True
                    elif ch == '"':
                        break
                    else:
                        out_chars.append(ch)
                    i += 1
                labels.append((k, "".join(out_chars)))
                body = body[i + 1:].lstrip(",")
            key = tuple(labels)
        else:
            name, key = metric, ()
        out.setdefault(name, {})[key] = value
    return out
