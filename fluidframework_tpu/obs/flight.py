"""Flight recorder: bounded post-mortem state, dumped on failure.

The chaos plane (PR 2) can say THAT an invariant tripped; it cannot say
what the wire looked like in the seconds before. This module keeps two
always-on rings per process — cheap enough to never turn off:

- an event ring: recent telemetry events (boxcar admissions, tickets,
  crashes) as small dicts;
- per-connection frame rings: the last N frame DIGESTS (timestamp,
  direction, length, first bytes hex) seen on each socket — digests,
  not bodies, so a hot connection pins a few KB, not its throughput.

``dump(reason)`` snapshots both rings to a JSONL file and returns the
path. Triggers (wired at the call sites): the chaos ``InvariantMonitor``
firing, an injected orderer crash, an unhandled tier exception escaping
a connection handler. The soak attaches ``last_dump`` to its failure
report so a red run carries its own post-mortem.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

#: Ring capacities: telemetry events, frame digests per connection, and
#: distinct connections tracked (oldest-touched evicted beyond that).
EVENT_RING = 512
FRAME_RING = 64
MAX_CONNS = 256
#: Leading body bytes kept in a frame digest.
DIGEST_HEAD = 12


class FlightRecorder:
    """Bounded rings + JSONL dump (see module docstring)."""

    def __init__(self, dump_dir: Optional[str] = None,
                 event_ring: int = EVENT_RING,
                 frame_ring: int = FRAME_RING,
                 max_conns: int = MAX_CONNS):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=event_ring)
        self._frames: OrderedDict[str, deque] = OrderedDict()
        self._frame_ring = frame_ring
        self._max_conns = max_conns
        self._dump_dir = dump_dir
        self._dump_n = 0
        self.last_dump: Optional[str] = None

    def event(self, tier: str, kind: str, **fields) -> None:
        """Record one telemetry event into the ring."""
        rec = {"ts": time.time(), "tier": tier, "event": kind}
        rec.update(fields)
        self._events.append(rec)

    def frame(self, conn: str, direction: str, body: bytes) -> None:
        """Record one frame digest on a connection's ring.

        ``direction`` is "in" (socket → tier) or "out" (tier → socket).
        """
        digest = {"ts": time.time(), "dir": direction, "len": len(body),
                  "head": bytes(body[:DIGEST_HEAD]).hex()}
        with self._lock:
            ring = self._frames.get(conn)
            if ring is None:
                while len(self._frames) >= self._max_conns:
                    self._frames.popitem(last=False)
                ring = self._frames[conn] = deque(maxlen=self._frame_ring)
            else:
                self._frames.move_to_end(conn)
            ring.append(digest)

    def dump(self, reason: str, **fields) -> str:
        """Snapshot both rings to a JSONL file; returns its path.

        Line 1 is the dump header ({"flight": reason, ...}); then the
        event ring oldest-first; then every connection's frame ring
        oldest-first — so the TAIL of the file is the frames that
        immediately preceded the trigger.
        """
        with self._lock:
            events = list(self._events)
            frames = [(conn, list(ring))
                      for conn, ring in self._frames.items()]
            self._dump_n += 1
            n = self._dump_n
        d = self._dump_dir or os.environ.get(
            "FLUID_FLIGHT_DIR") or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"flight-{os.getpid()}-{n}.jsonl")
        with open(path, "w") as f:
            head = {"flight": reason, "ts": time.time(),
                    "events": len(events),
                    "conns": len(frames)}
            head.update(fields)
            f.write(json.dumps(head, default=str) + "\n")
            for rec in events:
                f.write(json.dumps({"kind": "event", **rec}, default=str)
                        + "\n")
            for conn, ring in frames:
                for digest in ring:
                    f.write(json.dumps(
                        {"kind": "frame", "conn": conn, **digest}) + "\n")
        self.last_dump = path
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder (lazily constructed singleton)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def reset_recorder() -> None:
    """Drop the singleton (test isolation only)."""
    global _recorder
    with _recorder_lock:
        _recorder = None
