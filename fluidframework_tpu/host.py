"""Dev host: run any example app against a chosen service topology.

Ref: packages/tools/webpack-fluid-loader — the reference's ``fluid
start`` serves a data object against local / tinylicious / r11s targets
through one resolver seam (multiResolver.ts:75). Here the same role for
the process world: the host owns the SERVICE topology, the app module
only knows how to drive clients against a port (its ``run_clients``),
so every app runs unchanged against every deployment shape:

    python -m fluidframework_tpu.host todo                  # single core
    python -m fluidframework_tpu.host canvas -t gateway     # via gateways
    python -m fluidframework_tpu.host clicker -t split      # staged core
    python -m fluidframework_tpu.host shared_text -t sharded  # 2-core

Apps are repo-root ``examples/<name>`` modules exposing
``run_clients(port) -> int`` — all seven (shared_text, clicker,
table_doc, todo, canvas, sudoku, album) support every topology. An app
without ``run_clients`` (a third-party module that embeds its own
server) still runs via its ``run_demo()``, but only under ``-t
single`` — the host refuses to spawn a topology such an app would
silently ignore.
"""

from __future__ import annotations

import argparse
import contextlib
import importlib
import shutil
import subprocess
import sys
import tempfile


def _spawn(args: list, ready: str = "LISTENING"):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline().strip()
    assert line.startswith(ready), f"{args[0]}: {line!r}"
    port = int(line.rsplit(":", 1)[1]) if ":" in line else 0
    return proc, port


@contextlib.contextmanager
def topology(kind: str):
    """Yield a client-facing port for the requested deployment shape."""
    procs = []
    tmp = tempfile.mkdtemp(prefix="fluid-host-")
    try:
        if kind == "single":
            core, port = _spawn(
                ["fluidframework_tpu.service.front_end", "--port", "0"])
            procs.append(core)
        elif kind == "gateway":
            core, cport = _spawn(
                ["fluidframework_tpu.service.front_end", "--port", "0"])
            procs.append(core)
            gw, port = _spawn(["fluidframework_tpu.service.gateway",
                               "--core-port", str(cport)])
            procs.append(gw)
        elif kind == "split":
            # durable core + external scribe stage + storage process
            store, sport = _spawn(
                ["fluidframework_tpu.service.storage_server",
                 "--dir", f"{tmp}/store"])
            procs.append(store)
            scribe, _ = _spawn(
                ["fluidframework_tpu.service.stage_runner", "--stage",
                 "scribe", "--log-dir", f"{tmp}/log",
                 "--state-dir", f"{tmp}/scribe"], ready="READY")
            procs.append(scribe)
            core, port = _spawn(
                ["fluidframework_tpu.service.front_end", "--port", "0",
                 "--log-dir", f"{tmp}/log",
                 "--storage-server", str(sport), "--external-scribe",
                 "--consume-backchannel", f"{tmp}/scribe"])
            procs.append(core)
        elif kind == "sharded":
            for prefer in ("0", "1"):
                core, _ = _spawn(
                    ["fluidframework_tpu.service.front_end", "--port",
                     "0", "--shard-dir", f"{tmp}/deploy", "--shards",
                     "2", "--prefer", prefer])
                procs.append(core)
            gw, port = _spawn(["fluidframework_tpu.service.gateway",
                               "--shard-dir", f"{tmp}/deploy",
                               "--shards", "2"])
            procs.append(gw)
        else:
            raise ValueError(f"unknown topology {kind!r}")
        yield port
    finally:
        for p in reversed(procs):
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    p = argparse.ArgumentParser(
        description="run an example app on a service topology")
    p.add_argument("app", help="examples/<app> module name (e.g. todo)")
    p.add_argument("-t", "--topology", default="single",
                   choices=("single", "gateway", "split", "sharded"))
    args = p.parse_args()
    mod = importlib.import_module(f"examples.{args.app}")
    run_clients = getattr(mod, "run_clients", None)
    if run_clients is None:
        # legacy examples embed their own server — running run_demo()
        # under a spawned topology would silently IGNORE -t (the demo
        # talks to its own single core, not the processes we started)
        if args.topology != "single":
            p.error(f"examples.{args.app} has no run_clients(port); it "
                    f"only supports -t single (its demo embeds its own "
                    f"server)")
        raise SystemExit(mod.run_demo())
    with topology(args.topology) as port:
        raise SystemExit(run_clients(port))


if __name__ == "__main__":
    main()
