"""Execution-context markers for the concurrency contract checker.

Every hard-way bug in PRs 10–13 was a thread-discipline violation, not
a logic error: CPU donation silently serializing dispatch, staging
refills racing in-flight executions, ``tier_counters`` weakrefs dying
under the ticker thread, migrations that are only sound on the core's
event loop. These decorators make the discipline *visible* so
``tools/fluidlint/concurrency_check.py`` can enforce it statically
(RacerD / Clang ``-Wthread-safety`` style: annotate the boundaries,
propagate contexts along the call graph, flag the crossings).

They are pure markers — at runtime each costs ONE attribute assignment
at import time and nothing per call (the function object is returned
unwrapped). The checker reads them from the AST, so even un-imported
fixture trees are checkable.

Taxonomy (the context strings the checker propagates):

- ``@loop_only("core")`` — must only ever run on the named event-loop
  thread. The front end's pipeline, admission, presence, and the
  migration engine are ``loop_only("core")``: single-threadedness IS
  their locking discipline.
- ``@ticker_thread("slo")`` — runs on the named daemon ticker thread
  (SloEngine, Rebalancer, the applier worker). Also the right marker
  for callbacks *handed to* a ticker (the rebalancer's actuate seam).
- ``@any_thread`` — safe from any context; the function synchronizes
  internally (the journal's lock-guarded ``emit``).
- ``@holds_lock("epoch_table_flock")`` — acquires and holds the named
  lock for its body. Feeds the LOCK-ORDER rule (acquisitions must
  follow the single global order table) and fences shared-state writes.
- ``@blocking("...")`` — performs blocking I/O (socket round-trip,
  flock, mmap flush). A call to a ``blocking`` function reachable from
  an event-loop context is a BLOCKING-ON-LOOP violation unless waived.
"""

from __future__ import annotations

__all__ = ["loop_only", "ticker_thread", "any_thread", "holds_lock",
           "blocking"]


def loop_only(loop_name: str = "core"):
    """This function must only run on the named event-loop thread."""
    def mark(fn):
        fn.__affinity__ = ("loop", loop_name)
        return fn
    return mark


def ticker_thread(ticker_name: str):
    """This function runs on (or is a callback for) the named daemon
    ticker thread."""
    def mark(fn):
        fn.__affinity__ = ("ticker", ticker_name)
        return fn
    return mark


def any_thread(fn):
    """Safe from any context — the function synchronizes internally."""
    fn.__affinity__ = ("any", "")
    return fn


def holds_lock(lock_name: str):
    """The function acquires and holds the named registry lock for its
    body (see tools/fluidlint/registries.py LOCK_ORDER)."""
    def mark(fn):
        held = list(getattr(fn, "__holds_locks__", ()))
        held.append(lock_name)
        fn.__holds_locks__ = tuple(held)
        return fn
    return mark


def blocking(why: str):
    """The function performs blocking I/O; ``why`` names the operation
    and the PR that made it load-bearing."""
    def mark(fn):
        fn.__blocking__ = why
        return fn
    return mark
