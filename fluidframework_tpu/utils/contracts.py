"""Kernel contracts: declarative TPU hot-path invariants, checked statically.

The load-bearing performance claims of ARCHITECTURE.md ("apply is
gather-free", "everything under one jit", "staged ops ship as int16
packed waves") used to live only in prose — a regression in any of them
was silent until a bench run on real hardware. This module turns each
claim into a REGISTERED contract that ``tools/fluidlint`` (the repo's
static contract checker) abstract-evals and enforces in CI, the same way
the reference enforces its layer DAG mechanically with layer-check.

A contract names a hot-path entry point, an example-shape builder (lazy,
so registration costs nothing at import), and the invariants its jaxpr
must satisfy:

- ``no_gather`` / ``no_scatter`` — the traced program (walked through
  every nested jaxpr: scan/while/cond bodies, pjit calls, pallas_call
  kernels) contains no ``gather``/``scatter*`` primitive. Computed-index
  gathers/scatters are the TPU slow path — measured ~6x the entire
  apply for one 64k-row scatter.
- ``max_gathers`` — a budget instead of a ban, for kernels that fuse a
  deliberate once-per-wave gather (zamboni compaction's argsort repack)
  onto the gather-free per-op path. The budget catches a NEW gather
  creeping into the K-amplified part.
- ``max_dynamic_slices`` — budget for ``dynamic_slice`` equations, the
  second computed-index shape XLA can sink to the slow path.
- ``no_int16_arithmetic`` — no arithmetic primitive consumes an int16
  operand: every packed-wave field must be explicitly widened
  (``astype(int32)``) before math, never silently promoted.
- ``single_jit`` — calling the (jitted) kernel twice with same-shape
  inputs compiles exactly once; catches recompile regressions from
  unhashable statics, weak-type churn, or accidental python-level
  closure rebuilding.

Registration is zero-overhead on the hot path: the decorator records the
function in a module-level registry and returns it UNCHANGED.

This module sits in the bottom layer (``utils``) so every kernel layer
may import it; it imports nothing from the framework.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

#: () -> (args, kwargs) for one example trace of the kernel.
ExampleBuilder = Callable[[], tuple]

#: () -> (fn, example_builder); lets factory-produced kernels (jitted
#: closures keyed by geometry) defer construction to check time.
ContractBuilder = Callable[[], tuple]


@dataclass(frozen=True)
class KernelContract:
    """One registered hot-path entry point and its jaxpr invariants."""

    name: str
    build: ContractBuilder
    no_gather: bool = False
    no_scatter: bool = False
    max_gathers: Optional[int] = None
    max_dynamic_slices: Optional[int] = None
    no_int16_arithmetic: bool = False
    single_jit: bool = False
    notes: str = ""


# name -> KernelContract; fluidlint imports the kernel modules, which
# populate this at import time
_REGISTRY: dict[str, KernelContract] = {}


def kernel_contract(
    name: str,
    *,
    example: ExampleBuilder,
    no_gather: bool = False,
    no_scatter: bool = False,
    max_gathers: Optional[int] = None,
    max_dynamic_slices: Optional[int] = None,
    no_int16_arithmetic: bool = False,
    single_jit: bool = False,
    notes: str = "",
    registry: Optional[dict] = None,
) -> Callable:
    """Decorator form: register ``fn`` under ``name`` and return it
    unchanged. ``example()`` must return ``(args, kwargs)`` the kernel
    can be traced (and, for ``single_jit``, executed) with."""

    def deco(fn: Callable) -> Callable:
        register_kernel_contract(
            name,
            build=lambda: (fn, example),
            no_gather=no_gather,
            no_scatter=no_scatter,
            max_gathers=max_gathers,
            max_dynamic_slices=max_dynamic_slices,
            no_int16_arithmetic=no_int16_arithmetic,
            single_jit=single_jit,
            notes=notes,
            registry=registry,
        )
        return fn

    return deco


def register_kernel_contract(
    name: str,
    *,
    build: ContractBuilder,
    registry: Optional[dict] = None,
    **invariants: Any,
) -> KernelContract:
    """Non-decorator form for kernels produced by factories: ``build()``
    returns ``(fn, example_builder)``. Re-registration under the same
    name replaces (idempotent module reloads)."""
    contract = KernelContract(name=name, build=build, **invariants)
    (_REGISTRY if registry is None else registry)[name] = contract
    return contract


def registered_contracts() -> dict[str, KernelContract]:
    """The global registry (populated by importing the kernel modules)."""
    return dict(_REGISTRY)
