"""Telemetry: namespaced logger + perf events + metrics + trace hops.

Ref: packages/utils/telemetry-utils/src/logger.ts — ChildLogger
namespacing (:239), MultiSinkLogger (:283), PerformanceEvent scoped
timing (:434); server metric counters (services/src/metricClient.ts:7);
wire-level trace hops consumed for per-hop latency
(protocol-definitions/src/protocol.ts:59, deli stamping).

Differences by design: sinks are plain callables (no transport baked
in), and the trace consumer turns the hops deli already stamps into the
per-hop latency breakdown the load benches report — the reference
stamps traces but ships them to an external telegraf; here the
aggregation is in-process and queryable.
"""

from __future__ import annotations

import random
import time
from collections import defaultdict
from typing import Any, Callable, Optional

Sink = Callable[[dict], None]


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class TelemetryLogger:
    """Namespaced event logger with injectable sinks.

    ``child("deli")`` shares the sink chain and prefixes the namespace —
    the ChildLogger pattern. Events are dicts with at least
    ``{"category", "event", "namespace", "ts"}``.
    """

    def __init__(self, namespace: str = "", sinks: Optional[list[Sink]] = None):
        self.namespace = namespace
        self._sinks: list[Sink] = sinks if sinks is not None else []

    def child(self, namespace: str) -> "TelemetryLogger":
        ns = f"{self.namespace}:{namespace}" if self.namespace else namespace
        out = TelemetryLogger(ns)
        out._sinks = self._sinks  # shared chain: adding a sink later
        return out                # reaches existing children too

    def add_sink(self, sink: Sink) -> None:
        self._sinks.append(sink)

    def send(self, category: str, event: str, **fields: Any) -> None:
        if not self._sinks:
            return
        record = {"category": category, "event": event,
                  "namespace": self.namespace, "ts": time.time(), **fields}
        for sink in self._sinks:
            sink(record)

    def info(self, event: str, **fields: Any) -> None:
        self.send("generic", event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self.send("error", event, **fields)

    def perf(self, event: str, **fields: Any) -> "PerformanceEvent":
        return PerformanceEvent(self, event, fields)


class BufferSink:
    """Ring-buffer sink for tests and the /repl-style debug surface."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self.records: list[dict] = []

    def __call__(self, record: dict) -> None:
        self.records.append(record)
        if len(self.records) > self.capacity:
            del self.records[: len(self.records) - self.capacity]

    def of(self, event: str) -> list[dict]:
        return [r for r in self.records if r["event"] == event]


class PerformanceEvent:
    """Scoped timing (ref: PerformanceEvent logger.ts:434): emits
    ``<event>_end`` with duration_ms on success, ``<event>_cancel`` with
    the error on exception."""

    def __init__(self, logger: TelemetryLogger, event: str, fields: dict):
        self._logger = logger
        self._event = event
        self._fields = fields
        self._t0 = 0.0

    def __enter__(self) -> "PerformanceEvent":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        ms = (time.perf_counter() - self._t0) * 1e3
        if exc_type is None:
            self._logger.send("performance", f"{self._event}_end",
                              duration_ms=ms, **self._fields)
        else:
            self._logger.send("performance", f"{self._event}_cancel",
                              duration_ms=ms, error=str(exc), **self._fields)


class Counters:
    """Named monotonic counters + value observations (metricClient role).

    Value series are bounded: each keeps a ``max_samples`` reservoir
    (uniform reservoir sampling, seeded so snapshots are reproducible)
    plus the true observation count — a long-running service observing
    per-op latencies must not grow a list per op forever. ``count`` in
    the snapshot is always the TRUE number of observations, not the
    reservoir size.
    """

    def __init__(self, max_samples: int = 4096):
        self._counts: dict[str, int] = defaultdict(int)
        self._values: dict[str, list[float]] = defaultdict(list)
        self._observed: dict[str, int] = defaultdict(int)
        self._max_samples = max_samples
        self._rng = random.Random(0)

    def inc(self, name: str, by: int = 1) -> None:
        self._counts[name] += by

    def observe(self, name: str, value: float) -> None:
        n = self._observed[name] = self._observed[name] + 1
        vals = self._values[name]
        if len(vals) < self._max_samples:
            vals.append(value)
        else:
            j = self._rng.randrange(n)
            if j < self._max_samples:
                vals[j] = value

    def snapshot(self) -> dict:
        out: dict[str, Any] = dict(self._counts)
        for name, vals in self._values.items():
            s = sorted(vals)
            series: dict[str, Any] = {
                "count": self._observed[name],
                "p50": round(percentile(s, 0.5), 3),
                "p99": round(percentile(s, 0.99), 3),
            }
            if name in self._counts:
                # a counter and a value series share the name: surface
                # both under the key instead of the series silently
                # clobbering the counter (or vice versa)
                series["counter"] = self._counts[name]
            out[name] = series
        return out


class TraceAggregator:
    """Consume wire trace hops into a per-hop latency breakdown.

    The submitting client stamps ``client/submit``; deli stamps
    ``deli/sequence`` (service/deli.py); the ack observer calls
    ``record(msg)`` when its own op comes back. Produces the
    submit→deli and deli→ack split the north-star p99 decomposes into.
    """

    def __init__(self):
        self._hops: dict[str, list[float]] = defaultdict(list)

    def record(self, msg, ack_time: Optional[float] = None) -> None:
        now = ack_time if ack_time is not None else time.time()
        submit_ts = None
        deli_ts = None
        for hop in msg.traces:
            if hop.service == "client" and hop.action == "submit":
                submit_ts = hop.timestamp
            elif hop.service == "deli" and hop.action == "sequence":
                deli_ts = hop.timestamp
        if submit_ts is not None and deli_ts is not None:
            self._hops["submit_to_deli"].append((deli_ts - submit_ts) * 1e3)
        if deli_ts is not None:
            self._hops["deli_to_ack"].append((now - deli_ts) * 1e3)

    def merge_raw(self, hops: dict[str, list[float]]) -> None:
        for name, vals in hops.items():
            self._hops[name].extend(vals)

    @property
    def raw(self) -> dict[str, list[float]]:
        return dict(self._hops)

    def report(self) -> dict:
        out = {}
        for name, vals in self._hops.items():
            s = sorted(vals)
            out[name] = {"count": len(s),
                         "p50_ms": round(percentile(s, 0.5), 3),
                         "p99_ms": round(percentile(s, 0.99), 3)}
        return out
