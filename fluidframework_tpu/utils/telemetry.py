"""Telemetry: namespaced logger + perf events + metrics + trace hops.

Ref: packages/utils/telemetry-utils/src/logger.ts — ChildLogger
namespacing (:239), MultiSinkLogger (:283), PerformanceEvent scoped
timing (:434); server metric counters (services/src/metricClient.ts:7);
wire-level trace hops consumed for per-hop latency
(protocol-definitions/src/protocol.ts:59, deli stamping).

Differences by design: sinks are plain callables (no transport baked
in), and the trace consumer turns the hops deli already stamps into the
per-hop latency breakdown the load benches report — the reference
stamps traces but ships them to an external telegraf; here the
aggregation is in-process and queryable.
"""

from __future__ import annotations

import random
import time
from collections import defaultdict
from typing import Any, Callable, Optional

Sink = Callable[[dict], None]

# --------------------------------------------------------------- hop taxonomy
#
# The per-tier trace-hop vocabulary. Columnar wire frames carry hops
# as compact (hop id, timestamp) pairs (the binwire hoptail); rec
# frames carry the (service, action) strings. Both sides map through
# THIS table — it is the taxonomy's single source of truth — and the
# breakdown pair names (``submit_to_deli``, ``deli_to_ack``,
# ``admit_to_deli``, …) derive from the SHORT keys of consecutive
# PRESENT hops, so the legacy two-pair split falls out as the special
# case where only client/submit and deli/sequence are stamped.
#
# STABILITY: hop ids are WIRE values (hoptail u8, durable replays,
# mixed-version gateways) — existing ids are FROZEN and new hops are
# APPENDED, never inserted. Numeric id order therefore stopped
# matching path order at id 6; the pipeline position lives in
# HOP_PIPELINE below, and every ordering consumer sorts by that.
HOPS = (
    ("client", "submit", "submit"),
    ("gateway", "relay", "relay"),
    ("frontend", "admit", "admit"),
    ("deli", "sequence", "deli"),
    ("broadcast", "fanout", "fanout"),
    ("client", "ack", "ack"),
    # -- appended (PR 14): ids 6+ are newer than some stampers --
    ("frontend", "shed", "shed"),      # driver parked the op on a shed nack
    ("applier", "stage", "stage"),     # host half of a dispatch wave
    ("applier", "execute", "execute"),  # device half of a dispatch wave
)
(HOP_SUBMIT, HOP_RELAY, HOP_ADMIT, HOP_DELI, HOP_FANOUT,
 HOP_ACK, HOP_SHED, HOP_STAGE, HOP_EXECUTE) = range(len(HOPS))
#: hop id → (service, action) — the rec-frame string pair.
HOP_SERVICE_ACTION = tuple((s, a) for s, a, _ in HOPS)
#: (service, action) → hop id.
HOP_ID = {(s, a): i for i, (s, a, _) in enumerate(HOPS)}
#: hop id → short key used in breakdown pair names.
HOP_SHORT = tuple(short for _, _, short in HOPS)
#: Hop ids in PIPELINE order — shed precedes submit (the park happens
#: before the retry-flush restamps submit), stage/execute sit between
#: sequencing and fan-out (the applier consumes the sequenced stream).
HOP_PIPELINE = (HOP_SHED, HOP_SUBMIT, HOP_RELAY, HOP_ADMIT, HOP_DELI,
                HOP_STAGE, HOP_EXECUTE, HOP_FANOUT, HOP_ACK)
#: hop id → pipeline position (the sort key for breakdown legs).
HOP_ORDER = {h: i for i, h in enumerate(HOP_PIPELINE)}


def hop_pair_name(a: int, b: int) -> str:
    """The breakdown key for the leg between two hop ids."""
    return f"{HOP_SHORT[a]}_to_{HOP_SHORT[b]}"


def hop_pairs(hops) -> list[tuple[str, float]]:
    """[(hop_id, ts), ...] → [(pair_name, delta_ms), ...] between
    consecutive PRESENT hops in pipeline order (unknown ids ignored;
    a repeated id keeps its last timestamp — EXCEPT gateway/relay,
    where every stamp is kept in arrival order: stacked relay tiers
    each stamp the same id, so the repeats ARE the relay depth and
    each inter-tier leg surfaces as a ``relay_to_relay`` pair)."""
    ts_by_id: dict[int, float] = {}
    relays: list[float] = []
    for i, ts in hops:
        if not 0 <= i < len(HOPS):
            continue
        if i == HOP_RELAY:
            relays.append(ts)
        else:
            ts_by_id[i] = ts
    seq: list[tuple[int, float]] = []
    for h in HOP_PIPELINE:
        if h == HOP_RELAY:
            seq.extend((HOP_RELAY, ts) for ts in relays)
        elif h in ts_by_id:
            seq.append((h, ts_by_id[h]))
    return [(hop_pair_name(a, b), (tb - ta) * 1e3)
            for (a, ta), (b, tb) in zip(seq, seq[1:])]


def count_unknown_hops(hops) -> int:
    """Entries whose id falls outside the taxonomy — a version-skewed
    stamper. Callers surface the count as ``obs.trace.unknown_hops``
    (this module sits below obs/, so it cannot reach the registry)."""
    return sum(1 for i, _ in hops if not 0 <= i < len(HOPS))


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class TelemetryLogger:
    """Namespaced event logger with injectable sinks.

    ``child("deli")`` shares the sink chain and prefixes the namespace —
    the ChildLogger pattern. Events are dicts with at least
    ``{"category", "event", "namespace", "ts"}``.
    """

    def __init__(self, namespace: str = "", sinks: Optional[list[Sink]] = None):
        self.namespace = namespace
        self._sinks: list[Sink] = sinks if sinks is not None else []

    def child(self, namespace: str) -> "TelemetryLogger":
        ns = f"{self.namespace}:{namespace}" if self.namespace else namespace
        out = TelemetryLogger(ns)
        out._sinks = self._sinks  # shared chain: adding a sink later
        return out                # reaches existing children too

    def add_sink(self, sink: Sink) -> None:
        self._sinks.append(sink)

    def send(self, category: str, event: str, **fields: Any) -> None:
        if not self._sinks:
            return
        record = {"category": category, "event": event,
                  "namespace": self.namespace, "ts": time.time(), **fields}
        for sink in self._sinks:
            sink(record)

    def info(self, event: str, **fields: Any) -> None:
        self.send("generic", event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self.send("error", event, **fields)

    def perf(self, event: str, **fields: Any) -> "PerformanceEvent":
        return PerformanceEvent(self, event, fields)


class BufferSink:
    """Ring-buffer sink for tests and the /repl-style debug surface."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self.records: list[dict] = []

    def __call__(self, record: dict) -> None:
        self.records.append(record)
        if len(self.records) > self.capacity:
            del self.records[: len(self.records) - self.capacity]

    def of(self, event: str) -> list[dict]:
        return [r for r in self.records if r["event"] == event]


class PerformanceEvent:
    """Scoped timing (ref: PerformanceEvent logger.ts:434): emits
    ``<event>_end`` with duration_ms on success, ``<event>_cancel`` with
    the error on exception."""

    def __init__(self, logger: TelemetryLogger, event: str, fields: dict):
        self._logger = logger
        self._event = event
        self._fields = fields
        self._t0 = 0.0

    def __enter__(self) -> "PerformanceEvent":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        ms = (time.perf_counter() - self._t0) * 1e3
        if exc_type is None:
            self._logger.send("performance", f"{self._event}_end",
                              duration_ms=ms, **self._fields)
        else:
            self._logger.send("performance", f"{self._event}_cancel",
                              duration_ms=ms, error=str(exc), **self._fields)


class Counters:
    """Named monotonic counters + value observations (metricClient role).

    Value series are bounded: each keeps a ``max_samples`` reservoir
    (uniform reservoir sampling, seeded so snapshots are reproducible)
    plus the true observation count — a long-running service observing
    per-op latencies must not grow a list per op forever. ``count`` in
    the snapshot is always the TRUE number of observations, not the
    reservoir size.
    """

    def __init__(self, max_samples: int = 4096):
        self._counts: dict[str, int] = defaultdict(int)
        self._values: dict[str, list[float]] = defaultdict(list)
        self._observed: dict[str, int] = defaultdict(int)
        self._max_samples = max_samples
        self._rng = random.Random(0)

    def inc(self, name: str, by: int = 1) -> None:
        self._counts[name] += by

    def observe(self, name: str, value: float) -> None:
        n = self._observed[name] = self._observed[name] + 1
        vals = self._values[name]
        if len(vals) < self._max_samples:
            vals.append(value)
        else:
            j = self._rng.randrange(n)
            if j < self._max_samples:
                vals[j] = value

    def snapshot(self) -> dict:
        out: dict[str, Any] = dict(self._counts)
        for name, vals in self._values.items():
            s = sorted(vals)
            series: dict[str, Any] = {
                "count": self._observed[name],
                "p50": round(percentile(s, 0.5), 3),
                "p99": round(percentile(s, 0.99), 3),
            }
            if name in self._counts:
                # a counter and a value series share the name: surface
                # both under the key instead of the series silently
                # clobbering the counter (or vice versa)
                series["counter"] = self._counts[name]
            out[name] = series
        return out


class TraceAggregator:
    """Consume wire trace hops into an ordered hop-pair breakdown.

    Each tier stamps its hop from the :data:`HOPS` taxonomy (client/
    submit, gateway/relay, frontend/admit, deli/sequence, broadcast/
    fanout); the ack observer calls ``record(msg)`` when its own op
    comes back. Every leg between consecutive PRESENT hops becomes a
    ``{a}_to_{b}`` latency series — partial stamping (only client+deli)
    reproduces the legacy submit→deli / deli→ack split exactly.
    """

    def __init__(self):
        self._hops: dict[str, list[float]] = defaultdict(list)
        #: hops dropped for an id outside the taxonomy — a
        #: version-skewed stamper; surfaced in ``report()`` (and by
        #: service consumers as ``obs.trace.unknown_hops``) instead of
        #: vanishing silently.
        self.unknown_hops = 0

    def record(self, msg, ack_time: Optional[float] = None) -> None:
        hops = []
        for hop in msg.traces:
            i = HOP_ID.get((hop.service, hop.action))
            if i is not None:
                hops.append((i, hop.timestamp))
            else:
                self.unknown_hops += 1
        self.record_hops(
            hops, ack_time if ack_time is not None else time.time())

    def record_hops(self, hops, ack_time: Optional[float] = None) -> None:
        """Fold an ordered [(hop_id, timestamp), ...] list (the wire
        hoptail shape) into the breakdown.

        ``ack_time`` contributes the client/ack hop — but only when the
        op was actually sequenced (a deli-or-later hop is present): an
        op that never reached the sequencer has no ack latency to
        attribute, so a lone client/submit stamp records nothing.
        """
        known = [(i, ts) for i, ts in hops if 0 <= i < len(HOPS)]
        self.unknown_hops += len(hops) - len(known)
        # "actually sequenced" means deli-or-later in PIPELINE order —
        # appended ids like frontend/shed are numerically past deli but
        # sit before it on the path, so numeric comparison would lie
        deli_pos = HOP_ORDER[HOP_DELI]
        if (ack_time is not None
                and all(i != HOP_ACK for i, _ in known)
                and any(HOP_ORDER[i] >= deli_pos for i, _ in known)):
            known.append((HOP_ACK, ack_time))
        for name, ms in hop_pairs(known):
            self._hops[name].append(ms)

    def merge_raw(self, hops: dict[str, list[float]]) -> None:
        for name, vals in hops.items():
            self._hops[name].extend(vals)

    @property
    def raw(self) -> dict[str, list[float]]:
        return dict(self._hops)

    def report(self) -> dict:
        out = {}
        for name, vals in self._hops.items():
            s = sorted(vals)
            out[name] = {"count": len(s),
                         "p50_ms": round(percentile(s, 0.5), 3),
                         "p99_ms": round(percentile(s, 0.99), 3)}
        if self.unknown_hops:
            out["unknown_hops"] = {"count": self.unknown_hops}
        return out
