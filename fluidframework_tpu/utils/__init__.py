"""Base utils (SURVEY §1.1): telemetry logger, perf events, metrics,
wire-trace consumption, kernel-contract registry.
"""

from .contracts import (  # noqa: F401
    KernelContract,
    kernel_contract,
    register_kernel_contract,
    registered_contracts,
)
from .telemetry import (  # noqa: F401
    HOP_ACK,
    HOP_ADMIT,
    HOP_DELI,
    HOP_EXECUTE,
    HOP_FANOUT,
    HOP_ORDER,
    HOP_PIPELINE,
    HOP_RELAY,
    HOP_SHED,
    HOP_STAGE,
    HOP_SUBMIT,
    HOPS,
    BufferSink,
    Counters,
    PerformanceEvent,
    TelemetryLogger,
    TraceAggregator,
    count_unknown_hops,
    hop_pair_name,
    percentile,
)
