"""Base utils (SURVEY §1.1): telemetry logger, perf events, metrics,
wire-trace consumption.
"""

from .telemetry import (  # noqa: F401
    BufferSink,
    Counters,
    PerformanceEvent,
    TelemetryLogger,
    TraceAggregator,
    percentile,
)
