"""Fetch a LIVE document over the network into the file-driver layout.

Ref: packages/tools/fetch-tool — downloads a service document's ops and
snapshots for offline analysis; the output here is exactly the replay
tool's input (driver/file.py layout), so a production doc fetched from
any deployment replays through the real client stack offline:

    python -m fluidframework_tpu.replay.fetch --port P t doc --out DIR
    python -m fluidframework_tpu.replay.tool DIR/t/doc   # then inspect

Works against any front door: the core directly, or a gateway (storage
RPCs relay through).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def fetch_document(host: str, port: int, tenant: str, doc: str,
                   out_dir: str, token_provider=None) -> str:
    from ..driver.file import write_doc_dir
    from ..driver.network import NetworkDocumentServiceFactory

    factory = NetworkDocumentServiceFactory(host, port,
                                            token_provider=token_provider,
                                            snapshot_cache=False)
    svc = factory.create_document_service(tenant, doc)
    try:
        # snapshot FIRST: a long-lived doc's log prefix is truncated by
        # summary-driven retention (scriptorium.truncate_below), and a
        # from-zero delta request would be refused with
        # LogTruncatedError. The acked summary always covers the
        # truncated prefix, so fetching the snapshot + the tail above
        # its sequence_number reconstructs the doc completely.
        snap = svc.connect_to_storage().get_snapshot_tree()
        base = snap["sequence_number"] if snap else 0
        msgs = svc.connect_to_delta_storage().get_deltas(base, 10 ** 9)
        return write_doc_dir(os.path.join(out_dir, tenant, doc),
                             msgs, snap)
    finally:
        # library callers fetch many docs per process: the RPC
        # transport (socket + reader thread) must not leak per doc
        if svc._rpc is not None:
            svc._rpc.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fetch a live doc into the replay corpus layout")
    p.add_argument("tenant")
    p.add_argument("doc")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--out", required=True)
    args = p.parse_args(argv)
    doc_dir = fetch_document(args.host, args.port, args.tenant, args.doc,
                             args.out)
    n = len(json.load(open(os.path.join(doc_dir, "messages.json"))))
    print(f"fetched {args.tenant}/{args.doc}: {n} ops -> {doc_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
