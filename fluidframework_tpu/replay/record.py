"""Corpus recorder: seeded multi-client scenarios → committed replay
corpus (file-driver layout + expectations).

Ref: the reference's snapshot corpus is recorded real documents
(packages/test/snapshots README.md:80-97); here the corpus is generated
by the same randomized farms that fuzz the merge-tree, so it covers
concurrent inserts/removes/annotates, markers, map ops, and reconnects
deterministically. Run ``python -m fluidframework_tpu.replay.record
--out tests/corpus`` to (re-)record after an INTENTIONAL format change;
CI replays the committed corpus and fails on any unintentional drift.
"""

from __future__ import annotations

import argparse
import json
import os
import random

from ..driver import LocalDocumentServiceFactory
from ..driver.file import record_document
from ..loader import Loader
from ..service import LocalServer
from .tool import ReplayController, replay_through_applier
from ..driver.file import FileDocumentService

SCENARIOS = {
    # name → (seed, clients, rounds)
    "text-basic": (7, 2, 40),
    "text-conflict": (23, 4, 60),
    "text-map-mixed": (51, 3, 50),
}


def run_scenario(server: LocalServer, name: str, seed: int, n_clients: int,
                 rounds: int) -> str:
    """Deterministic multi-client editing session on one document."""
    rng = random.Random(seed)
    loader = Loader(LocalDocumentServiceFactory(server))
    clients = [loader.resolve("corpus", name) for _ in range(n_clients)]
    ds = clients[0].runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    text.insert_text(0, "seed text for the corpus. ")
    kv = ds.create_channel("kv", "shared-map") if "map" in name else None
    if "basic" in name:
        # give one scenario an acked mid-stream summary, so the corpus
        # also covers boot-from-snapshot + tail replay
        from ..runtime.summarizer import SummaryManager

        SummaryManager(clients[0], max_ops=25)

    for r in range(rounds):
        c = clients[rng.randrange(n_clients)]
        s = c.runtime.get_data_store("default").get_channel("text")
        length = len(s.get_text())
        roll = rng.random()
        if roll < 0.45 or length < 6:
            pos = rng.randrange(length + 1)
            s.insert_text(pos, f"w{r} ")
        elif roll < 0.7:
            a = rng.randrange(length - 2)
            s.remove_text(a, min(length, a + 1 + rng.randrange(4)))
        elif roll < 0.85:
            a = rng.randrange(length - 2)
            s.annotate_range(a, min(length, a + 1 + rng.randrange(6)),
                             {"style": rng.randrange(4)})
        elif roll < 0.92:
            s.insert_marker(rng.randrange(length + 1),
                            {"kind": "para"}, {"m": r})
        elif kv is not None:
            m = c.runtime.get_data_store("default").get_channel("kv")
            m.set(f"k{rng.randrange(8)}", r)
        else:
            pos = rng.randrange(length + 1)
            s.insert_text(pos, "*")
    # convergence sanity before recording
    texts = {
        c.runtime.get_data_store("default").get_channel("text").get_text()
        for c in clients
    }
    assert len(texts) == 1, "scenario did not converge"
    return texts.pop()


def record_all(out_dir: str) -> None:
    for name, (seed, n_clients, rounds) in SCENARIOS.items():
        server = LocalServer()
        live_text = run_scenario(server, name, seed, n_clients, rounds)
        doc_dir = record_document(server, "corpus", name, out_dir)
        # expectations come from an immediate replay; the live text cross-
        # checks that replay-through-container equals the live replicas
        expect = ReplayController(
            FileDocumentService.from_dir(doc_dir)).run(snapshot_every=50)
        assert expect["final_text"] == live_text, name
        device_text = replay_through_applier(doc_dir)
        assert device_text == live_text, f"{name}: device replay diverged"
        with open(os.path.join(doc_dir, "expect.json"), "w") as f:
            json.dump(expect, f, indent=1, sort_keys=True)
        print(f"recorded {name}: {expect['last_seq']} ops, "
              f"{len(expect['snapshots'])} fingerprints")


def main() -> None:
    p = argparse.ArgumentParser(description="record replay corpus")
    p.add_argument("--out", default="tests/corpus")
    args = p.parse_args()
    record_all(args.out)


if __name__ == "__main__":
    main()
