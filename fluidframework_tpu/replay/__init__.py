"""Replay tool + snapshot-regression harness (SURVEY §5.7 aux ring).

Ref: packages/tools/replay-tool (replayMessages.ts) and
packages/test/snapshots (replayMultipleFiles.ts:33 Mode.Write/Compare).
"""

from .tool import (  # noqa: F401
    ReplayController,
    replay_and_compare,
    replay_through_applier,
    state_fingerprint,
)
