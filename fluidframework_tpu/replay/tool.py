"""Replay recorded op logs through the REAL client stack and the TPU
applier, asserting byte-identical state fingerprints across versions.

Ref: replay-tool/src/replayMessages.ts (drives loader+runtime over the
replay driver, snapshotting at intervals) and
packages/test/snapshots/src/replayMultipleFiles.ts:33 (Write mode records
expectations, Compare mode fails on any drift). A fingerprint mismatch
against a committed corpus means a semantic change to the CRDT — either
an intentional format bump (re-record the corpus) or a regression.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from ..driver.file import FileDocumentService
from ..loader.container import Container
from ..obs import tier_counters
from ..protocol.messages import MessageType

DS_ID = "default"
TEXT_CHANNEL = "text"


def state_fingerprint(container: Container) -> str:
    """Canonical sha256 over the container's full replica state — the
    byte-identity the snapshot-regression suite compares across code
    versions (dict key order normalized; no timestamps included)."""
    state = {
        "protocol": container.protocol.snapshot(),
        "runtime": container.runtime.snapshot(),
        "sequence_number": container.delta_manager.last_processed_seq,
    }
    blob = json.dumps(state, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


class ReplayController:
    """Pumps a document through a real Container in steps.

    Boot is history-first: when the service exposes a history surface
    holding a committed version (live local/network docs the history
    plane tracks), the container boots O(snapshot) from the newest
    commit through the replay driver and only the tail above its base
    is pumped. Otherwise — file-driver corpus docs, docs never
    summarized — the legacy path replays the recorded log from its
    start and is counted under ``history.replay.legacy`` so deployments
    can see how many offline replays still bypass the commit graph."""

    def __init__(self, service):
        self.service = service
        self.counters = tier_counters("driver")
        self.history = self._resolve_history(service)
        if self.history is not None:
            self._last = self._history_head(self.history)
            self.container = Container(
                self.history.replay_service(self._last)).load(connect=False)
        else:
            self._last = service.connect_to_delta_storage().last_seq
            self.container = Container(service).load(connect=False)
            self.counters.inc("history.replay.legacy")

    @staticmethod
    def _resolve_history(service):
        try:
            history = service.history()
        except NotImplementedError:
            return None
        return history if history.log(1) else None

    @staticmethod
    def _history_head(history) -> int:
        """Last sequenced seq the history plane can serve: the newest
        commit's base plus its durable tail."""
        base = history.at(10 ** 9)["base_seq"]
        tail = history.deltas(base, 10 ** 9)
        return tail[-1].sequence_number if tail else base

    def run(self, snapshot_every: int = 50) -> dict:
        """Replay to the end, fingerprinting every ``snapshot_every``
        sequenced ops; returns the expectations record. The fingerprint
        grid stays anchored at multiples of ``snapshot_every`` whatever
        the boot base, so history-first and legacy replays of the same
        doc agree on every seq they both cover."""
        last = self._last
        snapshots: dict[str, str] = {}
        base = self.container.delta_manager.last_processed_seq
        seq = base - (base % snapshot_every)
        while seq < last:
            seq = min(seq + snapshot_every, last)
            at = self.container.delta_manager.advance_to(seq)
            snapshots[str(at)] = state_fingerprint(self.container)
        return {
            "last_seq": last,
            "snapshots": snapshots,
            "final_text": self.final_text(),
        }

    def final_text(self) -> Optional[str]:
        ds = self.container.runtime.data_stores.get(DS_ID)
        if ds is None or TEXT_CHANNEL not in ds.channels:
            return None
        return ds.get_channel(TEXT_CHANNEL).get_text()


def replay_and_compare(doc_dir: str, expect: dict,
                       snapshot_every: int = 50) -> list[str]:
    """Compare mode: replay ``doc_dir`` and diff against committed
    expectations. Returns human-readable mismatches (empty = pass)."""
    got = ReplayController(
        FileDocumentService.from_dir(doc_dir)).run(snapshot_every)
    problems = []
    if got["last_seq"] != expect["last_seq"]:
        problems.append(
            f"last_seq: got {got['last_seq']}, want {expect['last_seq']}")
    if got["final_text"] != expect["final_text"]:
        problems.append(
            f"final_text drift: got {got['final_text']!r}, "
            f"want {expect['final_text']!r}")
    for seq, want in expect["snapshots"].items():
        have = got["snapshots"].get(seq)
        if have != want:
            problems.append(f"fingerprint @seq {seq}: {have} != {want}")
    return problems


def replay_through_applier(doc_dir: str, applier=None) -> str:
    """Feed the recorded doc's text-channel stream through a
    TpuDocumentApplier (the scribe-replay role, BASELINE config 5) and
    return the device-side final text."""
    from ..service.tpu_applier import TpuDocumentApplier

    service = FileDocumentService.from_dir(doc_dir)
    msgs = service.connect_to_delta_storage().get_deltas(0, 10**9)
    if applier is None:
        applier = TpuDocumentApplier(max_docs=4, max_slots=512,
                                     ops_per_dispatch=16)
    applier.set_replay_source(lambda t, d: [])
    pairs = []
    for m in msgs:
        if m.type != MessageType.OPERATION:
            continue
        env = m.contents
        if not isinstance(env, dict) or env.get("kind") != "chanop":
            continue
        if env["address"] != DS_ID:
            continue
        inner = env["contents"]
        if inner.get("address") != TEXT_CHANNEL or "attach" in inner:
            continue
        pairs.append((m, inner["contents"]))
    applier.ingest_batch("replay", os.path.basename(doc_dir), pairs)
    applier.finalize()
    return applier.get_text("replay", os.path.basename(doc_dir))


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="replay a doc through the real client stack "
                    "(history-first where a committed version exists)")
    p.add_argument("target", nargs="+",
                   help="a file-driver doc dir, or TENANT DOC with --port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int,
                   help="replay a LIVE doc through its history plane")
    p.add_argument("--every", type=int, default=50,
                   help="fingerprint interval in sequenced ops")
    args = p.parse_args(argv)
    if args.port is not None:
        if len(args.target) != 2:
            p.error("--port takes TENANT DOC")
        from ..driver.network import NetworkDocumentServiceFactory

        svc = NetworkDocumentServiceFactory(
            args.host, args.port,
            snapshot_cache=False).create_document_service(*args.target)
        controller = ReplayController(svc)
    else:
        if len(args.target) != 1:
            p.error("exactly one doc dir without --port")
        controller = ReplayController(
            FileDocumentService.from_dir(args.target[0]))
    got = controller.run(args.every)
    mode = ("history-first" if controller.history is not None
            else "legacy whole-log")
    print(f"{mode} replay to seq {got['last_seq']}: "
          f"{len(got['snapshots'])} fingerprint(s)")
    print(f"final text: {got['final_text']!r}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
