"""Replay recorded op logs through the REAL client stack and the TPU
applier, asserting byte-identical state fingerprints across versions.

Ref: replay-tool/src/replayMessages.ts (drives loader+runtime over the
replay driver, snapshotting at intervals) and
packages/test/snapshots/src/replayMultipleFiles.ts:33 (Write mode records
expectations, Compare mode fails on any drift). A fingerprint mismatch
against a committed corpus means a semantic change to the CRDT — either
an intentional format bump (re-record the corpus) or a regression.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from ..driver.file import FileDocumentService
from ..loader.container import Container
from ..protocol.messages import MessageType

DS_ID = "default"
TEXT_CHANNEL = "text"


def state_fingerprint(container: Container) -> str:
    """Canonical sha256 over the container's full replica state — the
    byte-identity the snapshot-regression suite compares across code
    versions (dict key order normalized; no timestamps included)."""
    state = {
        "protocol": container.protocol.snapshot(),
        "runtime": container.runtime.snapshot(),
        "sequence_number": container.delta_manager.last_processed_seq,
    }
    blob = json.dumps(state, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


class ReplayController:
    """Pumps a file-driver document through a real Container in steps."""

    def __init__(self, service: FileDocumentService):
        self.service = service
        self.container = Container(service).load(connect=False)

    def run(self, snapshot_every: int = 50) -> dict:
        """Replay to the end, fingerprinting every ``snapshot_every``
        sequenced ops; returns the expectations record."""
        last = self.service.last_seq
        snapshots: dict[str, str] = {}
        seq = 0
        while seq < last:
            seq = min(seq + snapshot_every, last)
            at = self.container.delta_manager.advance_to(seq)
            snapshots[str(at)] = state_fingerprint(self.container)
        return {
            "last_seq": last,
            "snapshots": snapshots,
            "final_text": self.final_text(),
        }

    def final_text(self) -> Optional[str]:
        ds = self.container.runtime.data_stores.get(DS_ID)
        if ds is None or TEXT_CHANNEL not in ds.channels:
            return None
        return ds.get_channel(TEXT_CHANNEL).get_text()


def replay_and_compare(doc_dir: str, expect: dict,
                       snapshot_every: int = 50) -> list[str]:
    """Compare mode: replay ``doc_dir`` and diff against committed
    expectations. Returns human-readable mismatches (empty = pass)."""
    got = ReplayController(
        FileDocumentService.from_dir(doc_dir)).run(snapshot_every)
    problems = []
    if got["last_seq"] != expect["last_seq"]:
        problems.append(
            f"last_seq: got {got['last_seq']}, want {expect['last_seq']}")
    if got["final_text"] != expect["final_text"]:
        problems.append(
            f"final_text drift: got {got['final_text']!r}, "
            f"want {expect['final_text']!r}")
    for seq, want in expect["snapshots"].items():
        have = got["snapshots"].get(seq)
        if have != want:
            problems.append(f"fingerprint @seq {seq}: {have} != {want}")
    return problems


def replay_through_applier(doc_dir: str, applier=None) -> str:
    """Feed the recorded doc's text-channel stream through a
    TpuDocumentApplier (the scribe-replay role, BASELINE config 5) and
    return the device-side final text."""
    from ..service.tpu_applier import TpuDocumentApplier

    service = FileDocumentService.from_dir(doc_dir)
    msgs = service.connect_to_delta_storage().get_deltas(0, 10**9)
    if applier is None:
        applier = TpuDocumentApplier(max_docs=4, max_slots=512,
                                     ops_per_dispatch=16)
    applier.set_replay_source(lambda t, d: [])
    pairs = []
    for m in msgs:
        if m.type != MessageType.OPERATION:
            continue
        env = m.contents
        if not isinstance(env, dict) or env.get("kind") != "chanop":
            continue
        if env["address"] != DS_ID:
            continue
        inner = env["contents"]
        if inner.get("address") != TEXT_CHANNEL or "attach" in inner:
            continue
        pairs.append((m, inner["contents"]))
    applier.ingest_batch("replay", os.path.basename(doc_dir), pairs)
    applier.finalize()
    return applier.get_text("replay", os.path.basename(doc_dir))
