"""Perspectives: the (refSeq, clientId) views that make the CRDT tick.

Every operation is interpreted in the view its author had when creating it:
segments inserted after the author's refSeq by OTHER clients are invisible
to it; the author's own prior (even unacked) segments are visible. This is
the rule the reference encodes in merge-tree length queries
(packages/dds/merge-tree/src/partialLengths.ts:62,432 and
mergeTree.ts leaf visibility) — here it is two pure integer predicates,
shared verbatim in spirit with the int32 tensor kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..protocol.messages import UNASSIGNED_SEQ


@dataclass(frozen=True)
class Perspective:
    """(ref_seq, client) view; ``local_seq`` optionally bounds which of the
    client's OWN pending ops have applied — the "rebase view" used when
    regenerating op ``local_seq`` after reconnect (only pending inserts with
    local seq ≤ bound and pending removes with local seq < bound count;
    ref: client.ts:675 findReconnectionPostition's localSeq walks)."""

    ref_seq: int
    client: int
    local_seq: Optional[int] = None

    def sees_insert(self, ins_seq: int, ins_client: int) -> bool:
        """Is a segment's insert visible in this view?

        Own inserts are always visible (a client's later ops may reference
        its own still-unacked content); others' only once sequenced at or
        below ref_seq.
        """
        return ins_client == self.client or ins_seq <= self.ref_seq

    def sees_removed(self, rem_seq: int, rem_client: int) -> bool:
        """Is a segment's remove visible (i.e. the segment gone) in this view?

        ``rem_seq`` uses 0 for "never removed" handled by caller; here a
        remove counts if it is our own or sequenced at or below ref_seq.
        """
        return rem_client == self.client or rem_seq <= self.ref_seq


# The local client's current view: refSeq = UNASSIGNED_SEQ makes every
# assigned stamp (and the client's own pending UNASSIGNED stamps) visible.
# Construct per-client as Perspective(UNASSIGNED_SEQ, my_client_id).
def LOCAL_CLIENT_VIEW(client: int) -> Perspective:
    return Perspective(UNASSIGNED_SEQ, client)
