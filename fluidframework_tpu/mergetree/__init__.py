"""The merge tree: the core sequence CRDT.

Scalar reference implementation ("the oracle") of the merge logic the TPU
kernels in :mod:`fluidframework_tpu.ops` vectorize. Semantics match the
reference's packages/dds/merge-tree (SURVEY.md §2.1): segments stamped with
``(clientId, seq)`` insert/remove pairs, position resolution against a
``(refSeq, clientId)`` perspective, optimistic local apply with ack
stamping, reconnect rebase, collab-window compaction (zamboni).

Deliberate design departures from the reference (TPU-first):

- Flat ordered segment list (structure-of-arrays friendly), not an 8-ary
  B-tree: the kernel's masked prefix-sum over contiguous arrays replaces
  the tree's PartialSequenceLengths cache (ref mergeTree.ts:333,
  partialLengths.ts:62).
- All stamps are plain ints with ``UNASSIGNED_SEQ = 2**31-1`` so every
  visibility rule is a branch-free integer comparison — identical code path
  in the oracle and the int32 tensor kernel.
"""

from .ops import (
    MergeTreeDeltaType,
    InsertOp,
    RemoveOp,
    AnnotateOp,
    GroupOp,
    MergeOp,
    op_from_wire,
    op_to_wire,
)
from .segments import Segment, NO_CLIENT
from .perspective import Perspective, LOCAL_CLIENT_VIEW
from .mergetree import MergeTree
from .client import MergeTreeClient
from .references import LocalReference, ReferenceType

__all__ = [
    "MergeTreeDeltaType",
    "InsertOp",
    "RemoveOp",
    "AnnotateOp",
    "GroupOp",
    "MergeOp",
    "op_from_wire",
    "op_to_wire",
    "Segment",
    "NO_CLIENT",
    "Perspective",
    "LOCAL_CLIENT_VIEW",
    "MergeTree",
    "MergeTreeClient",
    "LocalReference",
    "ReferenceType",
]
