"""Merge-tree operation model.

Ref: packages/dds/merge-tree/src/ops.ts:34-110 (MergeTreeDeltaType,
IMergeTreeInsertMsg/RemoveMsg/AnnotateMsg/GroupMsg) and opBuilder.ts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Optional, Union


class MergeTreeDeltaType(IntEnum):
    INSERT = 0
    REMOVE = 1
    ANNOTATE = 2
    GROUP = 3


@dataclass
class InsertOp:
    pos: int
    text: Optional[str] = None  # text payload, or None for a marker
    marker: Optional[dict] = None  # marker payload: {"refType": int, ...}
    props: Optional[dict] = None

    type: MergeTreeDeltaType = MergeTreeDeltaType.INSERT


@dataclass
class RemoveOp:
    start: int
    end: int  # exclusive

    type: MergeTreeDeltaType = MergeTreeDeltaType.REMOVE


@dataclass
class AnnotateOp:
    start: int
    end: int  # exclusive
    props: dict = field(default_factory=dict)

    type: MergeTreeDeltaType = MergeTreeDeltaType.ANNOTATE


@dataclass
class GroupOp:
    ops: list["MergeOp"] = field(default_factory=list)

    type: MergeTreeDeltaType = MergeTreeDeltaType.GROUP


MergeOp = Union[InsertOp, RemoveOp, AnnotateOp, GroupOp]


def op_to_wire(op: MergeOp) -> dict:
    """JSON-serializable wire form (used in DocumentMessage.contents)."""
    if isinstance(op, InsertOp):
        d = {"type": int(op.type), "pos": op.pos}
        if op.text is not None:
            d["text"] = op.text
        if op.marker is not None:
            d["marker"] = op.marker
        if op.props:
            d["props"] = op.props
        return d
    if isinstance(op, RemoveOp):
        return {"type": int(op.type), "start": op.start, "end": op.end}
    if isinstance(op, AnnotateOp):
        return {"type": int(op.type), "start": op.start, "end": op.end, "props": op.props}
    if isinstance(op, GroupOp):
        return {"type": int(op.type), "ops": [op_to_wire(o) for o in op.ops]}
    raise TypeError(f"not a merge-tree op: {op!r}")


def op_from_wire(d: dict) -> MergeOp:
    t = MergeTreeDeltaType(d["type"])
    if t == MergeTreeDeltaType.INSERT:
        return InsertOp(
            pos=d["pos"], text=d.get("text"), marker=d.get("marker"), props=d.get("props")
        )
    if t == MergeTreeDeltaType.REMOVE:
        return RemoveOp(start=d["start"], end=d["end"])
    if t == MergeTreeDeltaType.ANNOTATE:
        return AnnotateOp(start=d["start"], end=d["end"], props=d["props"])
    if t == MergeTreeDeltaType.GROUP:
        return GroupOp(ops=[op_from_wire(o) for o in d["ops"]])
    raise ValueError(f"unknown merge-tree op type {t}")
