"""MergeTreeClient: the op protocol around a MergeTree replica.

Ref: packages/dds/merge-tree/src/client.ts:43 — local op creation
(insertSegmentLocal :202, removeRangeLocal :189, annotateRangeLocal :164),
remote apply (applyMsg :797 → applyRemoteOp :768), own-op ack
(ackPendingSegment mergeTree.ts:1926), reconnect rebase
(regeneratePendingOp client.ts:855).

Client ids: the wire uses string client ids; each replica interns them to
small ints for stamp comparisons (and for the int32 tensor path). The
mapping is replica-local — convergence only needs distinctness, since the
tie-break orders concurrent inserts by seq alone.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
    UNASSIGNED_SEQ,
)
from .mergetree import MergeTree
from .ops import (
    AnnotateOp,
    GroupOp,
    InsertOp,
    MergeOp,
    MergeTreeDeltaType,
    RemoveOp,
    op_from_wire,
)
from .perspective import Perspective
from .references import LocalReference, ReferenceType
from .segments import Segment


@dataclass
class SegmentGroup:
    """The segments touched by ONE in-flight wire op.

    The ack path stamps exactly this group — never "all segments with the
    same local seq", because reconnect regeneration can fragment one local
    op into several wire ops, each sequenced separately
    (ref: SegmentGroup / segmentGroups in mergeTree.ts).
    """

    segments: list[Segment] = field(default_factory=list)

    def attach(self, seg: Segment) -> None:
        self.segments.append(seg)
        seg.pending_groups.append(self)

    def detach_all(self) -> None:
        for seg in self.segments:
            if self in seg.pending_groups:
                seg.pending_groups.remove(self)
        self.segments = []


@dataclass
class PendingOp:
    local_seq: int
    op: MergeOp
    group: SegmentGroup = field(default_factory=SegmentGroup)


class MergeTreeClient:
    def __init__(self, client_id: str, blocked: bool = True):
        self.client_id = client_id
        self._ids: dict[str, int] = {client_id: 0}
        self._my_ids: set[str] = {client_id}
        # production replicas use the blocked tree (O(1) window advance,
        # block-skipping walks — mergetree/blocked.py); the flat tree
        # stays available as the semantics oracle the fuzz suites
        # compare against (and the kernel-parity reference)
        if blocked:
            from .blocked import BlockedMergeTree

            self.tree = BlockedMergeTree()
        else:
            self.tree = MergeTree()
        self.local_seq = 0
        self.pending: deque[PendingOp] = deque()

    def update_client_id(self, new_id: str) -> None:
        """Adopt the client id of a new connection after reconnect.

        All of this replica's identities (old and new) intern to 0, so
        pending-segment stamps and the local view stay coherent; ops from a
        PREVIOUS connection that were sequenced before our leave still ack
        as our own (ref: Client.startOrUpdateCollaboration updates
        longClientId, client.ts).
        """
        self.client_id = new_id
        self._my_ids.add(new_id)
        self._ids[new_id] = 0

    def is_own_message(self, client_id: Optional[str]) -> bool:
        return client_id in self._my_ids

    # -- id interning ----------------------------------------------------
    # interned id for server/system-authored stamps (never a local client)
    SYSTEM_CLIENT = 1_000_000

    def intern(self, client_id: Optional[str]) -> int:
        if client_id is None:
            return self.SYSTEM_CLIENT
        if client_id not in self._ids:
            self._ids[client_id] = len(self._ids)
        return self._ids[client_id]

    @property
    def my_id(self) -> int:
        return 0

    def local_view(self) -> Perspective:
        return Perspective(UNASSIGNED_SEQ, self.my_id)

    # -- queries ---------------------------------------------------------
    def get_text(self) -> str:
        return self.tree.get_text(self.local_view())

    def get_length(self) -> int:
        return self.tree.visible_length(self.local_view())

    def get_properties_at(self, pos: int) -> dict:
        """Properties of the visible character at ``pos`` in the local view
        (ref: getPropertiesAtPosition, merge-tree client.ts)."""
        return self.tree.properties_at(pos, self.local_view())

    # -- local ops (optimistic apply; caller submits returned op) --------
    def insert_text_local(self, pos: int, text: str, props: Optional[dict] = None) -> InsertOp:
        self.local_seq += 1
        seg = Segment(
            text=text,
            props=dict(props) if props else None,
            ins_seq=UNASSIGNED_SEQ,
            ins_client=self.my_id,
            ins_local_seq=self.local_seq,
        )
        self.tree.insert_segment(pos, seg, self.local_view())
        op = InsertOp(pos=pos, text=text, props=dict(props) if props else None)
        entry = PendingOp(self.local_seq, op)
        entry.group.attach(seg)
        self.pending.append(entry)
        return op

    def insert_marker_local(self, pos: int, marker: dict, props: Optional[dict] = None) -> InsertOp:
        self.local_seq += 1
        seg = Segment(
            marker=dict(marker),
            props=dict(props) if props else None,
            ins_seq=UNASSIGNED_SEQ,
            ins_client=self.my_id,
            ins_local_seq=self.local_seq,
        )
        self.tree.insert_segment(pos, seg, self.local_view())
        op = InsertOp(pos=pos, marker=dict(marker), props=dict(props) if props else None)
        entry = PendingOp(self.local_seq, op)
        entry.group.attach(seg)
        self.pending.append(entry)
        return op

    def remove_range_local(self, start: int, end: int) -> RemoveOp:
        self.local_seq += 1
        affected = self.tree.mark_removed(
            start,
            end,
            self.local_view(),
            rem_seq=UNASSIGNED_SEQ,
            rem_client=self.my_id,
            rem_local_seq=self.local_seq,
        )
        op = RemoveOp(start=start, end=end)
        entry = PendingOp(self.local_seq, op)
        for seg in affected:
            entry.group.attach(seg)
        self.pending.append(entry)
        return op

    def annotate_range_local(self, start: int, end: int, props: dict) -> AnnotateOp:
        self.local_seq += 1
        affected = self.tree.annotate_range(
            start, end, props, self.local_view(), local_seq=self.local_seq
        )
        op = AnnotateOp(start=start, end=end, props=dict(props))
        entry = PendingOp(self.local_seq, op)
        for seg in affected:
            entry.group.attach(seg)
        self.pending.append(entry)
        return op

    # -- sequenced message application ----------------------------------
    def apply_msg(
        self, msg: SequencedDocumentMessage, local: Optional[bool] = None
    ) -> None:
        """Apply one sequenced merge-tree message (op contents on the wire).

        Dispatch: our own message → ack the oldest pending op (server
        sequences each client FIFO); otherwise apply remotely at the
        author's perspective. Always advances (seq, minSeq) and compacts.

        ``local`` is the authoritative own-op flag when the caller (the
        container, which tracks every id it has held) knows it; standalone
        use falls back to the replica's own id registry.
        """
        if msg.type == MessageType.OPERATION:
            contents = msg.contents
            op = op_from_wire(contents) if isinstance(contents, dict) else contents
            if self.is_own_message(msg.client_id) if local is None else local:
                self._ack(op, msg.sequence_number)
            else:
                perspective = Perspective(
                    msg.reference_sequence_number, self.intern(msg.client_id)
                )
                self._apply_remote(op, msg.sequence_number, perspective)
        self.tree.current_seq = max(self.tree.current_seq, msg.sequence_number)
        self.tree.update_min_seq(msg.minimum_sequence_number)

    def _apply_remote(self, op: MergeOp, seq: int, perspective: Perspective) -> None:
        if isinstance(op, GroupOp):
            for sub in op.ops:
                self._apply_remote(sub, seq, perspective)
            return
        if isinstance(op, InsertOp):
            seg = Segment(
                text=op.text or "",
                marker=dict(op.marker) if op.marker is not None else None,
                props=dict(op.props) if op.props else None,
                ins_seq=seq,
                ins_client=perspective.client,
            )
            self.tree.insert_segment(op.pos, seg, perspective)
        elif isinstance(op, RemoveOp):
            self.tree.mark_removed(
                op.start, op.end, perspective, rem_seq=seq, rem_client=perspective.client
            )
        elif isinstance(op, AnnotateOp):
            self.tree.annotate_range(op.start, op.end, op.props, perspective)
        else:
            raise TypeError(f"unknown op {op!r}")

    def _ack(self, op: MergeOp, seq: int) -> None:
        assert self.pending, "ack with no pending op"
        entry = self.pending.popleft()
        segments = list(entry.group.segments)
        if isinstance(entry.op, InsertOp):
            for seg in segments:
                seg.ins_seq = seq
                seg.ins_local_seq = None
        elif isinstance(entry.op, RemoveOp):
            for seg in segments:
                if seg.rem_seq == UNASSIGNED_SEQ:
                    seg.rem_seq = seq
                # else: an assigned remote remove overlapped ours and won
                seg.rem_local_seq = None
        elif isinstance(entry.op, AnnotateOp):
            for seg in segments:
                for key in entry.op.props:
                    if seg.pending_props.get(key) == entry.local_seq:
                        del seg.pending_props[key]
        else:
            raise AssertionError("group ops are flattened before submit")
        entry.group.detach_all()

    # -- reconnect rebase ------------------------------------------------
    def regenerate_pending_ops(self) -> list[MergeOp]:
        """Rebuild pending ops against CURRENT state for resubmission.

        After reconnect, old pending ops reference stale positions; the
        pending segments themselves know where they live now. Pending
        inserts may have been split — regenerate one insert per surviving
        part; removes/annotates re-derive their ranges from the stamped
        segments (ref: regeneratePendingOp client.ts:855,
        findReconnectionPostition :675).
        """
        # Renumber every pending op with a fresh, unique local_seq first
        # (continuing the counter upward, so new values never collide with
        # old ones). A previous regeneration may have fragmented one op into
        # several wire ops SHARING a local_seq — but those fragments apply
        # sequentially on remotes, so the bounded-perspective ordering below
        # ("op L sees pending removes < L") needs them strictly ordered.
        for entry in self.pending:
            old_ls = entry.local_seq
            self.local_seq += 1
            new_ls = self.local_seq
            if isinstance(entry.op, InsertOp):
                for seg in entry.group.segments:
                    seg.ins_local_seq = new_ls
            elif isinstance(entry.op, RemoveOp):
                for seg in entry.group.segments:
                    seg.rem_local_seq = new_ls
            elif isinstance(entry.op, AnnotateOp):
                for seg in entry.group.segments:
                    for key in entry.op.props:
                        if seg.pending_props.get(key) == old_ls:
                            seg.pending_props[key] = new_ls
            entry.local_seq = new_ls

        new_ops: list[MergeOp] = []
        new_pending: deque[PendingOp] = deque()
        for entry in self.pending:
            ls = entry.local_seq
            rebase_view = Perspective(self.tree.current_seq, self.my_id, local_seq=ls)
            members = set(map(id, entry.group.segments))
            entry.group.detach_all()
            if isinstance(entry.op, InsertOp):
                # tree order, via group membership
                parts = [s for s in self.tree.segments if id(s) in members]
                for part in parts:
                    if part.rem_seq is not None and part.rem_seq != UNASSIGNED_SEQ:
                        # inserted-then-removed at an assigned seq: the op is
                        # moot; settle the stamp so the segment isn't
                        # pending forever (droppable once minSeq passes)
                        part.ins_seq = part.rem_seq
                        part.ins_local_seq = None
                        continue
                    # CRITICAL (found by the reconnect farm): the author
                    # must RE-PLACE the pending segment with the exact walk
                    # remotes will use for the regenerated op — its old
                    # physical spot may sit inside a tombstone run that the
                    # remote walk stops in front of, and a third client can
                    # later insert between the two placements.
                    pos = self.tree.position_of_segment(part, rebase_view)
                    self.tree.remove_segment(part)
                    self.tree.insert_segment(pos, part, rebase_view)
                    op = InsertOp(
                        pos=pos,
                        text=None if part.is_marker else part.text,
                        marker=dict(part.marker) if part.is_marker else None,
                        props=dict(part.props) if part.props else None,
                    )
                    new_entry = PendingOp(ls, op)
                    new_entry.group.attach(part)
                    new_ops.append(op)
                    new_pending.append(new_entry)
            elif isinstance(entry.op, RemoveOp):
                for start, end, segs in self._rebase_ranges(
                    rebase_view,
                    lambda s: id(s) in members and s.rem_seq == UNASSIGNED_SEQ,
                    exclude_matched=True,
                ):
                    op = RemoveOp(start=start, end=end)
                    new_entry = PendingOp(ls, op)
                    for seg in segs:
                        new_entry.group.attach(seg)
                    new_ops.append(op)
                    new_pending.append(new_entry)
            elif isinstance(entry.op, AnnotateOp):
                keys = set(entry.op.props.keys())
                for start, end, segs in self._rebase_ranges(
                    rebase_view,
                    lambda s: id(s) in members
                    and any(s.pending_props.get(k) == ls for k in keys),
                ):
                    op = AnnotateOp(start=start, end=end, props=dict(entry.op.props))
                    new_entry = PendingOp(ls, op)
                    for seg in segs:
                        new_entry.group.attach(seg)
                    new_ops.append(op)
                    new_pending.append(new_entry)
        self.pending = new_pending
        return new_ops

    def _rebase_ranges(
        self, rebase_view: Perspective, pred, exclude_matched: bool = False
    ) -> list[tuple[int, int, list[Segment]]]:
        """(start, end, segments) ranges (in ``rebase_view``) of segments
        matching ``pred``, merging adjacent runs.

        ``exclude_matched``: for REMOVE regeneration. Each range becomes a
        separate wire op, and the remote applies them sequentially with our
        earlier removes perspective-visible — so once a segment is emitted
        in a range it must stop counting toward later ranges' positions.
        (Annotates don't change visibility, so they keep full lengths.)
        """
        ranges: list[tuple[int, int, list[Segment]]] = []
        pos = 0
        for seg in self.tree.segments:
            vl = seg.visible_length(rebase_view)
            if vl and pred(seg):
                # A range may only grow while members are contiguous in the
                # view — any interposed visible non-member (e.g. a concurrent
                # insert that landed inside the original range) must break
                # it, or the regenerated op would swallow content the
                # original op never touched. Under exclude_matched, members
                # do not advance ``pos``, so contiguity means pos == start;
                # without it, pos == current end.
                extend = bool(ranges) and (
                    pos == ranges[-1][0] if exclude_matched else pos == ranges[-1][1]
                )
                if extend:
                    start, end, segs = ranges[-1]
                    segs.append(seg)
                    ranges[-1] = (start, end + vl, segs)
                else:
                    ranges.append((pos, pos + vl, [seg]))
                if exclude_matched:
                    continue  # emitted: invisible to subsequent ranges
            pos += vl
        return ranges

    # -- local references -------------------------------------------------
    def create_reference(
        self, pos: int, ref_type: ReferenceType = ReferenceType.SLIDE_ON_REMOVE
    ) -> LocalReference:
        return self.create_reference_at(pos, self.local_view(), ref_type)

    def create_reference_at(
        self,
        pos: int,
        perspective: Perspective,
        ref_type: ReferenceType = ReferenceType.SLIDE_ON_REMOVE,
    ) -> LocalReference:
        """Create a reference interpreting ``pos`` in an arbitrary view —
        remote interval ops anchor at the AUTHOR's (refSeq, client)
        perspective (ref: intervalCollection op apply, sequence pkg).
        Boundary positions attach to the first perspective-visible
        segment at or after the resolution point."""
        seg, offset = self.tree.visible_segment_at(pos, perspective)
        if seg is None:
            ref = LocalReference(None, 0, ref_type)
        else:
            ref = LocalReference(seg, offset, ref_type)
            seg.local_refs.append(ref)
        return ref

    def reference_position(self, ref: LocalReference) -> int:
        return self.tree.local_reference_position(ref, self.local_view())

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Portable snapshot: interned int client ids are replica-local, so
        stamps inside the collab window are translated back to wire string
        ids before serialization (ref: SnapshotV1 stores original client ids,
        snapshotV1.ts:87)."""
        snap = self.tree.snapshot()
        reverse = {v: k for k, v in self._ids.items()}
        for d in snap["segments"]:
            if "insClient" in d:
                d["insClient"] = reverse.get(d["insClient"])
            if "remClient" in d:
                d["remClient"] = reverse.get(d["remClient"])
            if "remClients" in d:
                d["remClients"] = [reverse.get(c) for c in d["remClients"]]
        return snap

    @classmethod
    def load(cls, client_id: str, snap: dict,
             blocked: bool = True) -> "MergeTreeClient":
        c = cls(client_id, blocked=blocked)
        c.tree = type(c.tree).load(
            {
                **snap,
                "segments": [
                    {
                        **d,
                        **(
                            {"insClient": c.intern(d["insClient"])}
                            if "insClient" in d
                            else {}
                        ),
                        **(
                            {"remClient": c.intern(d["remClient"])}
                            if "remClient" in d
                            else {}
                        ),
                        **(
                            {"remClients": [c.intern(x) for x in d["remClients"]]}
                            if "remClients" in d
                            else {}
                        ),
                    }
                    for d in snap["segments"]
                ],
            }
        )
        return c
