"""MergeTree: ordered segment store with perspective-correct operations.

Scalar oracle for the TPU kernels. A flat ordered list stands in for the
reference's 8-ary B-tree (mergeTree.ts:333): every query is an O(n) scan
here; the kernel version does the same math as masked prefix sums on int32
arrays (see fluidframework_tpu.ops). Semantic parity targets, with
reference anchors:

- position resolution at (refSeq, clientId)      — partialLengths.ts:432
- concurrent-insert tie-break                    — mergeTree.ts:2281 (breakTie)
- remove/annotate over perspective-visible spans — mergeTree.ts:2640,2598
- own-op ack stamping                            — mergeTree.ts:1926
- collab-window compaction (zamboni)             — mergeTree.ts:1455

Tie-break rule (convergent; see tests/test_mergetree_farm.py): among
segments inserted concurrently at the same resolved position, HIGHER
sequence number sorts EARLIER; a client's own unacked segments
(ins_seq = UNASSIGNED_SEQ) sort earliest of all. Both sides of every race
order segments identically because the rule depends only on stamps.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..protocol.messages import UNASSIGNED_SEQ, UNIVERSAL_SEQ
from .perspective import Perspective
from .references import LocalReference, ReferenceType
from .segments import NO_CLIENT, Segment


class MergeTree:
    def __init__(self):
        self.segments: list[Segment] = []
        self.min_seq = 0
        self.current_seq = 0

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def visible_length(self, perspective: Perspective) -> int:
        return sum(s.visible_length(perspective) for s in self.segments)

    def get_text(self, perspective: Perspective) -> str:
        out = []
        for s in self.segments:
            if s.visible_in(perspective) and not s.is_marker:
                out.append(s.text)
        return "".join(out)

    def resolve(self, pos: int, perspective: Perspective) -> tuple[int, int]:
        """Map a perspective position to (segment index, in-segment offset).

        Lands on the EARLIEST boundary when ``pos`` falls between segments
        (i.e. before any run of perspective-invisible segments); the insert
        tie-break then walks forward from there. offset > 0 means strictly
        inside segment ``index``.
        """
        if pos < 0:
            raise IndexError(f"negative position {pos}")
        remaining = pos
        for i, seg in enumerate(self.segments):
            if remaining == 0:
                return (i, 0)
            vl = seg.visible_length(perspective)
            if remaining < vl:
                return (i, remaining)
            remaining -= vl
        if remaining == 0:
            return (len(self.segments), 0)
        raise IndexError(
            f"position {pos} out of range (len {self.visible_length(perspective)})"
        )

    def visible_segment_at(
        self, pos: int, perspective: Perspective
    ) -> tuple[Optional[Segment], int]:
        """The segment holding the visible character AT ``pos`` (walking
        past invisible segments on a boundary), with the in-segment
        offset; (None, 0) when pos is the end of the document. The
        shared resolve-then-walk step of reference creation and item
        lookup — tree-structure-agnostic, unlike raw index math."""
        idx, offset = self.resolve(pos, perspective)
        segs = self.segments
        if offset == 0:
            while idx < len(segs) and \
                    segs[idx].visible_length(perspective) == 0:
                idx += 1
        if idx >= len(segs):
            return None, 0
        return segs[idx], offset

    def properties_at(self, pos: int, perspective: Perspective) -> dict:
        """Properties of the visible character at ``pos``."""
        seg, _ = self.visible_segment_at(pos, perspective)
        if seg is None:
            raise IndexError(pos)
        return dict(seg.props)

    def remove_segment(self, seg: Segment) -> None:
        """Physically remove a segment (reconnect re-placement path)."""
        self.segments.remove(seg)

    def position_of_segment(self, target: Segment, perspective: Perspective) -> int:
        """Perspective position of the first character of ``target``."""
        pos = 0
        for seg in self.segments:
            if seg is target:
                return pos
            pos += seg.visible_length(perspective)
        raise ValueError("segment not in tree")

    def local_reference_position(self, ref: LocalReference, perspective: Perspective) -> int:
        if ref.segment is None:
            return 0
        base = self.position_of_segment(ref.segment, perspective)
        if ref.segment.visible_in(perspective):
            return base + ref.offset
        return base

    # ------------------------------------------------------------------
    # mutation: insert
    # ------------------------------------------------------------------
    def insert_segment(
        self,
        pos: int,
        segment: Segment,
        perspective: Perspective,
    ) -> Segment:
        """Insert ``segment`` at perspective position ``pos``.

        ``segment`` arrives pre-stamped (UNASSIGNED for local ops, the
        assigned seq for remote ops). Implements the earliest-boundary +
        higher-seq-leftward tie-break described in the module docstring
        (ref: insertingWalk/breakTie mergeTree.ts:2378,2281).
        """
        idx, offset = self.resolve(pos, perspective)
        if offset > 0:
            tail = self.segments[idx].split(offset)
            self.segments.insert(idx + 1, tail)
            idx += 1
        else:
            # effective insert key: pending segments compare by
            # (UNASSIGNED, local_seq) so re-placed reconnect inserts order
            # among their own in-flight siblings exactly as their eventual
            # seqs will
            new_key = (segment.ins_seq, segment.ins_local_seq or 0)
            bound = perspective.local_seq
            while idx < len(self.segments):
                s = self.segments[idx]
                ins_seen = (
                    s.ins_client == perspective.client
                    and not (
                        bound is not None
                        and s.ins_local_seq is not None
                        and s.ins_local_seq > bound
                    )
                ) or s.ins_seq <= perspective.ref_seq
                if ins_seen:
                    break  # author saw it: position is relative to it, stay left
                if (s.ins_seq, s.ins_local_seq or 0) <= new_key:
                    break  # concurrent but earlier-sequenced: we sort before it
                idx += 1
        self.segments.insert(idx, segment)
        return segment

    # ------------------------------------------------------------------
    # mutation: remove
    # ------------------------------------------------------------------
    def mark_removed(
        self,
        start: int,
        end: int,
        perspective: Perspective,
        rem_seq: int,
        rem_client: int,
        rem_local_seq: Optional[int] = None,
    ) -> list[Segment]:
        """Mark [start, end) removed in the given perspective.

        Only perspective-visible segments are touched: content inserted
        concurrently inside the range survives (the remover never saw it).
        Overlapping removes keep the earliest assigned stamp; a pending
        local stamp is superseded by any assigned one but retains
        ``rem_local_seq`` so the eventual ack can settle the pending op
        (ref: overlapping-remove bookkeeping, mergeTree.ts:2640).
        """
        if end <= start:
            return []
        affected: list[Segment] = []
        pos = 0
        i = 0
        while i < len(self.segments) and pos < end:
            seg = self.segments[i]
            vl = seg.visible_length(perspective)
            if vl > 0:
                seg_start, seg_end = pos, pos + vl
                if seg_end > start:  # overlaps [start, end)?
                    if seg_start < start:
                        tail = seg.split(start - seg_start)
                        self.segments.insert(i + 1, tail)
                        pos = start
                        i += 1
                        continue
                    if seg_end > end:
                        tail = seg.split(end - seg_start)
                        self.segments.insert(i + 1, tail)
                        vl = end - seg_start
                    # fully covered: stamp. Every remover is recorded in
                    # rem_clients; the primary (rem_seq, rem_client) is the
                    # EARLIEST assigned remove, since ops apply in seq order
                    # an assigned stamp only ever replaces a pending one.
                    seg.rem_clients.add(rem_client)
                    if seg.rem_seq is None:
                        seg.rem_seq = rem_seq
                        seg.rem_client = rem_client
                        seg.rem_local_seq = rem_local_seq
                    elif seg.rem_seq == UNASSIGNED_SEQ and rem_seq != UNASSIGNED_SEQ:
                        # our pending remove raced an assigned remote remove:
                        # the assigned (earlier) stamp wins; rem_local_seq
                        # stays so our eventual ack can settle the pending op
                        seg.rem_seq = rem_seq
                        seg.rem_client = rem_client
                    affected.append(seg)
                pos = seg_end
            i += 1
        return affected

    # ------------------------------------------------------------------
    # mutation: annotate
    # ------------------------------------------------------------------
    def annotate_range(
        self,
        start: int,
        end: int,
        props: dict,
        perspective: Perspective,
        local_seq: Optional[int] = None,
    ) -> list[Segment]:
        """Set properties on [start, end).

        Last-writer-wins per key by sequence number. A pending local
        annotate shadows remote writes to the same key (its eventual seq is
        necessarily higher); ``None`` values delete keys
        (ref: annotateRange mergeTree.ts:2598, segmentPropertiesManager.ts).
        """
        if end <= start:
            return []
        affected: list[Segment] = []
        pos = 0
        i = 0
        while i < len(self.segments) and pos < end:
            seg = self.segments[i]
            vl = seg.visible_length(perspective)
            if vl > 0:
                seg_start, seg_end = pos, pos + vl
                if seg_end > start:
                    if seg_start < start:
                        tail = seg.split(start - seg_start)
                        self.segments.insert(i + 1, tail)
                        pos = start
                        i += 1
                        continue
                    if seg_end > end:
                        tail = seg.split(end - seg_start)
                        self.segments.insert(i + 1, tail)
                    self._apply_props(seg, props, local_seq)
                    affected.append(seg)
                pos = min(seg_end, end)
            i += 1
        return affected

    @staticmethod
    def _apply_props(seg: Segment, props: dict, local_seq: Optional[int]) -> None:
        for key, value in props.items():
            if local_seq is not None:  # local pending annotate
                seg.pending_props[key] = local_seq
            elif key in seg.pending_props:
                continue  # our pending write wins over this remote one
            if value is None:
                seg.props.pop(key, None)
            else:
                seg.props[key] = value

    # ------------------------------------------------------------------
    # collab window / zamboni
    # ------------------------------------------------------------------
    def update_min_seq(self, min_seq: int) -> None:
        """Advance the collaboration-window floor and compact.

        Every connected client has processed everything ≤ min_seq, so no
        future perspective can have ref_seq < min_seq: segments removed at
        or below it are invisible forever (drop them), and adjacent
        old clean text runs can merge (ref: zamboni mergeTree.ts:1455).
        """
        if min_seq <= self.min_seq:
            return
        self.min_seq = min_seq
        kept: list[Segment] = []
        for seg in self.segments:
            droppable = (
                seg.rem_seq is not None
                and seg.rem_seq != UNASSIGNED_SEQ
                and seg.rem_seq <= min_seq
                and seg.rem_local_seq is None
            )
            if droppable:
                self._slide_refs_off(seg, kept)
            else:
                prev = kept[-1] if kept else None
                if (
                    prev is not None
                    and prev.ins_seq <= min_seq
                    and seg.ins_seq <= min_seq
                    and prev.can_append(seg)
                ):
                    prev.append(seg)
                else:
                    kept.append(seg)
        # refs that slid onto a later segment: nothing more to do — they
        # were re-attached inside _slide_refs_off
        self.segments = kept

    def _slide_refs_off(self, dying: Segment, kept: list[Segment]) -> None:
        """SlideOnRemove: move refs from a dropped segment to a survivor."""
        if not dying.local_refs:
            return
        # prefer the previous kept segment's end; else detach to doc start
        target = kept[-1] if kept else None
        for ref in dying.local_refs:
            if ref.ref_type & ReferenceType.STAY_ON_REMOVE:
                ref.segment = None
                ref.offset = 0
                continue
            if target is not None:
                ref.segment = target
                ref.offset = target.length
                target.local_refs.append(ref)
            else:
                ref.segment = None
                ref.offset = 0
        dying.local_refs = []

    # ------------------------------------------------------------------
    # snapshot / load
    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """Serializable state at current (seq, min_seq).

        Requires no pending local state (the summarizer only runs on a
        fully-acked replica; ref: SnapshotV1 snapshotV1.ts:35). Stamps at or
        below min_seq normalize to UNIVERSAL_SEQ so loaders treat them as
        base content; younger stamps are preserved for in-window perspective
        checks by catch-up ops.

        The output is CANONICAL: adjacent text runs whose serialized
        stamps are identical coalesce at write time, so the bytes do not
        depend on the in-memory segmentation (flat eager-zamboni vs
        blocked amortized-zamboni) — the snapshot-regression fingerprints
        then pin semantics, not representation.
        """
        segs: list[dict] = []
        for seg in self.segments:
            if seg.is_pending():
                raise RuntimeError("cannot snapshot with pending local ops")
            if seg.rem_seq is not None and seg.rem_seq <= self.min_seq:
                continue  # invisible forever
            d: dict = {"props": seg.props} if seg.props else {}
            if seg.is_marker:
                d["marker"] = seg.marker
            else:
                d["text"] = seg.text
            if seg.ins_seq > self.min_seq:
                d["insSeq"] = seg.ins_seq
                d["insClient"] = seg.ins_client
            if seg.rem_seq is not None:
                d["remSeq"] = seg.rem_seq
                d["remClient"] = seg.rem_client
                if len(seg.rem_clients) > 1:
                    d["remClients"] = sorted(seg.rem_clients)
            prev = segs[-1] if segs else None
            if (prev is not None and "text" in prev and "text" in d
                    and {k: v for k, v in prev.items() if k != "text"}
                    == {k: v for k, v in d.items() if k != "text"}):
                prev["text"] += d["text"]
            else:
                segs.append(d)
        return {"minSeq": self.min_seq, "seq": self.current_seq, "segments": segs}

    @classmethod
    def load(cls, snap: dict) -> "MergeTree":
        tree = cls()
        tree.min_seq = snap["minSeq"]
        tree.current_seq = snap["seq"]
        for d in snap["segments"]:
            seg = Segment(
                text=d.get("text", ""),
                marker=d.get("marker"),
                props=dict(d.get("props", {})),
                ins_seq=d.get("insSeq", UNIVERSAL_SEQ),
                ins_client=d.get("insClient", NO_CLIENT),
            )
            if "remSeq" in d:
                seg.rem_seq = d["remSeq"]
                seg.rem_client = d["remClient"]
                seg.rem_clients = set(d.get("remClients", [d["remClient"]]))
            tree.segments.append(seg)
        return tree
