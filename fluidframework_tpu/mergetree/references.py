"""Local references: stable cursors into the merge tree.

Ref: packages/dds/merge-tree/src/localReference.ts and ops.ts:6
(ReferenceType). A reference pins (segment, offset); when its segment is
removed/compacted it slides to the nearest surviving segment (SlideOnRemove
semantics). Interval collections build on these.
"""

from __future__ import annotations

from enum import IntFlag
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from .segments import Segment


class ReferenceType(IntFlag):
    SIMPLE = 0
    SLIDE_ON_REMOVE = 1
    STAY_ON_REMOVE = 2
    TRANSIENT = 4
    RANGE_BEGIN = 8
    RANGE_END = 16


class LocalReference:
    __slots__ = ("segment", "offset", "ref_type", "properties")

    def __init__(
        self,
        segment: Optional["Segment"],
        offset: int = 0,
        ref_type: ReferenceType = ReferenceType.SLIDE_ON_REMOVE,
        properties: Optional[dict] = None,
    ):
        self.segment = segment
        self.offset = offset
        self.ref_type = ref_type
        self.properties = properties or {}

    def is_detached(self) -> bool:
        return self.segment is None
