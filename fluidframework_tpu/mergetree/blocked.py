"""BlockedMergeTree: the production host merge-tree — O(√n)-ish ops.

Ref: packages/dds/merge-tree/src/mergeTree.ts:333 — the reference keeps
segments in an 8-ary B-tree whose internal nodes cache partial lengths
per in-window sequence number (partialLengths.ts:62), so position
resolution skips whole subtrees. This is the same idea in a two-level
shape tuned for Python: segments live in BLOCKS of ~B, and each block
caches

- ``settled_len`` — total length of its UNIVERSALLY-VISIBLE segments
  (``ins_seq <= min_seq`` and never removed): every legal perspective
  has ``ref_seq >= min_seq``, so these contribute their full length to
  any view without inspection;
- ``volatile`` — the segments whose visibility is perspective-dependent
  (in-window stamps, pending local state): evaluated live per query.

A block's visible length under perspective P is then
``settled_len + Σ volatile.visible_length(P)`` — O(window ops in the
block), not O(B). Walks (resolve / remove / annotate) skip whole
non-overlapping blocks; only the overlapping blocks pay a per-segment
scan. The flat :class:`~.mergetree.MergeTree` remains the scalar oracle
(kernel fuzz parity) and the semantics contract: tests fuzz this class
against it op-for-op.

Compaction (zamboni, mergeTree.ts:1455) is AMORTIZED instead of eager:
``update_min_seq`` is O(1) plus a two-block round-robin rebuild, so a
1M-char document does not pay a full-tree scan on every sequenced op —
the flat oracle's dominant cost. Rebuilding a block drops dead
segments (sliding local references, references.py), merges adjacent
settled text runs, and re-settles in-window segments the advanced
``min_seq`` now covers.
"""

from __future__ import annotations

from typing import Optional

from ..protocol.messages import UNASSIGNED_SEQ
from .mergetree import MergeTree
from .perspective import Perspective
from .segments import Segment

TARGET_BLOCK = 96          # split threshold is 2×
REBUILD_PER_ADVANCE = 2    # blocks compacted per min_seq advance


class _Block:
    __slots__ = ("segs", "settled_len", "volatile", "dirty")

    def __init__(self, segs: Optional[list] = None):
        self.segs: list[Segment] = segs if segs is not None else []
        self.settled_len = 0
        self.volatile: list[Segment] = []
        self.dirty = True

    def visible_length(self, tree: "BlockedMergeTree",
                       perspective: Perspective) -> int:
        if self.dirty:
            tree._rebuild(self)
        n = self.settled_len
        for s in self.volatile:
            n += s.visible_length(perspective)
        return n


def _settled(seg: Segment, min_seq: int) -> bool:
    """Universally visible: counts toward EVERY legal perspective."""
    return (seg.ins_seq <= min_seq and seg.rem_seq is None
            and not seg.is_pending())


def _droppable(seg: Segment, min_seq: int) -> bool:
    return (seg.rem_seq is not None and seg.rem_seq != UNASSIGNED_SEQ
            and seg.rem_seq <= min_seq and seg.rem_local_seq is None)


class BlockedMergeTree(MergeTree):
    """Drop-in for MergeTree with blocked storage.

    ``segments`` is a FLATTENED COPY for iteration (the cold paths —
    snapshot, reconnect rebase, item scans — keep their flat-list
    shape); all hot mutations and walks are overridden block-aware.
    """

    def __init__(self):
        self._blocks: list[_Block] = [_Block()]
        self._rr = 0  # round-robin compaction cursor
        super().__init__()  # its ``segments = []`` routes to the setter

    # -- storage view ----------------------------------------------------

    @property
    def segments(self) -> list:
        out = []
        for b in self._blocks:
            out.extend(b.segs)
        return out

    @segments.setter
    def segments(self, value) -> None:
        # base-class __init__ assigns []; rebuild blocks on any reset
        self._blocks = [_Block(list(value))]
        self._rr = 0

    # -- summaries -------------------------------------------------------

    def _rebuild(self, block: _Block) -> None:
        """Recompute the block summary; drop dead segments and merge
        adjacent settled runs (the per-block zamboni)."""
        min_seq = self.min_seq
        kept: list[Segment] = []
        for seg in block.segs:
            if _droppable(seg, min_seq):
                self._slide_refs_blocked(seg, kept, block)
            else:
                prev = kept[-1] if kept else None
                if (prev is not None and prev.ins_seq <= min_seq
                        and seg.ins_seq <= min_seq
                        and prev.can_append(seg)):
                    prev.append(seg)
                else:
                    kept.append(seg)
        block.segs = kept
        settled = 0
        volatile = []
        for seg in kept:
            if _settled(seg, min_seq):
                settled += seg.length
            else:
                volatile.append(seg)
        block.settled_len = settled
        block.volatile = volatile
        block.dirty = False

    def _slide_refs_blocked(self, dying: Segment, kept: list,
                            block: _Block) -> None:
        """SlideOnRemove across block boundaries: prefer the previous
        kept segment in this block, else the last segment of the nearest
        non-empty earlier block."""
        if not dying.local_refs:
            return
        target = kept[-1] if kept else None
        if target is None:
            bi = self._blocks.index(block)
            for j in range(bi - 1, -1, -1):
                if self._blocks[j].segs:
                    target = self._blocks[j].segs[-1]
                    break
        from .references import ReferenceType

        for ref in dying.local_refs:
            if ref.ref_type & ReferenceType.STAY_ON_REMOVE or target is None:
                ref.segment = None
                ref.offset = 0
            else:
                ref.segment = target
                ref.offset = target.length
                target.local_refs.append(ref)
        dying.local_refs = []

    def _split_block(self, bi: int) -> None:
        b = self._blocks[bi]
        if len(b.segs) <= 2 * TARGET_BLOCK:
            return
        half = len(b.segs) // 2
        tail = _Block(b.segs[half:])
        b.segs = b.segs[:half]
        b.dirty = True
        self._blocks.insert(bi + 1, tail)

    # -- queries ---------------------------------------------------------

    def visible_length(self, perspective: Perspective) -> int:
        return sum(b.visible_length(self, perspective)
                   for b in self._blocks)

    def get_text(self, perspective: Perspective) -> str:
        out = []
        for b in self._blocks:
            for s in b.segs:
                if s.visible_in(perspective) and not s.is_marker:
                    out.append(s.text)
        return "".join(out)

    def resolve(self, pos: int, perspective: Perspective) -> tuple[int, int]:
        if pos < 0:
            raise IndexError(f"negative position {pos}")
        remaining = pos
        base = 0  # global segment index of the current block's start
        for b in self._blocks:
            bl = b.visible_length(self, perspective)
            # skip only on STRICT excess: at remaining == bl the earliest
            # boundary may sit before a trailing invisible run INSIDE
            # this block, which the in-block scan finds (oracle parity)
            if remaining > bl:
                remaining -= bl
                base += len(b.segs)
                continue
            for i, seg in enumerate(b.segs):
                if remaining == 0:
                    return (base + i, 0)
                vl = seg.visible_length(perspective)
                if remaining < vl:
                    return (base + i, remaining)
                remaining -= vl
            base += len(b.segs)
        if remaining == 0:
            return (base, 0)
        raise IndexError(
            f"position {pos} out of range "
            f"(len {self.visible_length(perspective)})")

    def position_of_segment(self, target: Segment,
                            perspective: Perspective) -> int:
        pos = 0
        for b in self._blocks:
            # blocks not containing the target contribute their summary
            # length in O(volatile); only the target's block pays a scan
            contained = False
            for s in b.segs:
                if s is target:
                    contained = True
                    break
            if not contained:
                pos += b.visible_length(self, perspective)
                continue
            for seg in b.segs:
                if seg is target:
                    return pos
                pos += seg.visible_length(perspective)
        raise ValueError("segment not in tree")

    def visible_segment_at(
        self, pos: int, perspective: Perspective
    ) -> tuple[Optional[Segment], int]:
        """Block-aware override (the inherited one materializes the full
        flattened list per call)."""
        remaining = pos
        if remaining < 0:
            raise IndexError(f"negative position {pos}")
        walking = False
        for b in self._blocks:
            if not walking:
                bl = b.visible_length(self, perspective)
                if remaining > bl:
                    remaining -= bl
                    continue
            for seg in b.segs:
                vl = seg.visible_length(perspective)
                if walking or remaining == 0:
                    if vl > 0:
                        return seg, 0
                    continue  # boundary: walk past invisible segments
                if remaining < vl:
                    return seg, remaining
                remaining -= vl
            walking = walking or remaining == 0
        if remaining == 0:
            return None, 0
        raise IndexError(
            f"position {pos} out of range "
            f"(len {self.visible_length(perspective)})")

    # -- mutation --------------------------------------------------------

    def insert_segment(self, pos: int, segment: Segment,
                       perspective: Perspective) -> Segment:
        bi, si, offset = self._locate(pos, perspective)
        b = self._blocks[bi]
        if offset > 0:
            tail = b.segs[si].split(offset)
            b.segs.insert(si + 1, tail)
            si += 1
        else:
            # tie-break walk (oracle parity: mergetree.py insert_segment)
            new_key = (segment.ins_seq, segment.ins_local_seq or 0)
            bound = perspective.local_seq
            while True:
                if si >= len(b.segs):
                    if bi + 1 >= len(self._blocks):
                        break
                    bi += 1
                    b = self._blocks[bi]
                    si = 0
                    continue
                s = b.segs[si]
                ins_seen = (
                    s.ins_client == perspective.client
                    and not (
                        bound is not None
                        and s.ins_local_seq is not None
                        and s.ins_local_seq > bound
                    )
                ) or s.ins_seq <= perspective.ref_seq
                if ins_seen:
                    break
                if (s.ins_seq, s.ins_local_seq or 0) <= new_key:
                    break
                si += 1
        b.segs.insert(si, segment)
        b.dirty = True
        self._split_block(bi)
        return segment

    def _locate(self, pos: int, perspective: Perspective
                ) -> tuple[int, int, int]:
        """(block index, in-block segment index, offset) for ``pos`` —
        the blocked analog of resolve's earliest-boundary contract."""
        remaining = pos
        if remaining < 0:
            raise IndexError(f"negative position {pos}")
        for bi, b in enumerate(self._blocks):
            bl = b.visible_length(self, perspective)
            if remaining > bl:
                remaining -= bl
                continue
            for si, seg in enumerate(b.segs):
                if remaining == 0:
                    return (bi, si, 0)
                vl = seg.visible_length(perspective)
                if remaining < vl:
                    return (bi, si, remaining)
                remaining -= vl
        if remaining == 0:
            return (len(self._blocks) - 1,
                    len(self._blocks[-1].segs), 0)
        raise IndexError(
            f"position {pos} out of range "
            f"(len {self.visible_length(perspective)})")

    def mark_removed(
        self,
        start: int,
        end: int,
        perspective: Perspective,
        rem_seq: int,
        rem_client: int,
        rem_local_seq: Optional[int] = None,
    ) -> list[Segment]:
        if end <= start:
            return []
        affected: list[Segment] = []
        pos = 0
        touched_blocks: list[int] = []
        bi = 0
        while bi < len(self._blocks) and pos < end:
            b = self._blocks[bi]
            bl = b.visible_length(self, perspective)
            if pos + bl <= start:  # no overlap with [start, end)
                pos += bl
                bi += 1
                continue
            i = 0
            touched = False
            while i < len(b.segs) and pos < end:
                seg = b.segs[i]
                vl = seg.visible_length(perspective)
                if vl > 0:
                    seg_start, seg_end = pos, pos + vl
                    if seg_end > start:
                        if seg_start < start:
                            tail = seg.split(start - seg_start)
                            b.segs.insert(i + 1, tail)
                            pos = start
                            i += 1
                            touched = True
                            continue
                        if seg_end > end:
                            tail = seg.split(end - seg_start)
                            b.segs.insert(i + 1, tail)
                            vl = end - seg_start
                        seg.rem_clients.add(rem_client)
                        if seg.rem_seq is None:
                            seg.rem_seq = rem_seq
                            seg.rem_client = rem_client
                            seg.rem_local_seq = rem_local_seq
                        elif seg.rem_seq == UNASSIGNED_SEQ \
                                and rem_seq != UNASSIGNED_SEQ:
                            seg.rem_seq = rem_seq
                            seg.rem_client = rem_client
                        affected.append(seg)
                        touched = True
                    pos = seg_end
                i += 1
            if touched:
                b.dirty = True
                touched_blocks.append(bi)
            bi += 1
        # split AFTER the walk (back to front): splitting mid-iteration
        # would shift block indices and re-visit the inserted tail with
        # an already-advanced pos, corrupting the range accounting
        for bj in reversed(touched_blocks):
            self._split_block(bj)
        return affected

    def annotate_range(
        self,
        start: int,
        end: int,
        props: dict,
        perspective: Perspective,
        local_seq: Optional[int] = None,
    ) -> list[Segment]:
        if end <= start:
            return []
        affected: list[Segment] = []
        pos = 0
        touched_blocks: list[int] = []
        bi = 0
        while bi < len(self._blocks) and pos < end:
            b = self._blocks[bi]
            bl = b.visible_length(self, perspective)
            if pos + bl <= start:
                pos += bl
                bi += 1
                continue
            i = 0
            touched = False
            while i < len(b.segs) and pos < end:
                seg = b.segs[i]
                vl = seg.visible_length(perspective)
                if vl > 0:
                    seg_start, seg_end = pos, pos + vl
                    if seg_end > start:
                        if seg_start < start:
                            tail = seg.split(start - seg_start)
                            b.segs.insert(i + 1, tail)
                            pos = start
                            i += 1
                            touched = True
                            continue
                        if seg_end > end:
                            tail = seg.split(end - seg_start)
                            b.segs.insert(i + 1, tail)
                        self._apply_props(seg, props, local_seq)
                        affected.append(seg)
                        touched = True
                    pos = min(seg_end, end)
                i += 1
            if touched:
                b.dirty = True
                touched_blocks.append(bi)
            bi += 1
        # see mark_removed: splits are deferred past the walk
        for bj in reversed(touched_blocks):
            self._split_block(bj)
        return affected

    def remove_segment(self, seg: Segment) -> None:
        for b in self._blocks:
            for i, s in enumerate(b.segs):
                if s is seg:
                    del b.segs[i]
                    b.dirty = True
                    return
        raise ValueError("segment not in tree")

    # -- collab window ----------------------------------------------------

    def update_min_seq(self, min_seq: int) -> None:
        """O(1) + amortized compaction: advancing the floor never walks
        the whole tree (the flat oracle's per-op dominant cost); instead
        a round-robin cursor rebuilds a couple of blocks per advance, so
        every block is compacted once per (blocks/2) advances."""
        if min_seq <= self.min_seq:
            return
        self.min_seq = min_seq
        for _ in range(min(REBUILD_PER_ADVANCE, len(self._blocks))):
            self._rr = (self._rr + 1) % len(self._blocks)
            b = self._blocks[self._rr]
            if b.dirty or b.volatile:
                self._rebuild(b)
            if not b.segs and len(self._blocks) > 1:
                self._blocks.remove(b)
                self._rr %= len(self._blocks)

    # -- snapshot ---------------------------------------------------------
    # snapshot() is inherited: it iterates the flattened ``segments``
    # property and is segmentation-tolerant on load. load() must build
    # a blocked instance:

    @classmethod
    def load(cls, snap: dict) -> "BlockedMergeTree":
        flat = MergeTree.load(snap)  # plain flat build of the snapshot
        tree = cls()
        tree.min_seq = flat.min_seq
        tree.current_seq = flat.current_seq
        segs = flat.segments
        tree._blocks = [
            _Block(segs[i:i + TARGET_BLOCK])
            for i in range(0, len(segs), TARGET_BLOCK)
        ] or [_Block()]
        return tree
