"""Segments: the atoms of the merge tree.

A segment is a run of content (text, or a single marker) carrying integer
insert/remove stamps. Ref: packages/dds/merge-tree/src/mergeTree.ts:486
(BaseSegment), textSegment.ts (TextSegment), mergeTree.ts:668 (Marker).

Stamp encoding (shared with the int32 tensor layout in
fluidframework_tpu.ops):

- ``ins_seq``: assigned sequence number, or ``UNASSIGNED_SEQ`` while the
  local insert is unacked.
- ``rem_seq``: ``None`` if never removed; ``UNASSIGNED_SEQ`` while a local
  remove is unacked; otherwise the remover's assigned seq.
- ``*_local_seq``: the client-local op number while pending, for ack
  matching and reconnect rebase (ref: localSeq tracking in
  mergeTree.ts / SegmentGroup).
"""

from __future__ import annotations

from typing import Optional

from ..protocol.messages import UNASSIGNED_SEQ, UNIVERSAL_SEQ
from .references import LocalReference

# client-id sentinel for "no client" (snapshot-loaded / never-removed slots)
NO_CLIENT = -1


class Segment:
    __slots__ = (
        "text",
        "marker",
        "props",
        "ins_seq",
        "ins_client",
        "ins_local_seq",
        "rem_seq",
        "rem_client",
        "rem_clients",
        "rem_local_seq",
        "pending_props",
        "pending_groups",
        "local_refs",
    )

    def __init__(
        self,
        text: str = "",
        marker: Optional[dict] = None,
        props: Optional[dict] = None,
        ins_seq: int = UNIVERSAL_SEQ,
        ins_client: int = NO_CLIENT,
        ins_local_seq: Optional[int] = None,
    ):
        self.text = text
        self.marker = marker  # non-None ⇒ this is a marker segment
        self.props: dict = props or {}
        self.ins_seq = ins_seq
        self.ins_client = ins_client
        self.ins_local_seq = ins_local_seq
        self.rem_seq: Optional[int] = None  # earliest ASSIGNED remove seq (or UNASSIGNED while only pending)
        self.rem_client: int = NO_CLIENT  # author of rem_seq
        # ALL clients that removed this segment — overlapping concurrent
        # removes must each count for their author's later perspectives
        # (ref: overlapping-remove bookkeeping, mergeTree.ts:2640)
        self.rem_clients: set[int] = set()
        self.rem_local_seq: Optional[int] = None
        # key → local_seq of the pending local annotate that set it
        self.pending_props: dict = {}
        # SegmentGroups (one per in-flight wire op) this segment belongs to;
        # the ack path stamps exactly one group's segments with the op's
        # assigned seq (ref: SegmentGroupCollection, mergeTree.ts SegmentGroup)
        self.pending_groups: list = []
        self.local_refs: list[LocalReference] = []

    # -- basic geometry --------------------------------------------------
    @property
    def is_marker(self) -> bool:
        return self.marker is not None

    @property
    def length(self) -> int:
        return 1 if self.is_marker else len(self.text)

    def is_pending(self) -> bool:
        return (
            self.ins_local_seq is not None
            or self.rem_local_seq is not None
            or bool(self.pending_props)
        )

    # -- visibility ------------------------------------------------------
    def visible_in(self, perspective) -> bool:
        bound = perspective.local_seq
        # insert side: own inserts always visible (unless past the rebase
        # bound); others' only once sequenced at/below refSeq
        if self.ins_client == perspective.client:
            if (
                bound is not None
                and self.ins_local_seq is not None
                and self.ins_local_seq > bound
            ):
                return False
        elif not self.ins_seq <= perspective.ref_seq:
            return False
        # remove side
        if self.rem_seq is None:
            return True
        if perspective.client in self.rem_clients:
            if (
                bound is not None
                and self.rem_local_seq is not None
                and not self.rem_local_seq < bound
            ):
                # our pending remove lands at/after the bounded op — for
                # this view the segment is not yet gone by OUR hand; an
                # overlapping assigned remove may still hide it (below)
                pass
            else:
                return False
        if self.rem_seq != UNASSIGNED_SEQ and self.rem_seq <= perspective.ref_seq:
            return False
        return True

    def visible_length(self, perspective) -> int:
        return self.length if self.visible_in(perspective) else 0

    # -- split / merge ---------------------------------------------------
    def split(self, offset: int) -> "Segment":
        """Split at text offset (0 < offset < length); returns the tail.

        Both halves keep identical stamps so ack matching and perspective
        checks are unaffected (ref: BaseSegment.splitAt mergeTree.ts:523).
        Markers (length 1) are never split.
        """
        assert not self.is_marker and 0 < offset < len(self.text)
        tail = Segment(
            text=self.text[offset:],
            props=dict(self.props),
            ins_seq=self.ins_seq,
            ins_client=self.ins_client,
            ins_local_seq=self.ins_local_seq,
        )
        tail.rem_seq = self.rem_seq
        tail.rem_client = self.rem_client
        tail.rem_clients = set(self.rem_clients)
        tail.rem_local_seq = self.rem_local_seq
        tail.pending_props = dict(self.pending_props)
        # the tail stays part of every in-flight op the head belongs to
        tail.pending_groups = list(self.pending_groups)
        for g in self.pending_groups:
            g.segments.append(tail)
        self.text = self.text[:offset]
        # references at or past the split move to the tail
        keep, move = [], []
        for ref in self.local_refs:
            (move if ref.offset >= offset else keep).append(ref)
        for ref in move:
            ref.segment = tail
            ref.offset -= offset
        self.local_refs = keep
        tail.local_refs = move
        return tail

    def can_append(self, other: "Segment") -> bool:
        """May ``other`` (the immediate successor) be merged into self?

        Only fully-acked, never-removed, same-props text runs merge —
        zamboni's compaction criterion (ref: mergeTree.ts:1455).
        """
        return (
            not self.is_marker
            and not other.is_marker
            and self.rem_seq is None
            and other.rem_seq is None
            and not self.is_pending()
            and not other.is_pending()
            and self.props == other.props
        )

    def append(self, other: "Segment") -> None:
        base = len(self.text)
        self.text += other.text
        for ref in other.local_refs:
            ref.segment = self
            ref.offset += base
        self.local_refs.extend(other.local_refs)
        other.local_refs = []

    def __repr__(self) -> str:  # debugging aid for farm divergence dumps
        stamp = f"i{self.ins_seq}@{self.ins_client}"
        if self.ins_local_seq is not None:
            stamp += f"(L{self.ins_local_seq})"
        if self.rem_seq is not None:
            stamp += f" r{self.rem_seq}@{self.rem_client}"
        body = f"M{self.marker}" if self.is_marker else repr(self.text)
        return f"<Seg {body} {stamp}>"
