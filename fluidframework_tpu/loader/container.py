"""Container: one client's live replica of one document.

Ref: loader/container-loader/src/container.ts — boot (:931): fetch latest
summary version → load protocol state (:1116, the client-side quorum
replica via ProtocolOpHandler) → instantiate runtime (:1547) → attach the
delta stream and catch up. Afterwards every sequenced message flows
protocol-first, then into the runtime (§3.3).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..driver.definitions import DocumentService, DocumentServiceFactory
from ..protocol.consensus import SequencedClient
from ..protocol.messages import (
    MessageType,
    Nack,
    SequencedDocumentMessage,
    Signal,
)
from ..protocol.quorum import ProtocolOpHandler
from ..runtime.container_runtime import ContainerRuntime
from .delta_manager import DeltaManager


class Container:
    def __init__(
        self,
        service: DocumentService,
        runtime_factory: Optional[Callable[["Container"], ContainerRuntime]] = None,
        code_loader=None,
        auto_reconnect: bool = False,
    ):
        # auto_reconnect: re-dial after a SERVER-initiated drop with
        # backoff (ref: the deltaManager.ts:294,444 reconnect state
        # machine, where it is the default). Opt-in here; the sharded
        # core's failover path relies on it (a doc's partition moving to
        # a takeover core drops the session mid-stream).
        self.auto_reconnect = auto_reconnect
        self._service = service
        self._code_loader = code_loader
        self.storage = service.connect_to_storage()
        self.delta_manager = DeltaManager(service)
        self.delta_manager.process_handler = self._process
        self.delta_manager.connection_handler = self._on_connection_change
        self.delta_manager.nack_handler = self._on_nack
        self.delta_manager.signal_handler = self._on_signal
        self.delta_manager.on_log_truncated = self._reanchor
        self.protocol: Optional[ProtocolOpHandler] = None
        self.runtime: Optional[ContainerRuntime] = None
        self._runtime_factory = runtime_factory or (lambda c: ContainerRuntime(c))
        self.existing = False
        self.closed = False
        self.detached = False
        # client-side readonly policy (ref: readonly modes,
        # deltaManager.ts:274): when set, local submission is refused
        self._force_readonly = False
        self.on_signal: Optional[Callable[[Signal], None]] = None
        self.on_nack: Optional[Callable[[Nack], None]] = None
        self._base_snapshot: Optional[dict] = None
        # every client id this container has ever held: ops from a PREVIOUS
        # connection sequenced before our leave must still count as local
        # (acks), or pending state double-applies after reconnect
        self._my_client_ids: set[str] = set()
        # subsystems observing the sequenced stream (summarizer, telemetry)
        self._message_observers: list = []

    # ------------------------------------------------------------- lifecycle

    def load(self, connect: bool = True) -> "Container":
        """Boot from the latest summary (if any) and connect live."""
        self._boot_from(self.storage.get_snapshot_tree())
        if connect:
            self.connect()
        return self

    def _boot_from(self, snapshot: Optional[dict]) -> None:
        """(Re)build protocol + runtime from a summary snapshot — the
        boot core of :meth:`load`, reused by the log-truncation reanchor."""
        self._base_snapshot = snapshot
        if snapshot is not None:
            self.existing = True
            self.protocol = ProtocolOpHandler.load(snapshot["protocol"])
            self.delta_manager.last_processed_seq = snapshot["sequence_number"]
        else:
            self.protocol = ProtocolOpHandler()
        # the quorum-agreed code proposal picks the runtime factory when
        # a code loader is wired (ref: loadRuntimeFactory container.ts:1241)
        factory = self._runtime_factory
        if self._code_loader is not None:
            agreed = self._code_loader.factory_for(self)
            if agreed is not None:
                factory = agreed
        self.runtime = factory(self)
        if snapshot is not None:
            self.runtime.load_snapshot(snapshot["runtime"],
                                       base_seq=snapshot["sequence_number"])

    def _reanchor(self, err: Exception) -> bool:
        """Backfill hit the retention base (too far behind): drop the
        stale cached snapshot, re-boot from the LATEST summary — whose
        capture seq the trim is gated on, so it always lands at or past
        the hole — and let the delta manager retry the now-bounded tail.
        Returns False (error propagates) when no newer summary exists."""
        cache = getattr(self.storage, "_cache", None)
        if cache is not None:
            cache.invalidate(self.storage._tenant, self.storage._doc)
        snapshot = self.storage.get_snapshot_tree()
        if snapshot is None or snapshot["sequence_number"] \
                <= self.delta_manager.last_processed_seq:
            return False
        self._boot_from(snapshot)
        if self.delta_manager.counters is not None:
            self.delta_manager.counters.inc("boot.snapshot.reanchor")
        return True

    def connect(self) -> str:
        client_id = self.delta_manager.connect()
        # anything sequenced before our join means the document pre-existed
        if self.delta_manager.last_processed_seq > 1:
            self.existing = True
        return client_id

    def disconnect(self) -> None:
        self.delta_manager.disconnect()

    def reconnect(self) -> str:
        """Manual reconnect: new connection + pending-op replay
        (ref: auto-reconnect state machine deltaManager.ts:294,444)."""
        return self.delta_manager.reconnect()

    def attach(self) -> str:
        """Attach a detached container: connect and let the pending-op
        replay submit the offline-built initial state as the document's
        first ops (ref: container.ts:510 + runtime attach flow)."""
        if not self.detached:
            raise RuntimeError("container is not detached")
        self.detached = False
        return self.connect()

    # ------------------------------------------------------------ readonly

    @property
    def readonly(self) -> bool:
        return self._force_readonly

    def force_readonly(self, readonly: bool = True) -> None:
        """Client-side readonly switch: local edits raise while set
        (ref: forceReadonly / readonly modes deltaManager.ts:274)."""
        self._force_readonly = readonly

    def close(self) -> None:
        self.closed = True
        self.delta_manager.disconnect()

    # -------------------------------------------------------------- access

    @property
    def client_id(self) -> Optional[str]:
        return self.delta_manager.client_id

    @property
    def connected(self) -> bool:
        return self.delta_manager.connected

    @property
    def quorum(self):
        return self.protocol.quorum

    @property
    def blob_manager(self):
        """Attachment blobs (ref: blobManager.ts): payloads live in the
        content-addressed store, only handles ride the op stream."""
        if not hasattr(self, "_blob_manager"):
            from .blob_manager import BlobManager

            self._blob_manager = BlobManager(self.storage)
        return self._blob_manager

    @property
    def audience(self) -> dict[str, SequencedClient]:
        """Connected clients as known through the total order (join/leave)."""
        return dict(self.protocol.quorum.members)

    def propose(self, key: str, value: Any) -> None:
        """Submit a quorum proposal (commits when msn passes it with no
        rejection — protocol-base quorum.ts:67 semantics)."""
        self.delta_manager.submit(
            MessageType.PROPOSE, {"key": key, "value": value}
        )

    def propose_code(self, details: Any) -> None:
        """Propose the container code through the quorum — every replica
        boots the agreed package after commit (ref: "code" proposals)."""
        from .code_loader import CODE_KEY

        self.propose(CODE_KEY, details)

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        self.delta_manager.submit_signal(content, type)

    # ------------------------------------------------------------ internal

    def add_message_observer(self, fn: Callable[[SequencedDocumentMessage], None]) -> None:
        self._message_observers.append(fn)

    def _process(self, msg: SequencedDocumentMessage) -> None:
        local = msg.client_id in self._my_client_ids
        self.protocol.process_message(msg, local)
        if self.runtime is not None:
            if msg.type == MessageType.OPERATION:
                self.runtime.process(msg, local)
            elif msg.type == MessageType.CLIENT_LEAVE:
                # consensus collections release a leaver's holdings
                # deterministically off the sequenced leave (SURVEY §2.2)
                left = (msg.contents or {}).get("clientId")
                if left:
                    self.runtime.on_member_removed(
                        left, seq=msg.sequence_number)
        for fn in self._message_observers:
            fn(msg)

    def _on_connection_change(self, connected: bool, client_id: Optional[str]) -> None:
        if connected and client_id is not None:
            self._my_client_ids.add(client_id)
        if self.runtime is not None:
            self.runtime.set_connection_state(connected, client_id)
        if (not connected and self.auto_reconnect and not self.closed
                and not self.delta_manager.user_disconnected):
            import threading

            threading.Thread(target=self._reconnect_loop,
                             daemon=True).start()

    def _reconnect_loop(self) -> None:
        """Server-initiated drop: re-dial with backoff until the doc is
        served again (e.g. its partition's takeover core claimed the
        lease) or the container closes."""
        import time

        delay = 0.1
        while not self.closed and not self.connected:
            time.sleep(delay)
            delay = min(delay * 2, 2.0)
            if self.closed or self.connected \
                    or self.delta_manager.user_disconnected:
                return
            try:
                self.delta_manager.connect()
            except Exception:  # noqa: BLE001 — core still down: retry
                continue
            # connect() returning is NOT success: the connection only
            # activates when our join round-trips, and a pending
            # connection that dies fires no handler (was_active=False)
            # — so wait bounded here and retry instead of returning
            t0 = time.time()
            while (not self.closed and not self.connected
                   and self.delta_manager.pending_connection is not None
                   and time.time() - t0 < 10.0):
                time.sleep(0.05)
            if self.connected:
                return
            self.delta_manager.abort_pending()

    def _on_nack(self, nack: Nack) -> None:
        # a nack means our op stream is broken at the server: the recovery
        # is reconnect + rebase/resubmit (ref: deltaManager nack handling)
        if self.on_nack:
            self.on_nack(nack)

    def _on_signal(self, signal: Signal) -> None:
        if self.on_signal:
            self.on_signal(signal)


class Loader:
    """Resolves (tenant, document) → loaded Container
    (ref: loader.ts:142,202 resolve/loadContainer)."""

    def __init__(
        self,
        factory: DocumentServiceFactory,
        runtime_factory: Optional[Callable[[Container], ContainerRuntime]] = None,
        code_loader=None,
        auto_reconnect: bool = False,
    ):
        self._factory = factory
        self._runtime_factory = runtime_factory
        self._code_loader = code_loader
        self._auto_reconnect = auto_reconnect

    def resolve(
        self, tenant_id: str, document_id: str, connect: bool = True
    ) -> Container:
        service = self._factory.create_document_service(tenant_id, document_id)
        return Container(service, self._runtime_factory,
                         code_loader=self._code_loader,
                         auto_reconnect=self._auto_reconnect).load(connect)

    def resolve_at(self, tenant_id: str, document_id: str,
                   seq: int) -> Container:
        """Resolve a POINT-IN-TIME read: a read-only offline container
        of the doc as of ``seq``, booted from the nearest committed
        summary at or below it plus a bounded history-backed tail
        backfill (see loader/history_boot.py)."""
        from .history_boot import open_at

        service = self._factory.create_document_service(tenant_id,
                                                        document_id)
        return open_at(service.history(), seq,
                       runtime_factory=self._runtime_factory)

    def create_detached(self, tenant_id: str, document_id: str) -> Container:
        """A container that lives entirely client-side until ``attach()``
        (ref: container.ts:510 detached create → attach). Build the
        initial data stores/channels offline; every edit records as
        pending state, and attach() replays it through the normal
        pending-op machinery as the document's first ops."""
        service = self._factory.create_document_service(tenant_id, document_id)
        container = Container(service, self._runtime_factory,
                              code_loader=self._code_loader).load(
            connect=False)
        container.detached = True
        return container
