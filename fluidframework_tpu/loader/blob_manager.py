"""BlobManager: attachment blobs (binary payloads outside the op stream).

Ref: loader/container-loader/src/blobManager.ts — large binary content
(images, files) never rides ops: the client uploads it to the
content-addressed store, gets back a handle, and stores the HANDLE in a
DDS; readers fetch the payload through storage on demand. Payload
delivery cost is off the sequencer entirely, and identical content
dedupes by address.

The 16 KB op cap (config.max_message_size) is the forcing function: a
payload over the cap nacks at the front door, an attachment handle never
does.
"""

from __future__ import annotations

from typing import Optional


class BlobHandle:
    """A stored blob's address + mime tag, as kept inside DDS values."""

    KIND = "fluid-blob"

    def __init__(self, blob_id: str, mime: str = "application/octet-stream"):
        self.blob_id = blob_id
        self.mime = mime

    def to_value(self) -> dict:
        return {"kind": self.KIND, "id": self.blob_id, "mime": self.mime}

    @classmethod
    def from_value(cls, value: dict) -> Optional["BlobHandle"]:
        if isinstance(value, dict) and value.get("kind") == cls.KIND:
            return cls(value["id"], value.get("mime", ""))
        return None


class BlobManager:
    def __init__(self, storage):
        self._storage = storage
        self._cache: dict[str, bytes] = {}

    def create_blob(self, content: bytes,
                    mime: str = "application/octet-stream") -> BlobHandle:
        """Upload to the content-addressed store; identical content maps
        to the identical handle (dedupe is the store's sha addressing)."""
        blob_id = self._storage.write_blob(content)
        self._cache[blob_id] = content
        return BlobHandle(blob_id, mime)

    def get_blob(self, handle) -> bytes:
        blob_id = handle.blob_id if isinstance(handle, BlobHandle) \
            else (handle["id"] if isinstance(handle, dict) else handle)
        cached = self._cache.get(blob_id)
        if cached is None:
            cached = self._cache[blob_id] = self._storage.read_blob(blob_id)
        return cached
