"""DeltaManager: the client op pump.

Ref: loader/container-loader/src/deltaManager.ts — inbound sequenced ops
with gap detection + reorder buffer and backfill fetch (:1188, :432, :647),
outbound submission with clientSeq assignment (:583), connect/reconnect
state machine (:444). Everything is synchronous and deterministic here;
async pacing (DeltaScheduler time-slicing) is a host-side concern the TPU
build handles at the batch boundary instead.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..driver.definitions import DocumentService
from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    Nack,
    SequencedDocumentMessage,
    Signal,
)


class DeltaManager:
    """Pumps one document's op stream for one client.

    ``process_handler(msg)`` is called exactly once per sequenced message,
    in strict sequence order, regardless of delivery order or gaps.
    """

    def __init__(self, service: DocumentService):
        self._service = service
        self._delta_storage = service.connect_to_delta_storage()
        self.connection = None
        self._pending_connection = None  # opened, but our join not yet seen
        # True while the CLIENT chose to be offline (disconnect()); a
        # server-initiated drop leaves it False, which is what an
        # auto-reconnect policy keys on
        self.user_disconnected = False
        self.client_id: Optional[str] = None
        self.last_processed_seq = 0
        self.duplicates_received = 0
        self.minimum_sequence_number = 0
        self._client_seq = 0
        self._reorder: dict[int, SequencedDocumentMessage] = {}
        self.process_handler: Optional[Callable[[SequencedDocumentMessage], None]] = None
        self.nack_handler: Optional[Callable[[Nack], None]] = None
        self.signal_handler: Optional[Callable[[Signal], None]] = None
        self.connection_handler: Optional[Callable[[bool, Optional[str]], None]] = None
        self._details: Any = None
        # DeltaScheduler role (deltaScheduler.ts:25): long catch-up drains
        # call this hook every `inbound_slice` messages so a host can
        # yield/paint/heartbeat between slices of a big backlog
        self.inbound_yield: Optional[Callable[[int], None]] = None
        self.inbound_slice = 256
        self._drained_since_yield = 0
        # noop heartbeat (ref: submit coalescing + noop heuristics,
        # deltaManager.ts:583): a watch-only client must still advance
        # its refSeq through the sequencer or it pins the document's msn
        # — and with it the collaboration window and the device zamboni
        # floor. After this many remote ops with no local submission, a
        # NOOP goes out. 0 disables.
        self.noop_frequency = 50
        self._remote_since_submit = 0
        # per-client inbound pause (the OpProcessingController role,
        # opProcessingController.ts:16): tests freeze ONE replica's
        # delivery to force specific interleavings, then step/resume
        self._paused = False
        self._pause_buffer: list[SequencedDocumentMessage] = []
        # log-truncation reanchor hook (the container wires this): the
        # backfill range reached below the server's retention base —
        # return True after re-booting from the latest summary (which
        # advances last_processed_seq past the hole) to retry the tail
        self.on_log_truncated: Optional[Callable[[Exception], bool]] = None
        # boot-shape telemetry shared with the driver tier when the
        # service exposes one (boot.backfill.* — was the catch-up bounded
        # by a snapshot, or a whole-log replay?)
        self.counters = getattr(service, "counters", None)
        self._first_catchup = True

    @property
    def connected(self) -> bool:
        return self.connection is not None

    # ------------------------------------------------------------ connect

    def connect(self, details: Any = None) -> str:
        """Open the live stream and backfill pre-subscription history.

        The connection only becomes ACTIVE (connection_handler fires, write
        path opens) once our own join is processed from the stream — by
        then every op of a previous incarnation has been sequenced and
        acked, so pending-op replay cannot duplicate in-flight ops (ref:
        container.ts treats a connection as pending until the join op
        round-trips; deli fences old-client ops behind the leave).
        """
        if self.connection is not None or self._pending_connection is not None:
            return self.client_id
        self.user_disconnected = False
        self._details = details if details is not None else self._details
        conn = self._service.connect_to_delta_stream(self._details)
        self._pending_connection = conn
        try:
            conn.on_nack = self._on_nack
            conn.on_signal = self._on_signal
            conn.on_disconnect = lambda reason: self._on_disconnect(reason)
            # classify the boot shape BEFORE the op handler goes live:
            # assigning on_op flushes buffered events (our own join can
            # already be sitting there), and a buffered op with a gap
            # runs the whole gap repair inline — which advances
            # last_processed_seq and would mislabel a whole-log replay
            # as snapshot-bounded
            if self._first_catchup and self.counters is not None \
                    and conn.initial_sequence_number > 0:
                self._first_catchup = False
                self.counters.inc(
                    "boot.backfill.bounded" if self.last_processed_seq > 0
                    else "boot.backfill.full")
            conn.on_op = self._enqueue  # assigning flushes buffered events
            # repair any gap between our head and the pre-subscription
            # history; everything from the handshake on arrives live
            # (incl. our join)
            self._fetch_missing(upto=conn.initial_sequence_number)
        except BaseException:
            # a half-opened connection must not wedge future connects: a
            # still-pending _pending_connection makes connect() an early-
            # return no-op, which an auto-reconnect loop would read as
            # success and stop retrying
            if self._pending_connection is conn:
                self._pending_connection = None
            conn.on_disconnect = None
            try:
                conn.close()
            except Exception:
                pass
            raise
        if getattr(conn, "mode", "write") in ("read", "readonly"):
            # read/readonly connections never join the quorum, so there
            # is no join round-trip to wait for: they go active
            # immediately (and the write path below refuses their
            # submissions)
            if self._pending_connection is conn:
                self._activate_connection()
        return conn.client_id

    def _activate_connection(self) -> None:
        conn, self._pending_connection = self._pending_connection, None
        self.connection = conn
        self.client_id = conn.client_id
        self._client_seq = 0
        if self.connection_handler:
            self.connection_handler(True, self.client_id)

    @property
    def pending_connection(self):
        """The opened-but-not-yet-active connection (join in flight)."""
        return self._pending_connection

    def abort_pending(self) -> None:
        """Drop a pending connection WITHOUT marking a user disconnect —
        the auto-reconnect loop's cleanup when a join never lands."""
        conn, self._pending_connection = self._pending_connection, None
        if conn is not None:
            conn.on_disconnect = None
            try:
                conn.close()
            except Exception:
                pass

    def disconnect(self, reason: str = "client disconnect") -> None:
        self.user_disconnected = True
        conn = self.connection or self._pending_connection
        if conn is None:
            return
        was_active = self.connection is not None
        self.connection = self._pending_connection = None
        self.client_id = None
        conn.on_disconnect = None  # avoid re-entrant notification
        conn.close()
        if was_active and self.connection_handler:
            self.connection_handler(False, None)

    def reconnect(self, reason: str = "reconnect") -> str:
        self.disconnect(reason)
        return self.connect()

    def _on_disconnect(self, reason: str) -> None:
        # server-initiated drop: notify; the container decides when to
        # reconnect (auto-reconnect policy lives above, container.ts:294)
        was_active = self.connection is not None
        self.connection = self._pending_connection = None
        self.client_id = None
        if was_active and self.connection_handler:
            self.connection_handler(False, None)

    # ------------------------------------------------------------- submit

    def submit(
        self,
        type: MessageType,
        contents: Any,
        metadata: Optional[dict] = None,
    ) -> int:
        """Send one message on the live connection; returns clientSeq."""
        if self.connection is None:
            raise RuntimeError("cannot submit while disconnected")
        mode = getattr(self.connection, "mode", "write")
        if mode == "readonly":
            raise PermissionError(
                "readonly session: opened with readonly=True, no quorum "
                "membership to write from")
        if mode == "read":
            raise PermissionError(
                "read connection: this client's token lacks doc:write")
        self._remote_since_submit = 0
        self._client_seq += 1
        self.connection.submit(
            [
                DocumentMessage(
                    client_sequence_number=self._client_seq,
                    reference_sequence_number=self.last_processed_seq,
                    type=type,
                    contents=contents,
                    metadata=metadata,
                )
            ]
        )
        return self._client_seq

    def submit_batch(self, type: MessageType,
                     contents_list: list) -> list[int]:
        """Send a flushed batch as ONE submission: consecutive clientSeqs,
        one shared refSeq, first/last marked with batch metadata (ref:
        outbound DeltaQueue batch flush, deltaManager.ts:583 + the
        batchBegin/batchEnd metadata convention). The whole batch rides
        the raw log as one boxcar, so it is sequenced contiguously."""
        if self.connection is None:
            raise RuntimeError("cannot submit while disconnected")
        msgs = []
        seqs = []
        ref = self.last_processed_seq
        n = len(contents_list)
        for i, contents in enumerate(contents_list):
            self._client_seq += 1
            seqs.append(self._client_seq)
            metadata = None
            if n > 1:
                if i == 0:
                    metadata = {"batch": True}
                elif i == n - 1:
                    metadata = {"batch": False}
            msgs.append(DocumentMessage(
                client_sequence_number=self._client_seq,
                reference_sequence_number=ref,
                type=type,
                contents=contents,
                metadata=metadata,
            ))
        self.connection.submit(msgs)
        return seqs

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        if self.connection is None:
            raise RuntimeError("cannot signal while disconnected")
        self.connection.submit_signal(content, type)

    # ------------------------------------------------------------ inbound

    def pause_inbound(self) -> None:
        """Freeze delivery to THIS replica; arriving ops buffer."""
        self._paused = True

    def resume_inbound(self) -> None:
        """Deliver everything buffered, in order, then go live again."""
        self._paused = False
        pending, self._pause_buffer = self._pause_buffer, []
        for msg in pending:
            self._enqueue(msg)

    def step_inbound(self, count: int = 1) -> int:
        """Deliver up to ``count`` buffered messages while staying paused
        (the process/processIncoming stepping surface). Returns how many
        were delivered.

        Steps in SEQUENCE order, not arrival order: stepping an
        out-of-order arrival would trigger gap repair that pulls ops
        still sitting in the pause buffer from delta storage — delivering
        more than ``count`` and leaving silent duplicates behind."""
        delivered = 0
        while delivered < count and self._pause_buffer:
            msg = min(self._pause_buffer, key=lambda m: m.sequence_number)
            self._pause_buffer.remove(msg)
            self._paused = False
            try:
                self._enqueue(msg)
            finally:
                self._paused = True
            delivered += 1
        return delivered

    def _enqueue(self, msg: SequencedDocumentMessage) -> None:
        """Strict-order delivery with reorder buffer + gap repair
        (ref: processInboundMessage deltaManager.ts:1188)."""
        if self._paused:
            self._pause_buffer.append(msg)
            return
        if msg.sequence_number <= self.last_processed_seq:
            # dedupe is correctness (reconnect backfill overlap), but a
            # STEADY duplicate stream is a delivery bug upstream (e.g.
            # the gateway double-upstream race) that dedupe would mask —
            # count it so tests and telemetry can see it
            self.duplicates_received += 1
            return
        self._reorder[msg.sequence_number] = msg
        self._drain_reorder()
        if self._reorder:
            # a gap remains: repair from delta storage
            self._fetch_missing(upto=min(self._reorder))
            self._drain_reorder()
        self._maybe_heartbeat()

    def _maybe_heartbeat(self) -> None:
        """Send the refSeq-advancing NOOP when we have only been
        watching (outside the drain loop: submitting mid-drain would
        re-enter processing on a synchronous service)."""
        if (
            self.noop_frequency
            and self.connection is not None
            and getattr(self.connection, "mode", "write") == "write"
            and self._remote_since_submit >= self.noop_frequency
        ):
            self._remote_since_submit = 0
            self.submit(MessageType.NOOP, None)

    def _drain_reorder(self) -> None:
        while self.last_processed_seq + 1 in self._reorder:
            msg = self._reorder.pop(self.last_processed_seq + 1)
            self.last_processed_seq = msg.sequence_number
            self.minimum_sequence_number = msg.minimum_sequence_number
            if (
                msg.client_id is not None
                and msg.client_id != self.client_id
                and msg.type is not MessageType.NOOP
            ):
                # only CONTENT traffic triggers heartbeats: counting other
                # clients' noops would make the heartbeats self-sustaining
                # once the client count passes noop_frequency (a storm)
                self._remote_since_submit += 1
            if self.process_handler:
                self.process_handler(msg)
            if self.inbound_yield is not None:
                self._drained_since_yield += 1
                if self._drained_since_yield >= self.inbound_slice:
                    self._drained_since_yield = 0
                    self.inbound_yield(self.last_processed_seq)
            if (
                self._pending_connection is not None
                and msg.type == MessageType.CLIENT_JOIN
                and (msg.contents or {}).get("clientId")
                == self._pending_connection.client_id
            ):
                # our join round-tripped: the connection goes active AFTER
                # the quorum learned about us and every earlier op (incl.
                # a previous incarnation's in-flight ops) was processed
                self._activate_connection()

    def advance_to(self, seq: int) -> int:
        """Pull and process every sequenced message up to ``seq`` from
        delta storage WITHOUT a live connection — the replay-driver pump
        (ref: replay-driver ReplayController stepping the inbound queue).
        Returns the new last_processed_seq."""
        self._fetch_missing(upto=seq)
        return self.last_processed_seq

    def _fetch_missing(self, upto: int) -> None:
        """Backfill (last_processed, upto] from delta storage.

        A ``log_truncated`` refusal (our head is below the server's
        retention base — duck-typed on ``.base`` so both the local and
        network drivers' exception classes match) runs the reanchor hook
        once: the container re-boots from the latest summary, advancing
        ``last_processed_seq`` past the hole, and the (now bounded) tail
        fetch retries. No hook, or a hook that cannot reanchor, and the
        error propagates — it is not silently a partial catch-up."""
        if upto <= self.last_processed_seq:
            return
        try:
            msgs = self._delta_storage.get_deltas(
                self.last_processed_seq, upto + 1)
        except RuntimeError as e:
            if getattr(e, "base", None) is None \
                    or self.on_log_truncated is None \
                    or not self.on_log_truncated(e):
                raise
            if upto <= self.last_processed_seq:
                return
            msgs = self._delta_storage.get_deltas(
                self.last_processed_seq, upto + 1)
        for msg in msgs:
            self._reorder.setdefault(msg.sequence_number, msg)
        self._drain_reorder()

    def _on_nack(self, nack: Nack) -> None:
        if self.nack_handler:
            self.nack_handler(nack)

    def _on_signal(self, signal: Signal) -> None:
        if self.signal_handler:
            self.signal_handler(signal)
