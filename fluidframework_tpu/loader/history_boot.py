"""Point-in-time container boot over the history plane.

The loader half of the replay driver (ref: packages/drivers/
replay-driver ReplayController): ``driver.history.HistoryClient``
resolves the commit and binds a pinned :class:`DocumentService`
(``replay_service``); :func:`open_at` here boots a read-only container
from it and pumps the bounded tail ``(base, seq]`` through
``DeltaManager.advance_to``. Split across the two layers because
drivers may not import the loader — the driver supplies services, the
loader builds containers from them, same as the live path.
"""

from __future__ import annotations

from .container import Container


def open_at(history, seq: int, runtime_factory=None) -> Container:
    """Boot a read-only container of ``history``'s doc as of ``seq``.

    Snapshot-nearest-below plus bounded tail backfill; the returned
    container is offline and force-readonly — inspect its channels,
    never edit them. ``history`` is a ``DocumentService.history()``
    client (local or network)."""
    container = Container(history.replay_service(seq),
                          runtime_factory).load(connect=False)
    container.delta_manager.advance_to(seq)
    container.force_readonly(True)
    return container
