"""Code loader: the quorum-agreed container code → runtime factory.

Ref: the reference's containers carry their own code: clients propose
``IFluidCodeDetails`` through the quorum under the "code" key
(container.ts loadRuntimeFactory :1241 reads the accepted proposal), and
a code loader (web-code-loader: npm/cdn bundle fetch) turns the details
into the runtime factory that instantiates the container runtime. Every
client therefore runs the SAME code version, agreed through the same
total order as the data.

Python analog: packages are registered factories (the module registry is
the bundle store); the accepted quorum value picks which one boots the
runtime. Proposing an unregistered package fails boot on clients that
lack it — the same failure mode as a bundle fetch miss.
"""

from __future__ import annotations

from typing import Callable, Optional

CODE_KEY = "code"  # quorum key (ref: container.ts "code"/"code2" proposals)


class CodeLoader:
    """package name → ContainerRuntime factory registry."""

    def __init__(self):
        self._registry: dict[str, Callable] = {}

    def register(self, package: str, factory: Callable) -> "CodeLoader":
        self._registry[package] = factory
        return self

    def resolve(self, details) -> Callable:
        """Resolve code details ({"package": ..., "config": ...} or a
        bare package string) to a runtime factory."""
        package = details.get("package") if isinstance(details, dict) \
            else details
        factory = self._registry.get(package)
        if factory is None:
            raise KeyError(
                f"no code registered for package {package!r} "
                f"(have: {sorted(self._registry)})")
        return factory

    def factory_for(self, container) -> Optional[Callable]:
        """The factory for a container's ACCEPTED code proposal, or None
        when no proposal has committed (caller falls back to its default
        runtime factory)."""
        details = container.quorum.get(CODE_KEY)
        if details is None:
            return None
        return self.resolve(details)
