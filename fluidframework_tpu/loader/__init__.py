"""Loader layer: connects the driver to the runtime.

Ref: packages/loader/container-loader (SURVEY §2.4) — the Loader resolves
a document to a Container; the Container boots protocol state + runtime
from the latest summary and op tail; the DeltaManager pumps the op stream
both ways with gap repair and reconnect.
"""

from .delta_manager import DeltaManager
from .container import Container, Loader

__all__ = ["DeltaManager", "Container", "Loader"]
