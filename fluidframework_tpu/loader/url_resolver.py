"""URL resolution: document URLs → driver endpoints.

Ref: packages/drivers/*-urlResolver (routerlicious-urlResolver parses
https://host/tenant/doc into an IFluidResolvedUrl the driver factory
consumes). The scheme here:

    fluid://host:port/tenant/document

``open_url`` is the whole client bootstrap in one call: parse → network
driver factory → loader → container.
"""

from __future__ import annotations

from dataclasses import dataclass
from urllib.parse import urlparse


@dataclass(frozen=True)
class ResolvedUrl:
    host: str
    port: int
    tenant_id: str
    document_id: str


def resolve_url(url: str) -> ResolvedUrl:
    parsed = urlparse(url)
    if parsed.scheme != "fluid":
        raise ValueError(f"not a fluid:// url: {url!r}")
    parts = [p for p in parsed.path.split("/") if p]
    if parsed.hostname is None or parsed.port is None or len(parts) != 2:
        raise ValueError(
            f"expected fluid://host:port/tenant/document, got {url!r}")
    return ResolvedUrl(parsed.hostname, parsed.port, parts[0], parts[1])


def open_url(url: str, token_provider=None, connect: bool = True,
             runtime_factory=None, code_loader=None):
    """Parse, wire the network driver, and load the container."""
    from ..driver.network import NetworkDocumentServiceFactory
    from .container import Loader

    r = resolve_url(url)
    loader = Loader(
        NetworkDocumentServiceFactory(r.host, r.port,
                                      token_provider=token_provider),
        runtime_factory=runtime_factory,
        code_loader=code_loader,
    )
    return loader.resolve(r.tenant_id, r.document_id, connect=connect)
