"""ctypes binding for the native gateway relay (native/gateway.cpp).

The C++ loop owns the sockets and runs without the GIL (ctypes releases
it for the blocking ``gateway_run`` call); the Python process is only the
deployment shell (argv, readiness line, signals) — the §2.9 "native
front-end" posture with the uniform ``python -m`` deployment story.
"""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeGateway:
    def __init__(self, core_host: str, core_port: int,
                 host: str = "127.0.0.1", port: int = 0):
        self._lib = load_library("gateway")
        self._lib.gateway_create.restype = ctypes.c_void_p
        self._lib.gateway_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        self._lib.gateway_port.restype = ctypes.c_int
        self._lib.gateway_port.argtypes = [ctypes.c_void_p]
        self._lib.gateway_run.restype = ctypes.c_int
        self._lib.gateway_run.argtypes = [ctypes.c_void_p]
        self._lib.gateway_stop.argtypes = [ctypes.c_void_p]
        self._lib.gateway_destroy.argtypes = [ctypes.c_void_p]
        self._handle = self._lib.gateway_create(
            core_host.encode(), core_port, host.encode(), port)
        if not self._handle:
            raise OSError(
                f"cannot start native gateway (core {core_host}:{core_port})")
        self.port = self._lib.gateway_port(self._handle)

    def run(self) -> int:
        """Blocks in C++ until stop() or the core connection drops."""
        return self._lib.gateway_run(self._handle)

    def stop(self) -> None:
        if self._handle:
            self._lib.gateway_stop(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.gateway_destroy(self._handle)
            self._handle = None
