"""Lazy g++ build of the native components, cached by source hash."""

from __future__ import annotations

import ctypes
import hashlib
import pathlib
import subprocess

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
_SRC_DIR = _REPO_ROOT / "native"
_BUILD_DIR = _SRC_DIR / "build"


class NativeUnavailable(RuntimeError):
    pass


def _source(name: str) -> pathlib.Path:
    return _SRC_DIR / f"{name}.cpp"


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if stale) and dlopen native/<name>.cpp → <name>-<hash>.so."""
    src = _source(name)
    if not src.exists():
        raise NativeUnavailable(f"missing source {src}")
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    so_path = _BUILD_DIR / f"{name}-{digest}.so"
    if not so_path.exists():
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        tmp = so_path.with_suffix(".so.tmp")
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            str(src), "-o", str(tmp),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except FileNotFoundError as e:
            raise NativeUnavailable("g++ not found") from e
        except subprocess.CalledProcessError as e:
            raise NativeUnavailable(
                f"compile failed:\n{e.stderr.decode(errors='replace')}") from e
        tmp.rename(so_path)
    return ctypes.CDLL(str(so_path))


def native_available() -> bool:
    try:
        load_library("oplog")
        return True
    except NativeUnavailable:
        return False
