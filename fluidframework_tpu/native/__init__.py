"""Native (C++) runtime components and their ctypes bindings.

Ref: SURVEY §2.9 — the reference's only native code is librdkafka (the
ordered op log) and libgit2 (content-addressed snapshot storage). This
package provides the TPU build's equivalents:

- ``oplog``      durable append-only partitioned log (native/oplog.cpp)
- ``chunkstore`` sha256-addressed blob store (native/chunkstore.cpp)

Binaries build lazily on first use with g++ (cached under
native/build/); environments without a toolchain raise
``NativeUnavailable`` and callers fall back to the in-memory pure-Python
equivalents (LocalLog, InMemoryDb-backed storage).
"""

from .build import NativeUnavailable, native_available
from .oplog import NativeOpLog
from .chunkstore import NativeChunkStore

__all__ = [
    "NativeUnavailable",
    "native_available",
    "NativeOpLog",
    "NativeChunkStore",
]
