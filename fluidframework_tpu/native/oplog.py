"""ctypes binding for the durable op log (native/oplog.cpp)."""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeOpLog:
    """Durable append-only partitioned log of byte records."""

    def __init__(self, directory: str):
        self._lib = load_library("oplog")
        self._lib.oplog_open.restype = ctypes.c_void_p
        self._lib.oplog_open.argtypes = [ctypes.c_char_p]
        self._lib.oplog_close.argtypes = [ctypes.c_void_p]
        self._lib.oplog_append.restype = ctypes.c_int64
        self._lib.oplog_append.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_length.restype = ctypes.c_int64
        self._lib.oplog_length.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_read.restype = ctypes.c_int64
        self._lib.oplog_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_sync.restype = ctypes.c_int
        self._lib.oplog_sync.argtypes = [ctypes.c_void_p]
        self._handle = self._lib.oplog_open(directory.encode())
        if not self._handle:
            raise OSError(f"cannot open op log at {directory}")

    def append(self, topic: str, record: bytes) -> int:
        off = self._lib.oplog_append(
            self._handle, topic.encode(), record, len(record))
        if off < 0:
            raise OSError(f"append to {topic!r} failed")
        return off

    def length(self, topic: str) -> int:
        n = self._lib.oplog_length(self._handle, topic.encode())
        if n < 0:
            raise OSError(f"bad topic {topic!r}")
        return n

    def read(self, topic: str, offset: int) -> bytes:
        size = 4096
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.oplog_read(
                self._handle, topic.encode(), offset, buf, size)
            if n < 0:
                raise IndexError(f"no record {offset} in {topic!r}")
            if n <= size:
                return buf.raw[:n]
            size = n  # buffer too small: retry at the reported size

    def sync(self) -> None:
        if self._lib.oplog_sync(self._handle) != 0:
            raise OSError("sync failed")

    def close(self) -> None:
        if self._handle:
            self._lib.oplog_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
