"""ctypes binding for the durable op log (native/oplog.cpp)."""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeOpLog:
    """Durable append-only partitioned log of byte records.

    ``readonly=True`` opens a CONSUMER handle for a directory another
    process is writing: it never creates or truncates files, and
    :meth:`refresh` tails records the producer has flushed
    (``flush()``) since the last call — the cross-process pipe the
    per-stage service composition rides (service/stage_runner.py)."""

    def __init__(self, directory: str, readonly: bool = False):
        self._lib = load_library("oplog")
        self._lib.oplog_open.restype = ctypes.c_void_p
        self._lib.oplog_open.argtypes = [ctypes.c_char_p]
        self._lib.oplog_open_readonly.restype = ctypes.c_void_p
        self._lib.oplog_open_readonly.argtypes = [ctypes.c_char_p]
        self._lib.oplog_close.argtypes = [ctypes.c_void_p]
        self._lib.oplog_append.restype = ctypes.c_int64
        self._lib.oplog_append.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_length.restype = ctypes.c_int64
        self._lib.oplog_length.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_read.restype = ctypes.c_int64
        self._lib.oplog_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_sync.restype = ctypes.c_int
        self._lib.oplog_sync.argtypes = [ctypes.c_void_p]
        self._lib.oplog_flush.restype = ctypes.c_int
        self._lib.oplog_flush.argtypes = [ctypes.c_void_p]
        self._lib.oplog_refresh.restype = ctypes.c_int64
        self._lib.oplog_refresh.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self.readonly = readonly
        opener = (self._lib.oplog_open_readonly if readonly
                  else self._lib.oplog_open)
        self._handle = opener(directory.encode())
        if not self._handle:
            raise OSError(f"cannot open op log at {directory}")

    def append(self, topic: str, record: bytes) -> int:
        off = self._lib.oplog_append(
            self._handle, topic.encode(), record, len(record))
        if off < 0:
            raise OSError(f"append to {topic!r} failed")
        return off

    def length(self, topic: str) -> int:
        n = self._lib.oplog_length(self._handle, topic.encode())
        if n < 0:
            # readonly consumers race topic creation: a topic the
            # producer hasn't created yet has length 0, same contract as
            # refresh(). Writers auto-create, so -1 there is a real error.
            if self.readonly:
                return 0
            raise OSError(f"bad topic {topic!r}")
        return n

    def read(self, topic: str, offset: int) -> bytes:
        size = 4096
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.oplog_read(
                self._handle, topic.encode(), offset, buf, size)
            if n < 0:
                raise IndexError(f"no record {offset} in {topic!r}")
            if n <= size:
                return buf.raw[:n]
            size = n  # buffer too small: retry at the reported size

    def sync(self) -> None:
        if self._lib.oplog_sync(self._handle) != 0:
            raise OSError("sync failed")

    def flush(self) -> None:
        """Make buffered appends visible to consumer processes (fflush
        into the page cache — durability still requires sync())."""
        if self._lib.oplog_flush(self._handle) != 0:
            raise OSError("flush failed")

    def refresh(self, topic: str) -> int:
        """Tail records another process appended; returns the topic's
        refreshed length (0 if the producer hasn't created it yet)."""
        n = self._lib.oplog_refresh(self._handle, topic.encode())
        return 0 if n < 0 else n

    def close(self) -> None:
        if self._handle:
            self._lib.oplog_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
