"""ctypes binding for the durable op log (native/oplog.cpp)."""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeOpLog:
    """Durable append-only partitioned log of byte records.

    ``readonly=True`` opens a CONSUMER handle for a directory another
    process is writing: it never creates or truncates files, and
    :meth:`refresh` tails records the producer has flushed
    (``flush()``) since the last call — the cross-process pipe the
    per-stage service composition rides (service/stage_runner.py)."""

    def __init__(self, directory: str, readonly: bool = False):
        self._lib = load_library("oplog")
        self._lib.oplog_open.restype = ctypes.c_void_p
        self._lib.oplog_open.argtypes = [ctypes.c_char_p]
        self._lib.oplog_open_readonly.restype = ctypes.c_void_p
        self._lib.oplog_open_readonly.argtypes = [ctypes.c_char_p]
        self._lib.oplog_close.argtypes = [ctypes.c_void_p]
        self._lib.oplog_append.restype = ctypes.c_int64
        self._lib.oplog_append.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_length.restype = ctypes.c_int64
        self._lib.oplog_length.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_read.restype = ctypes.c_int64
        self._lib.oplog_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_sync.restype = ctypes.c_int
        self._lib.oplog_sync.argtypes = [ctypes.c_void_p]
        self._lib.oplog_flush.restype = ctypes.c_int
        self._lib.oplog_flush.argtypes = [ctypes.c_void_p]
        self._lib.oplog_refresh.restype = ctypes.c_int64
        self._lib.oplog_refresh.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_seg_config.restype = ctypes.c_int
        self._lib.oplog_seg_config.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._lib.oplog_seg_append.restype = ctypes.c_int64
        self._lib.oplog_seg_append.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64]
        self._lib.oplog_seg_count.restype = ctypes.c_int64
        self._lib.oplog_seg_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_seg_read.restype = ctypes.c_int64
        self._lib.oplog_seg_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        self._lib.oplog_seg_entry.restype = ctypes.c_int
        self._lib.oplog_seg_entry.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64] + \
            [ctypes.POINTER(ctypes.c_int64)] * 6
        self._lib.oplog_seg_refresh.restype = ctypes.c_int64
        self._lib.oplog_seg_refresh.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        self._lib.oplog_seg_tear.restype = ctypes.c_int
        self._lib.oplog_seg_tear.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
        self._lib.oplog_fd_cap.restype = ctypes.c_int
        self._lib.oplog_fd_cap.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._lib.oplog_open_files.restype = ctypes.c_int64
        self._lib.oplog_open_files.argtypes = [ctypes.c_void_p]
        self.readonly = readonly
        # topic-name encode cache: append/length/read run per record on
        # the durable hot path; str.encode is measurable there
        self._names: dict[str, bytes] = {}
        opener = (self._lib.oplog_open_readonly if readonly
                  else self._lib.oplog_open)
        self._handle = opener(directory.encode())
        if not self._handle:
            raise OSError(f"cannot open op log at {directory}")

    def _name(self, topic: str) -> bytes:
        b = self._names.get(topic)
        if b is None:
            b = self._names[topic] = topic.encode()
        return b

    def append(self, topic: str, record: bytes) -> int:
        off = self._lib.oplog_append(
            self._handle, self._name(topic), record, len(record))
        if off < 0:
            raise OSError(f"append to {topic!r} failed")
        return off

    def length(self, topic: str) -> int:
        n = self._lib.oplog_length(self._handle, self._name(topic))
        if n < 0:
            # readonly consumers race topic creation: a topic the
            # producer hasn't created yet has length 0, same contract as
            # refresh(). Writers auto-create, so -1 there is a real error.
            if self.readonly:
                return 0
            raise OSError(f"bad topic {topic!r}")
        return n

    def read(self, topic: str, offset: int) -> bytes:
        size = 4096
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.oplog_read(
                self._handle, self._name(topic), offset, buf, size)
            if n < 0:
                raise IndexError(f"no record {offset} in {topic!r}")
            if n <= size:
                return buf.raw[:n]
            size = n  # buffer too small: retry at the reported size

    # ---------------------------------------------------- segment streams

    def seg_config(self, seg_bytes: int) -> None:
        """Segment roll threshold for this handle (testing knob)."""
        if self._lib.oplog_seg_config(self._handle, seg_bytes) != 0:
            raise OSError("bad segment size")

    def fd_cap(self, cap: int) -> None:
        """Cap concurrently open FILE*s across this handle's topics and
        segment streams (0 = unlimited). Topic metadata stays resident;
        cold handles are flushed, closed, and reopened on demand — how a
        core holds 10k+ rehydrated docs inside RLIMIT_NOFILE."""
        if self._lib.oplog_fd_cap(self._handle, cap) != 0:
            raise OSError("bad fd cap")

    def open_files(self) -> int:
        """Currently open FILE*s (tests and fd budgeting)."""
        return int(self._lib.oplog_open_files(self._handle))

    def seg_append(self, stream: str, first_seq: int, last_seq: int,
                   block: bytes, btype: int) -> int:
        n = self._lib.oplog_seg_append(
            self._handle, self._name(stream), first_seq, last_seq,
            block, len(block), btype)
        if n < 0:
            raise OSError(f"segment append to {stream!r} failed")
        return n

    def seg_count(self, stream: str) -> int:
        n = self._lib.oplog_seg_count(self._handle, self._name(stream))
        if n < 0:
            if self.readonly:
                return 0  # producer hasn't created the stream yet
            raise OSError(f"bad segment stream {stream!r}")
        return n

    def seg_read(self, stream: str, ordinal: int) -> bytes:
        size = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.oplog_seg_read(
                self._handle, self._name(stream), ordinal, buf, size)
            if n < 0:
                raise IndexError(f"no block {ordinal} in {stream!r}")
            if n <= size:
                return buf.raw[:n]
            size = n

    def seg_entry(self, stream: str, ordinal: int) -> tuple:
        """Block metadata: (first_seq, last_seq, seg, off, len, btype)."""
        out = [ctypes.c_int64() for _ in range(6)]
        rc = self._lib.oplog_seg_entry(
            self._handle, self._name(stream), ordinal,
            *[ctypes.byref(o) for o in out])
        if rc != 0:
            raise IndexError(f"no block {ordinal} in {stream!r}")
        return tuple(o.value for o in out)

    def seg_refresh(self, stream: str) -> int:
        """Tail blocks another process appended; refreshed block count."""
        n = self._lib.oplog_seg_refresh(self._handle, self._name(stream))
        return 0 if n < 0 else n

    def seg_tear(self, stream: str, first_seq: int, last_seq: int,
                 block: bytes, btype: int, mode: int = 0) -> None:
        """Chaos seam: leave a deliberately torn tail on disk without
        admitting the block (mode 0 = half the block bytes and no index
        entry, mode 1 = full block but half an index entry)."""
        rc = self._lib.oplog_seg_tear(
            self._handle, self._name(stream), first_seq, last_seq,
            block, len(block), btype, mode)
        if rc != 0:
            raise OSError(f"segment tear on {stream!r} failed")

    def sync(self) -> None:
        if self._lib.oplog_sync(self._handle) != 0:
            raise OSError("sync failed")

    def flush(self) -> None:
        """Make buffered appends visible to consumer processes (fflush
        into the page cache — durability still requires sync())."""
        if self._lib.oplog_flush(self._handle) != 0:
            raise OSError("flush failed")

    def refresh(self, topic: str) -> int:
        """Tail records another process appended; returns the topic's
        refreshed length (0 if the producer hasn't created it yet)."""
        n = self._lib.oplog_refresh(self._handle, self._name(topic))
        return 0 if n < 0 else n

    def close(self) -> None:
        if self._handle:
            self._lib.oplog_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
