"""ctypes binding for the content-addressed store (native/chunkstore.cpp)."""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeChunkStore:
    """sha256-addressed, dedup'd, crash-safe blob store (.git/objects
    layout)."""

    def __init__(self, directory: str):
        self._lib = load_library("chunkstore")
        self._lib.cas_open.restype = ctypes.c_void_p
        self._lib.cas_open.argtypes = [ctypes.c_char_p]
        self._lib.cas_close.argtypes = [ctypes.c_void_p]
        self._lib.cas_put.restype = ctypes.c_int
        self._lib.cas_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p]
        self._lib.cas_get.restype = ctypes.c_int64
        self._lib.cas_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        self._lib.cas_has.restype = ctypes.c_int
        self._lib.cas_has.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        self._handle = self._lib.cas_open(directory.encode())
        if not self._handle:
            raise OSError(f"cannot open chunk store at {directory}")

    def put(self, data: bytes) -> str:
        out = ctypes.create_string_buffer(65)
        if self._lib.cas_put(self._handle, data, len(data), out) != 0:
            raise OSError("put failed")
        return out.value.decode()

    def get(self, blob_hash: str) -> bytes:
        size = 65536
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.cas_get(self._handle, blob_hash.encode(), buf, size)
            if n < 0:
                raise KeyError(blob_hash)
            if n <= size:
                return buf.raw[:n]
            size = n

    def has(self, blob_hash: str) -> bool:
        return bool(self._lib.cas_has(self._handle, blob_hash.encode()))

    def close(self) -> None:
        if self._handle:
            self._lib.cas_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
