"""Hardware parallelism: the TPU mapping of the reference's scale-out axes.

The reference scales by (SURVEY §2.10): document-sharded data parallelism
(Kafka partitions keyed by (tenant,doc) — lambdas-driver
kafka-service/partitionManager.ts:22), pipeline stages connected by the
sequenced-op log, and horizontal front-end scale-out. Here those become:

- ``mesh``          device mesh construction ('docs' × 'seg' axes)
- ``sharded_apply`` doc-sharded batched merge-tree apply (the DP analog)
- ``placement``     doc → shard routing table (the partition-key analog)
- ``long_doc``      segment-sharded prefix sums for giant single docs
                    (the SP/context-parallel analog; ref §5.7)
"""

from .mesh import make_mesh
from .placement import DocPlacement
from .sharded_apply import make_sharded_packed_step, make_sharded_step
from .long_doc import sharded_visible_prefix, sharded_resolve_position

__all__ = [
    "make_mesh",
    "DocPlacement",
    "make_sharded_packed_step",
    "make_sharded_step",
    "sharded_visible_prefix",
    "sharded_resolve_position",
]
