"""Device mesh construction.

Two named axes:

- ``docs`` — document shards (the Kafka-partition analog; independent docs,
  so this axis only ever carries stats collectives like psum of applied-op
  counts — never data dependencies between docs).
- ``seg``  — segment shards within one giant document (the
  sequence-parallel analog; carries prefix-sum collectives over ICI).

On a real slice the 'docs' axis should span hosts (DCN-tolerant: traffic is
tiny) while 'seg' stays intra-slice (prefix exchanges ride ICI).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: newer releases promote it to
    the top-level namespace (param ``check_vma``); older ones ship it as
    ``jax.experimental.shard_map`` (param ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(
    n_devices: int | None = None,
    seg_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('docs', 'seg') mesh over ``n_devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    if n % seg_shards != 0:
        raise ValueError(f"{n} devices not divisible by seg_shards={seg_shards}")
    grid = np.asarray(devices).reshape(n // seg_shards, seg_shards)
    return Mesh(grid, ("docs", "seg"))
