"""Device mesh construction.

Two named axes:

- ``docs`` — document shards (the Kafka-partition analog; independent docs,
  so this axis only ever carries stats collectives like psum of applied-op
  counts — never data dependencies between docs).
- ``seg``  — segment shards within one giant document (the
  sequence-parallel analog; carries prefix-sum collectives over ICI).

On a real slice the 'docs' axis should span hosts (DCN-tolerant: traffic is
tiny) while 'seg' stays intra-slice (prefix exchanges ride ICI).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: newer releases promote it to
    the top-level namespace (param ``check_vma``); older ones ship it as
    ``jax.experimental.shard_map`` (param ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(
    n_devices: int | None = None,
    seg_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('docs', 'seg') mesh over ``n_devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    if n % seg_shards != 0:
        raise ValueError(f"{n} devices not divisible by seg_shards={seg_shards}")
    grid = np.asarray(devices).reshape(n // seg_shards, seg_shards)
    return Mesh(grid, ("docs", "seg"))


def force_host_devices(n_devices: int) -> None:
    """Ensure ``len(jax.devices()) >= n_devices`` by forcing host-platform
    virtual devices (CPU dev boxes, CI, the multichip bench/soak gates).

    XLA parses ``XLA_FLAGS`` exactly once, at the very first backend
    init, so the flag must land in the environment before anything
    queries devices; if a backend already initialized with fewer devices
    (e.g. an accelerator plugin pinned ``jax_platforms``), fall back to
    the CPU platform and drop the initialized backend set. No-op when
    enough devices already exist."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if len(jax.devices()) < n_devices:
        from jax.extend import backend as _jax_backend

        jax.config.update("jax_platforms", "cpu")
        _jax_backend.clear_backends()
        if len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"could not force {n_devices} host devices "
                f"(have {len(jax.devices())}); was a backend already "
                "initialized with XLA_FLAGS set differently?")
