"""Doc → shard placement: the partition-routing table.

Ref: the reference routes a document to a Kafka partition by hashing
``(tenantId, documentId)`` (services/src/kafkaFactory.ts producers key on
doc id; lambdas-driver document-router demuxes per doc). Here the same
decision places a doc into a batch slot on a mesh shard; the host front-end
uses it to route incoming ops to the right per-shard staging buffer.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


def _stable_hash(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "little")


@dataclass
class DocPlacement:
    """Assigns each (tenant, doc) a (shard, slot) and tracks occupancy."""

    n_shards: int
    slots_per_shard: int
    _map: dict[str, tuple[int, int]] = field(default_factory=dict)
    _free: list[list[int]] = field(default_factory=list)

    def __post_init__(self):
        if not self._free:
            self._free = [
                list(range(self.slots_per_shard - 1, -1, -1))
                for _ in range(self.n_shards)
            ]

    @staticmethod
    def key(tenant_id: str, document_id: str) -> str:
        return f"{tenant_id}/{document_id}"

    def place(self, tenant_id: str, document_id: str) -> tuple[int, int]:
        """Idempotently place a doc; sticky once assigned (ref: Mongo lease
        reservations, memory-orderer/src/reservationManager.ts:21)."""
        k = self.key(tenant_id, document_id)
        if k in self._map:
            return self._map[k]
        preferred = _stable_hash(k) % self.n_shards
        for delta in range(self.n_shards):
            shard = (preferred + delta) % self.n_shards
            if self._free[shard]:
                slot = self._free[shard].pop()
                self._map[k] = (shard, slot)
                return shard, slot
        raise RuntimeError("all shards full; grow slots_per_shard or n_shards")

    def lookup(self, tenant_id: str, document_id: str) -> tuple[int, int] | None:
        return self._map.get(self.key(tenant_id, document_id))

    def split_rows(self, rows):
        """Vectorized global state row → (shard, local_row). The state's
        doc axis is shard-major (row = shard * slots_per_shard + slot,
        matching NamedSharding's contiguous blocks), so this is THE map
        from placement rows to mesh devices; works on ints and numpy
        arrays alike."""
        shard = rows // self.slots_per_shard
        return shard, rows - shard * self.slots_per_shard

    def evict(self, tenant_id: str, document_id: str) -> None:
        """Release a doc's slot (idle expiry / doc close)."""
        k = self.key(tenant_id, document_id)
        if k in self._map:
            shard, slot = self._map.pop(k)
            self._free[shard].append(slot)

    def snapshot(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "slots_per_shard": self.slots_per_shard,
            "map": {k: list(v) for k, v in self._map.items()},
        }

    @classmethod
    def load(cls, snap: dict) -> "DocPlacement":
        p = cls(snap["n_shards"], snap["slots_per_shard"])
        for k, (shard, slot) in snap["map"].items():
            p._map[k] = (shard, slot)
            p._free[shard].remove(slot)
        return p
