"""Doc-sharded batched apply: the multi-chip hot step.

Documents are independent CRDTs, so the 'docs' mesh axis is pure data
parallelism — each shard applies its own docs' sequenced ops (the analog of
one Kafka partition's DocumentLambda loop, lambdas-driver
document-router/documentLambda.ts). The only cross-shard traffic is a
``psum`` of scalar stats (applied-op count, overflow count) used by the
host scheduler, so the step scales linearly over ICI/DCN.

The per-shard body is the vmapped scan kernel from ops/apply.py; zamboni
compaction runs fused in the same dispatch when ``min_seq`` advances.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.apply import (
    F_TYPE,
    OP_FIELDS,
    OP_NOOP,
    apply_ops_batch,
    compact_batch,
    wave_min_seq,
)
from ..ops.doc_state import DocState
from ..utils.contracts import register_kernel_contract
from .mesh import shard_map


def doc_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [D, ...] doc-batched arrays: split docs, replicate rest."""
    return NamedSharding(mesh, P("docs"))


def shard_state(state: DocState, mesh: Mesh) -> DocState:
    s = doc_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), state)


def make_sharded_step(mesh: Mesh, donate: bool = True):
    """Build the jitted sharded step:

    ``step(state, ops) -> (state', stats)`` where ``state`` holds [D, S]
    segment arrays sharded over 'docs', ``ops`` is [D, K, OP_FIELDS] int32
    (NOOP-padded, each op carrying its deli msn in F_MSN), and ``stats``
    is a replicated dict of globals. Zamboni compaction runs fused per
    doc at the wave's own msn floor (apply.wave_min_seq).
    """

    def _local(state: DocState, ops: jax.Array):
        state = apply_ops_batch(state, ops)
        state = compact_batch(state, wave_min_seq(ops))
        applied = jnp.sum((ops[..., F_TYPE] != OP_NOOP).astype(jnp.int32))
        overflowed = jnp.sum(state.overflow.astype(jnp.int32))
        stats = {
            "applied_ops": jax.lax.psum(applied, "docs"),
            "overflow_docs": jax.lax.psum(overflowed, "docs"),
        }
        return state, stats

    dp = P("docs")
    sharded = shard_map(
        _local,
        mesh=mesh,
        in_specs=(dp, dp),
        out_specs=(dp, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _contract_build():
    """Build the sharded step on a 1-device 'docs' mesh — the contract
    is about the traced program, which is shard-count-invariant."""
    import numpy as np

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("docs",))
    step = make_sharded_step(mesh, donate=False)

    def example():
        D, S, K = 8, 16, 4
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        state = shard_state(state, mesh)
        ops = jnp.zeros((D, K, OP_FIELDS), jnp.int32)
        return (state, ops), {}

    return step, example


# contract: per-op path gather-free; the only gathers are zamboni
# compaction's once-per-wave argsort repack (one per DocState field).
# Collectives (psum of stats) are not memory gathers and don't count.
register_kernel_contract(
    "parallel.sharded_step",
    build=_contract_build,
    no_scatter=True,
    max_gathers=10,
    single_jit=True,
    notes="doc-sharded apply + fused zamboni over the 'docs' mesh axis",
)
