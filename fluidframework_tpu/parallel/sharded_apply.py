"""Doc-sharded batched apply: the multi-chip hot step.

Documents are independent CRDTs, so the 'docs' mesh axis is pure data
parallelism — each shard applies its own docs' sequenced ops (the analog of
one Kafka partition's DocumentLambda loop, lambdas-driver
document-router/documentLambda.ts). The only cross-shard traffic is a
``psum`` of scalar stats (applied-op count, overflow count) used by the
host scheduler, so the step scales linearly over ICI/DCN.

The per-shard body is the vmapped scan kernel from ops/apply.py; zamboni
compaction runs fused in the same dispatch when ``min_seq`` advances.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.apply import (
    F_TYPE,
    OP_FIELDS,
    OP_NOOP,
    apply_ops_batch,
    compact_batch,
    unpack_wave16,
    wave_min_seq,
)
from ..ops.doc_state import DocState
from ..utils.contracts import register_kernel_contract
from .mesh import shard_map


def doc_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [D, ...] doc-batched arrays: split docs, replicate rest."""
    return NamedSharding(mesh, P("docs"))


def shard_state(state: DocState, mesh: Mesh) -> DocState:
    s = doc_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), state)


def donation_supported() -> bool:
    """Whether donating the state buffer into the step is a win here.

    On TPU (and GPU) the PJRT client honors input/output aliasing and the
    dispatch stays asynchronous — donation halves the state's device
    footprint for free. The CPU client instead runs donating computations
    SYNCHRONOUSLY (the dispatch call blocks until the step completes) and
    then ignores the aliasing request anyway — donation there buys
    nothing and serializes the stage/execute overlap pipeline. Gate by
    backend so host runs keep async dispatch."""
    return jax.default_backend() != "cpu"


def make_sharded_step(mesh: Mesh, donate: Optional[bool] = None):
    """Build the jitted sharded step:

    ``step(state, ops) -> (state', stats)`` where ``state`` holds [D, S]
    segment arrays sharded over 'docs', ``ops`` is [D, K, OP_FIELDS] int32
    (NOOP-padded, each op carrying its deli msn in F_MSN), and ``stats``
    is a replicated dict of globals. Zamboni compaction runs fused per
    doc at the wave's own msn floor (apply.wave_min_seq).
    """

    def _local(state: DocState, ops: jax.Array):
        state = apply_ops_batch(state, ops)
        state = compact_batch(state, wave_min_seq(ops))
        applied = jnp.sum((ops[..., F_TYPE] != OP_NOOP).astype(jnp.int32))
        overflowed = jnp.sum(state.overflow.astype(jnp.int32))
        stats = {
            "applied_ops": jax.lax.psum(applied, "docs"),
            "overflow_docs": jax.lax.psum(overflowed, "docs"),
        }
        return state, stats

    dp = P("docs")
    sharded = shard_map(
        _local,
        mesh=mesh,
        in_specs=(dp, dp),
        out_specs=(dp, P()),
        check_vma=False,
    )
    if donate is None:
        donate = donation_supported()
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


# jitted packed steps shared across applier instances, keyed on the mesh
# (hashable) + build options: per-instance closures would each re-trace
# and re-compile every wave-shape bucket
_PACKED_STEP_CACHE: dict = {}


def make_sharded_packed_step(mesh: Mesh, donate: Optional[bool] = None,
                             use_pallas: bool = False,
                             pallas_interpret: bool = False,
                             trace_hook=None):
    """The mesh lane's fast step pair ``(packed_fn, wide_fn)``:

    ``packed_fn(state, wave16, bases) -> (state', stats)`` takes the
    int16-delta packed wave (ops/apply.unpack_wave16 wire format) with
    int32 [D, 2] per-doc bases; ``wide_fn(state, wave)`` is the int32
    escape lane (giant docs / huge windows / chaos force_wide). Both
    shard every [D, ...] input over 'docs' — each device unpacks and
    applies ONLY its own rows — donate the state, and psum scalar stats
    only, so the step scales linearly over ICI/DCN like the plain
    ``make_sharded_step``.

    ``trace_hook(kernel, shape)`` (optional) runs at TRACE time inside
    the jitted body — the service layer injects its recompile-telemetry
    counter through it (parallel must not import obs; layer DAG)."""
    if donate is None:
        donate = donation_supported()
    key = (mesh, donate, use_pallas, pallas_interpret)
    fn = _PACKED_STEP_CACHE.get(key)
    if fn is not None:
        return fn
    if use_pallas:
        from ..ops.pallas_apply import pallas_apply_ops_batch

        def apply_fn(state, wave):
            return pallas_apply_ops_batch(
                state, wave, interpret=pallas_interpret)
    else:
        apply_fn = apply_ops_batch

    def _apply_local(state, wave, shape):
        if trace_hook is not None:
            trace_hook("sharded_step_packed", shape)
        state = apply_fn(state, wave)
        state = compact_batch(state, wave_min_seq(wave))
        applied = jnp.sum((wave[..., F_TYPE] != OP_NOOP).astype(jnp.int32))
        overflowed = jnp.sum(state.overflow.astype(jnp.int32))
        stats = {
            "applied_ops": jax.lax.psum(applied, "docs"),
            "overflow_docs": jax.lax.psum(overflowed, "docs"),
        }
        return state, stats

    def _local_packed(state: DocState, wave16, bases):
        shape = "x".join(map(str, wave16.shape[:2]))
        return _apply_local(state, unpack_wave16(wave16, bases), shape)

    def _local_wide(state: DocState, wave):
        shape = "x".join(map(str, wave.shape[:2])) + "w"
        return _apply_local(state, wave, shape)

    dp = P("docs")
    don = (0,) if donate else ()
    packed = shard_map(_local_packed, mesh=mesh, in_specs=(dp, dp, dp),
                       out_specs=(dp, P()), check_vma=False)
    wide = shard_map(_local_wide, mesh=mesh, in_specs=(dp, dp),
                     out_specs=(dp, P()), check_vma=False)
    fn = (jax.jit(packed, donate_argnums=don),
          jax.jit(wide, donate_argnums=don))
    _PACKED_STEP_CACHE[key] = fn
    return fn


def _contract_build():
    """Build the sharded step on a 1-device 'docs' mesh — the contract
    is about the traced program, which is shard-count-invariant."""
    import numpy as np

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("docs",))
    step = make_sharded_step(mesh, donate=False)

    def example():
        D, S, K = 8, 16, 4
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        state = shard_state(state, mesh)
        ops = jnp.zeros((D, K, OP_FIELDS), jnp.int32)
        return (state, ops), {}

    return step, example


# contract: per-op path gather-free; the only gathers are zamboni
# compaction's once-per-wave argsort repack (one per DocState field).
# Collectives (psum of stats) are not memory gathers and don't count.
register_kernel_contract(
    "parallel.sharded_step",
    build=_contract_build,
    no_scatter=True,
    max_gathers=10,
    single_jit=True,
    notes="doc-sharded apply + fused zamboni over the 'docs' mesh axis",
)


def _packed_contract_build():
    """The packed mesh step at a small fixed geometry on a 1-device
    'docs' mesh (the traced program is shard-count-invariant)."""
    import numpy as np

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("docs",))
    step, _wide = make_sharded_packed_step(mesh, donate=False)

    def example():
        D, S, K = 8, 16, 4
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        state = shard_state(state, mesh)
        wave16 = jnp.zeros((D, K, OP_FIELDS), jnp.int16)
        bases = jnp.zeros((D, 2), jnp.int32)
        return (state, wave16, bases), {}

    return step, example


# contract: the wave arrives int16 and must be EXPLICITLY widened before
# any arithmetic (no_int16_arithmetic catches silent promotion); the
# unpack+apply is gather-free, the fused zamboni repack owns the only
# gathers (one per DocState field, once per wave, off the K-amplified
# path); psum of scalar stats is a collective, not a memory gather; one
# compile per wave-shape bucket.
register_kernel_contract(
    "parallel.sharded_step_packed",
    build=_packed_contract_build,
    no_scatter=True,
    max_gathers=10,
    no_int16_arithmetic=True,
    single_jit=True,
    notes="int16 packed-wave unpack + doc-sharded apply + fused zamboni",
)


def _packed_pallas_contract_build():
    """The packed mesh step with the per-shard Pallas apply selected
    (interpret mode so the contract checks run on any backend; the
    traced program is what the contract is about and is identical to
    the Mosaic-lowered one)."""
    import numpy as np

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("docs",))
    step, _wide = make_sharded_packed_step(
        mesh, donate=False, use_pallas=True, pallas_interpret=True)

    def example():
        D, S, K = 8, 16, 4
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        state = shard_state(state, mesh)
        wave16 = jnp.zeros((D, K, OP_FIELDS), jnp.int16)
        bases = jnp.zeros((D, 2), jnp.int32)
        return (state, wave16, bases), {}

    return step, example


# contract: the mesh lane's Pallas selection keeps every invariant of the
# XLA lane — the checker walks INTO the pallas_call jaxpr inside the
# shard_map body, so the segmented-scan rewrite cannot smuggle a scatter,
# an extra gather, or silent int16 promotion past the lint
register_kernel_contract(
    "parallel.sharded_step_packed_pallas",
    build=_packed_pallas_contract_build,
    no_scatter=True,
    max_gathers=10,
    no_int16_arithmetic=True,
    single_jit=True,
    notes="int16 packed wave + per-shard Pallas VMEM apply + fused "
          "zamboni over the 'docs' mesh axis",
)
