"""Segment-sharded kernels for giant single documents (the SP analog).

The reference bounds per-query cost on long documents with per-block
partial length sums (merge-tree partialLengths.ts:62) — a prefix-sum
cache over B-tree blocks. Sharding one doc's slot arrays over the 'seg'
mesh axis makes that literally a distributed segmented prefix sum: each
shard cumsums its local visible lengths, the shard totals are exchanged
with one ``all_gather`` over ICI, and every shard adds the exclusive sum
of its predecessors. Position resolution is then a local search plus a
one-hot vote across shards. (SURVEY §5.7.)

These functions are written to run INSIDE ``jax.shard_map`` with the slot
axis sharded over 'seg'; they are the building block the giant-doc apply
path composes with the doc-sharded step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.apply import (
    F_CLIENT,
    F_POS,
    F_REFSEQ,
    _apply_core,
    _visibility,
    compact,
    wave_min_seq,
)
from ..ops.doc_state import DocState


def sharded_visible_prefix(state: DocState, ref_seq, client, local_count, axis="seg"):
    """Global exclusive prefix sum of visible lengths across 'seg' shards.

    Returns (vis, vlen, cum, total): cum[i] is the GLOBAL number of visible
    characters before local slot i; total is the doc's visible length.
    Must be called inside shard_map with the slot axis sharded over
    ``axis``. One all_gather of scalars is the only communication.
    """
    vis, vlen, local_cum = _visibility(state, ref_seq, client, count=local_count)
    local_total = jnp.sum(vlen)
    shard_totals = jax.lax.all_gather(local_total, axis)  # [n_shards]
    my = jax.lax.axis_index(axis)
    offset = jnp.sum(jnp.where(jnp.arange(shard_totals.shape[0]) < my, shard_totals, 0))
    return vis, vlen, local_cum + offset, jnp.sum(shard_totals)


def sharded_resolve_position(
    state: DocState, pos, ref_seq, client, local_count, axis="seg"
):
    """Resolve visible position → (global_slot, offset_in_slot, found).

    The distributed twin of MergeTree.resolve / getContainingSegment
    (mergeTree.ts:1656): each shard searches its slice against the global
    prefix, then a max-vote across shards picks the owner.
    """
    S = state.length.shape[-1]
    vis, vlen, cum, total = sharded_visible_prefix(
        state, ref_seq, client, local_count, axis
    )
    inside = vis & (cum <= pos) & (pos < cum + vlen)
    has_local = jnp.any(inside)
    j = jnp.argmax(inside)
    my = jax.lax.axis_index(axis)
    global_slot = my * S + j
    offset = pos - cum[j]
    # exactly one shard can contain an interior position; max-vote selects it
    vote = jnp.where(has_local, global_slot, -1)
    winner_slot = jax.lax.pmax(vote, axis)
    winner_off = jax.lax.pmax(jnp.where(has_local, offset, -1), axis)
    return winner_slot, winner_off, (winner_slot >= 0) & (pos < total)


def sharded_apply_op(state: DocState, op, axis="seg") -> DocState:
    """Apply ONE sequenced op to a GIANT doc whose slot arrays are
    sharded over ``axis`` — the composed segment-parallel apply (the SP
    analog the doc-sharded step cannot cover when a single document's
    segment array exceeds one chip).

    Runs inside ``shard_map``; ``state.count`` is the shard's LOCAL used
    count. Three collectives per op, all scalar-sized over ICI:
    the prefix all_gather, the insert-owner vote (pmin), and the
    all-shards abort reduction (pmax) — everything else is the same
    gather-free local rebuild as the single-chip kernel (_apply_core).

    Insert ownership: the op inserts at the EARLIEST global boundary
    (same tie-break as unsharded). Shard-local free tails carry
    cum == their shard's end offset, so the earliest boundary's shard is
    exactly the pmin over (shard, slot) keys among shards holding any
    boundary — content boundaries and the append point fall out of one
    rule.
    """
    S = state.length.shape[-1]
    vis, vlen, cum, total = sharded_visible_prefix(
        state, op[F_REFSEQ], op[F_CLIENT], state.count, axis)
    pos = op[F_POS]
    boundary = cum >= pos
    has_b = jnp.any(boundary)
    j0 = jnp.argmax(boundary)
    my = lax.axis_index(axis)
    big = jnp.int32(1 << 30)
    key = jnp.where(has_b, my * S + j0, big)
    owner_key = -lax.pmax(-key, axis)  # pmin
    insert_here = has_b & (owner_key == key)

    def reduce_any(x):
        return lax.pmax(x.astype(jnp.int32), axis) > 0

    return _apply_core(state, op, vis, vlen, cum, total,
                       insert_here=insert_here, reduce_any=reduce_any)


def sharded_apply_ops(state: DocState, ops, axis="seg") -> DocState:
    """Apply K sequenced ops (int32[K, OP_FIELDS]) to a sharded giant
    doc, in order, then run zamboni locally at the wave's msn floor
    (compaction is per-shard: packing never crosses shard boundaries, so
    global segment order is preserved shard-major)."""

    def step(s, op):
        return sharded_apply_op(s, op, axis), None

    out, _ = lax.scan(step, state, ops)
    return compact(out, wave_min_seq(ops))


def rebalance_shards(arrays: dict, counts) -> tuple[dict, "jnp.ndarray"]:
    """Host-side shard rebalancing for a giant doc.

    Mid-doc inserts always land on the shard owning the boundary, so hot
    spots fill one shard while neighbors sit empty; when a shard nears
    capacity the host redistributes the logical segment sequence evenly
    and resumes (the dynamic analog of the reference's B-tree node
    splits, mergeTree.ts:2509 — rebalancing IS the split, done in bulk).

    ``arrays``: field → np.ndarray[n_shards, S_LOCAL(, P)] in shard-major
    logical order with per-shard ``counts``. Returns evenly re-packed
    arrays + new counts. Pure numpy: this runs between device dispatches,
    like the TpuDocumentApplier's escalation path.
    """
    import numpy as np

    n_shards = len(counts)
    total = int(np.sum(counts))
    per = -(-total // n_shards)  # ceil: even spread
    cap = next(iter(arrays.values())).shape[1]
    if per > cap:
        # an even spread no longer fits: the doc outgrew the WHOLE seg
        # mesh, not one hot shard — silent out-of-bounds packing here
        # would corrupt shard-major order, so refuse loudly (the caller's
        # move is a bigger mesh or larger per-shard slot arrays)
        raise ValueError(
            f"doc has {total} live segments but the seg mesh holds "
            f"{n_shards} x {cap}; rebalancing cannot fit "
            f"{per} per shard")
    out = {f: np.zeros_like(a) for f, a in arrays.items()}
    new_counts = np.zeros(n_shards, np.int32)
    # concatenate live rows in logical order once
    live = {f: np.concatenate([a[s, : counts[s]] for s in range(n_shards)])
            for f, a in arrays.items()}
    at = 0
    for s in range(n_shards):
        take = min(per, total - at)
        for f in out:
            out[f][s, :take] = live[f][at:at + take]
        new_counts[s] = take
        at += take
    return out, new_counts
