"""Segment-sharded kernels for giant single documents (the SP analog).

The reference bounds per-query cost on long documents with per-block
partial length sums (merge-tree partialLengths.ts:62) — a prefix-sum
cache over B-tree blocks. Sharding one doc's slot arrays over the 'seg'
mesh axis makes that literally a distributed segmented prefix sum: each
shard cumsums its local visible lengths, the shard totals are exchanged
with one ``all_gather`` over ICI, and every shard adds the exclusive sum
of its predecessors. Position resolution is then a local search plus a
one-hot vote across shards. (SURVEY §5.7.)

These functions are written to run INSIDE ``jax.shard_map`` with the slot
axis sharded over 'seg'; they are the building block the giant-doc apply
path composes with the doc-sharded step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.apply import _visibility
from ..ops.doc_state import DocState


def sharded_visible_prefix(state: DocState, ref_seq, client, local_count, axis="seg"):
    """Global exclusive prefix sum of visible lengths across 'seg' shards.

    Returns (vis, vlen, cum, total): cum[i] is the GLOBAL number of visible
    characters before local slot i; total is the doc's visible length.
    Must be called inside shard_map with the slot axis sharded over
    ``axis``. One all_gather of scalars is the only communication.
    """
    vis, vlen, local_cum = _visibility(state, ref_seq, client, count=local_count)
    local_total = jnp.sum(vlen)
    shard_totals = jax.lax.all_gather(local_total, axis)  # [n_shards]
    my = jax.lax.axis_index(axis)
    offset = jnp.sum(jnp.where(jnp.arange(shard_totals.shape[0]) < my, shard_totals, 0))
    return vis, vlen, local_cum + offset, jnp.sum(shard_totals)


def sharded_resolve_position(
    state: DocState, pos, ref_seq, client, local_count, axis="seg"
):
    """Resolve visible position → (global_slot, offset_in_slot, found).

    The distributed twin of MergeTree.resolve / getContainingSegment
    (mergeTree.ts:1656): each shard searches its slice against the global
    prefix, then a max-vote across shards picks the owner.
    """
    S = state.length.shape[-1]
    vis, vlen, cum, total = sharded_visible_prefix(
        state, ref_seq, client, local_count, axis
    )
    inside = vis & (cum <= pos) & (pos < cum + vlen)
    has_local = jnp.any(inside)
    j = jnp.argmax(inside)
    my = jax.lax.axis_index(axis)
    global_slot = my * S + j
    offset = pos - cum[j]
    # exactly one shard can contain an interior position; max-vote selects it
    vote = jnp.where(has_local, global_slot, -1)
    winner_slot = jax.lax.pmax(vote, axis)
    winner_off = jax.lax.pmax(jnp.where(has_local, offset, -1), axis)
    return winner_slot, winner_off, (winner_slot >= 0) & (pos < total)
