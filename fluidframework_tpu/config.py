"""Unified configuration registry.

Ref: the reference scatters configuration across nconf JSON layering per
micro-service (server/routerlicious/config/config.json), ILoaderOptions
threading (container.ts), and static engineering flags
(MergeTree.options); SURVEY §5.6 calls for ONE registry. This module is
it: every tunable the framework reads lives here with its default, and a
config resolves by layering defaults ← explicit overrides ← environment
(``FLUID_TPU_<FIELD>``, the env layer of the nconf pattern).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any, Optional

ENV_PREFIX = "FLUID_TPU_"


@dataclass
class Config:
    """All framework tunables, server and client, in one place."""

    # ---- service: deli sequencer (ref: deli/lambdaFactory.ts:29-37)
    client_timeout_s: float = 300.0      # idle-client eviction
    # ---- service: front door (ref: localDeltaConnectionServer.ts:96)
    max_message_size: int = 16 * 1024    # per-op cap, larger ops nacked
    max_buffered_bytes: int = 32 * 1024 * 1024  # slow-consumer drop bound
    # ---- service: TPU applier geometry (ops/doc_state + tpu_applier)
    applier_max_docs: int = 256          # device doc slots [D]
    applier_max_slots: int = 256         # segment slots per doc [S]
    applier_ops_per_dispatch: int = 32   # wave depth [K]
    applier_min_wave_ops: int = 0        # async worker dispatch threshold
    applier_overflow_check_every: int = 64  # dispatches between fences
    # use the Pallas VMEM-resident apply (ops/pallas_apply.py) in the
    # applier's dense step (requires max_docs % 8 == 0; measured ~8%
    # faster than the XLA scan on TPU). Deprecated in favor of
    # ``applier_kernel``; None defers to it, an explicit bool wins (the
    # pre-kernel-selection API keeps working).
    applier_use_pallas: Optional[bool] = None
    # contract-kernel selection for the apply step: "auto" picks the
    # Pallas VMEM-resident kernel on real TPU devices and the XLA scan
    # everywhere else (falling back to XLA when the doc geometry cannot
    # tile, i.e. docs-per-shard % 8 != 0); "pallas"/"xla" force a lane
    # (a forced "pallas" raises on incompatible geometry instead of
    # silently degrading).
    applier_kernel: str = "auto"
    # overlap-staged dispatch: stage wave N+1 on the host (pack +
    # per-shard scatter + device_put) while wave N executes
    # asynchronously on device. Off = fence each wave before staging the
    # next (the serialized pre-overlap behavior, kept for A/B).
    applier_overlap: bool = True
    # ---- client: summarizer heuristics (ref: summarizer.ts:232)
    summary_max_ops: int = 100           # ops since last ack → attempt
    # ---- DDS: merge-tree snapshot chunking (ref: snapshotV1.ts:87)
    summary_chunk_segments: int = 256    # segments per summary chunk blob
    # ---- service: log retention margin kept BELOW an acked summary's
    # capture seq (ops older than that truncate from scriptorium; a
    # client disconnected past the window reloads from the summary).
    # Negative disables truncation entirely.
    log_retention_ops: int = 1000
    # ---- service: GC posture for long-lived service processes
    gc_gen0_threshold: int = 200_000

    def with_overrides(self, **overrides: Any) -> "Config":
        known = {f.name for f in fields(self)}
        bad = set(overrides) - known
        if bad:
            raise KeyError(f"unknown config keys: {sorted(bad)}")
        merged = {f.name: getattr(self, f.name) for f in fields(self)}
        merged.update(overrides)
        return Config(**merged)

    @classmethod
    def from_env(cls, base: Optional["Config"] = None) -> "Config":
        """Environment layer: FLUID_TPU_MAX_MESSAGE_SIZE=65536 etc."""
        base = base or cls()
        overrides: dict[str, Any] = {}
        for f in fields(cls):
            raw = os.environ.get(ENV_PREFIX + f.name.upper())
            if raw is None or raw.strip() == "":
                # set-but-empty (export FLUID_TPU_X=) means "unset" in
                # shell convention: keep the layered default
                continue
            cur = getattr(base, f.name)
            # Optional fields default to None: the only such tunables are
            # bool-typed (applier_use_pallas), so parse them as booleans
            typ = bool if cur is None else type(cur)
            if typ is bool:
                # bool("0") is True — parse the usual spellings instead
                low = raw.strip().lower()
                if low in ("1", "true", "yes", "on"):
                    overrides[f.name] = True
                elif low in ("0", "false", "no", "off"):
                    overrides[f.name] = False
                else:
                    raise ValueError(
                        f"{ENV_PREFIX}{f.name.upper()}={raw!r}: expected a "
                        "boolean (1/0/true/false/yes/no/on/off)")
            else:
                overrides[f.name] = typ(raw)
        return base.with_overrides(**overrides)


# process-wide default instance (explicit Config args always win)
DEFAULT = Config.from_env()
