"""FaultPlane: seeded, schedulable fault injection behind named points.

The seams (driver/network.py frame sends, service/local_log.py appends,
service/broadcaster.py fan-out, service/stage_runner.py checkpoints,
service/tpu_applier.py dispatch) each hold a duck-typed ``fault_plane``
attribute, ``None`` by default. When armed, a seam calls

    directive = self.fault_plane("log.append", topic=topic, record=value)

and interprets the returned directive string (``None`` = no fault). A
directive starting with ``"crash"`` is raised out of the plane itself as
:class:`SimulatedCrash`, so service code never needs to know the
exception type — the kill just propagates out of the seam like a real
process death would.

Determinism: rules fire on *match counts* (``at`` / ``every``), not wall
time, and the PRNG (used only for ``p``-rules) is seeded — the same seed
against the same workload produces the same injections in the same
places. Every injection is recorded in the ledger and counted into the
telemetry counters (``chaos.injected.<point>.<directive>``) so the soak
can cross-check "faults injected" against "recoveries observed".
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Callable, Optional

from ..obs import tier_counters
from ..utils.telemetry import Counters

#: injection point → boundary class, for the per-class coverage check
#: (the soak requires ≥1 injected fault per class per run).
BOUNDARY_CLASSES = {
    "net": "network",
    "log": "log",
    "broadcast": "fanout",
    "relay": "fanout",
    "stage": "stage",
    "partition": "stage",
    "applier": "device",
    "snapshot": "snapshot",
    "placement": "placement",
    "history": "history",
}


class SimulatedCrash(Exception):
    """A scheduled kill raised out of an injection point (the in-process
    stand-in for kill -9 between consume and checkpoint, or between
    checkpoint and emit). Harnesses catch it and run the real recovery
    path; nothing else may swallow it."""


class FaultRule:
    """One scheduled fault: fire ``directive`` at ``point``.

    ``at`` fires on the Nth matching consult (1-based); ``every`` fires
    on every Nth; ``p`` fires with seeded probability; ``times`` caps the
    total number of firings (default 1 for ``at``, unlimited otherwise).
    ``when(ctx)`` restricts matching to consults whose context passes.
    """

    def __init__(self, point: str, directive: str,
                 at: Optional[int] = None, every: Optional[int] = None,
                 p: Optional[float] = None, times: Optional[int] = None,
                 when: Optional[Callable[[dict], bool]] = None):
        self.point = point
        self.directive = directive
        self.at = at
        self.every = every
        self.p = p
        self.when = when
        self.times = times if times is not None else (1 if at is not None
                                                      else None)
        self.seen = 0   # matching consults observed
        self.fired = 0  # injections performed

    def matches(self, point: str, ctx: dict) -> bool:
        return point == self.point and (self.when is None or
                                        bool(self.when(ctx)))

    def should_fire(self, rng: random.Random) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.at is not None and self.seen == self.at:
            return True
        if self.every is not None and self.seen % self.every == 0:
            return True
        if self.p is not None and rng.random() < self.p:
            return True
        return False


class FaultPlane:
    """Seeded registry of fault rules behind named injection points."""

    def __init__(self, seed: int = 0, counters: Optional[Counters] = None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.counters = (counters if counters is not None
                         else tier_counters("chaos"))
        self.rules: list[FaultRule] = []
        self.armed = True
        self.calls: dict[str, int] = defaultdict(int)
        #: injection ledger: (point, directive, context summary)
        self.injected: list[tuple[str, str, dict]] = []

    def rule(self, point: str, directive: str, **kw: Any) -> FaultRule:
        r = FaultRule(point, directive, **kw)
        self.rules.append(r)
        return r

    def arm(self) -> None:
        self.armed = True

    def disarm(self) -> None:
        self.armed = False

    def __call__(self, point: str, **ctx: Any) -> Optional[str]:
        """Consult the plane at an injection point. Returns a directive
        string (or None); raises SimulatedCrash for crash directives."""
        if not self.armed:
            return None
        self.calls[point] += 1
        for r in self.rules:
            if not r.matches(point, ctx):
                continue
            r.seen += 1
            if not r.should_fire(self.rng):
                continue
            r.fired += 1
            self._record(point, r.directive, ctx)
            if r.directive.startswith("crash"):
                raise SimulatedCrash(f"{point}:{r.directive}")
            return r.directive
        return None

    def _record(self, point: str, directive: str, ctx: dict) -> None:
        # keep only scalar context in the ledger (records/bodies are big
        # and often unpicklable)
        lite = {k: v for k, v in ctx.items()
                if isinstance(v, (str, int, float, bool)) or v is None}
        self.injected.append((point, directive, lite))
        self.counters.inc(f"chaos.injected.{point}.{directive}")
        self.counters.inc("chaos.faults.injected")

    # -------------------------------------------------------- introspection

    def injected_by_class(self) -> dict[str, int]:
        """Injection counts per boundary class (network / log / fanout /
        stage / device) — the soak's coverage assertion reads this."""
        out: dict[str, int] = defaultdict(int)
        for point, _, _ in self.injected:
            cls = BOUNDARY_CLASSES.get(point.split(".", 1)[0], point)
            out[cls] += 1
        return dict(out)

    def merge_ledger(self, other: "FaultPlane") -> None:
        """Fold another plane's ledger into this one (the soak runs one
        plane per phase but asserts coverage over the whole run)."""
        self.injected.extend(other.injected)
