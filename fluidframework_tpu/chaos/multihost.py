"""Chaos host-kill campaign: kill -9 ONE host group mid-traffic,
respawn it from its spec copy, and audit exact-once delivery.

``python -m fluidframework_tpu.chaos.multihost --seed N`` runs a seeded
campaign against a real 2-host-group subprocess fleet
(service/topology.py ``multihost_spec``): ``h0`` is the placement host
(shard dir, storage tier, table door), ``h1`` runs in a DISJOINT
working dir with its core on ``RemoteTableClient`` — the lease/epoch
plane reached only over the ``admin_table_*`` door. Acts:

1. **The host kill.** ``Fleet.kill_host("h1")`` SIGKILLs h1's entire
   process group with the last submissions still in flight — a machine
   dying, not a process crashing. The placement host must not notice:
   its clients' in-flight traffic drains while h1 is dead (the blast
   radius is ONE host group).
2. **The crashed recovery.** Respawn h1 with the rehydration crash
   seam armed (``FLUID_CHAOS_BOOT_CRASH=K``): the respawned core dies
   with exit code 9 mid-boot-storm — a crash INSIDE the remote-table
   boot path is just another host start.
3. **The clean recovery.** Respawn again, seam disarmed. h1's clients
   reconnect, catch up through the door-routed boot path, and resubmit
   only the tokens the sequenced history does NOT already hold.

The verdict, per doc, through a fresh verifier client: every token
appears in the final text EXACTLY once — none lost by the host kill,
none doubled by tail replay. The campaign also asserts the lazy-boot
contract (``boot.part.full_replay == 0`` fleet-wide — the respawned
group boots O(snapshot+tail) THROUGH THE DOOR, never via a shared
file) and that the epoch table names exactly one owner per partition
after recovery (exactly one sequencer — the door's fence refused any
zombie write). Same seed ⇒ same token streams and kill points.
Exit 1 on violation.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

from ..obs import tier_counters
from ..utils.telemetry import Counters
from .coldstart import TENANT, TTL, BOOT_CRASH_AFTER, TokenClient, _wait
from .monitor import InvariantViolation

#: the host group this campaign kills (the non-placement group)
VICTIM = "h1"


def run_campaign(seed: int, counters: Counters,
                 quick: bool = False) -> dict:
    from ..driver.network import _Transport
    from ..service.placement_plane import EpochTable
    from ..service.stage_runner import doc_partition
    from ..service.topology import Fleet, multihost_spec

    n_parts, n_hosts = 4, 2
    docs_per_host = 2 if quick else 4
    tokens_each = 6 if quick else 10
    work_dir = tempfile.mkdtemp(prefix="chaos-multihost-")
    fl = None
    try:
        spec = multihost_spec(os.path.join(work_dir, "fleet"),
                              n_hosts=n_hosts, cores_per_host=1,
                              n_partitions=n_parts, lease_ttl=TTL,
                              gateway_per_host=False,
                              summarize_every=1000,
                              boot_rate=50.0, boot_burst=2)
        host_parts = {h: set(spec.cores[h].prefer)
                      for h in range(n_hosts)}
        fl = Fleet(spec, subprocess=True, env={}).start()
        fl.wait_claimed()
        table = EpochTable.for_shard_dir(spec.shard_dir)

        def core_port_for(doc: str) -> int:
            part = doc_partition(TENANT, doc, n_parts)
            rec = table.read()["parts"][str(part)]
            return int(rec["addr"].rsplit(":", 1)[1])

        def reroute_and_connect(c: "TokenClient") -> None:
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    c.port = core_port_for(c.doc)
                    c.connect()
                    return
                except (RuntimeError, ConnectionError, KeyError) as e:
                    if time.monotonic() >= deadline:
                        raise
                    if isinstance(e, RuntimeError) \
                            and "not the owner" not in str(e) \
                            and "refused" not in str(e).lower():
                        raise
                    time.sleep(0.2)

        # doc names mined per host: the audit must know which docs died
        # with the victim and which never left the placement host
        def mine_docs(h: int, n: int) -> list:
            out, t = [], 0
            while len(out) < n:
                doc = f"mh{h}t{t}"
                t += 1
                if doc_partition(TENANT, doc, n_parts) in host_parts[h]:
                    out.append(doc)
            return out

        doc_sets = {h: mine_docs(h, docs_per_host)
                    for h in range(n_hosts)}
        clients = {h: [] for h in range(n_hosts)}
        for h in range(n_hosts):
            for i, doc in enumerate(doc_sets[h]):
                c = TokenClient(doc, core_port_for(doc),
                                random.Random(seed * 1000 + h * 100 + i))
                c.connect()
                clients[h].append(c)
        everyone = clients[0] + clients[1]

        # ---- seeded traffic, then summaries + checkpoints ----------
        for j in range(tokens_each - 2):
            for h in range(n_hosts):
                for i, c in enumerate(clients[h]):
                    c.insert(f"T{seed}h{h}d{i}n{j:03d}")
        if not _wait(lambda: all(c.drained() for c in everyone)):
            raise InvariantViolation("pre-kill traffic never drained")
        for c in everyone:
            t = _Transport("127.0.0.1", c.port)
            t.request_rid({"t": "admin_summarize", "tenant": TENANT,
                           "doc": c.doc})
            t.close()
        time.sleep(2.5)  # one checkpoint-ticker pass past the summary

        # ---- act 1: kill ONE host group, submissions in flight -----
        for j in range(tokens_each - 2, tokens_each):
            for h in range(n_hosts):
                for i, c in enumerate(clients[h]):
                    c.insert(f"T{seed}h{h}d{i}n{j:03d}")
        counters.inc("chaos.injected.host_kill")
        fl.kill_host(VICTIM)
        for c in clients[1]:
            c.abandon()
        # blast radius: the surviving host's in-flight traffic drains
        # while the victim is dead — the placement plane never blinked
        if not _wait(lambda: all(c.drained() for c in clients[0])):
            raise InvariantViolation(
                "the SURVIVING host's traffic stalled after a peer "
                "host group died — blast radius exceeded one host")

        # ---- act 2: respawn that crashes mid-rehydration -----------
        fl._env_cache = {**os.environ,
                         "FLUID_CHAOS_BOOT_CRASH": str(BOOT_CRASH_AFTER)}
        fl.start_host(VICTIM)
        fl.wait_claimed(parts=host_parts[1])
        crash_proc = fl.procs[1]
        # reconnecting clients ARE the boot storm; the seam kills the
        # respawned core after BOOT_CRASH_AFTER admitted boots
        for c in clients[1]:
            try:
                c.port = core_port_for(c.doc)
                c.connect()
            except Exception:  # noqa: BLE001 — core died mid-storm
                pass
        try:
            rc = crash_proc.wait(timeout=30)
        except Exception:
            rc = None
        if rc != 9:
            raise InvariantViolation(
                f"FLUID_CHAOS_BOOT_CRASH armed but the respawned core "
                f"exited {rc!r}, not 9 — the crash seam never fired "
                f"inside the remote-table boot path")
        counters.inc("chaos.injected.boot_crash")
        for c in clients[1]:
            c.abandon()
        fl.kill_host(VICTIM)  # reap the dead generation's bookkeeping

        # ---- act 3: the clean recovery -----------------------------
        fl._env_cache = dict(os.environ)
        fl.start_host(VICTIM)
        fl.wait_claimed(parts=host_parts[1])
        resubmitted = 0
        for c in clients[1]:
            reroute_and_connect(c)
            counters.inc("chaos.recovered.reconnect")
        if not _wait(lambda: all(c.drained() for c in clients[1])):
            raise InvariantViolation("post-respawn catch-up never "
                                     "drained")
        for c in clients[1]:
            n = c.resubmit_missing()
            resubmitted += n
            if n:
                counters.inc("chaos.recovered.resubmit", n)
        if not _wait(lambda: all(c.drained() for c in everyone)):
            raise InvariantViolation("resubmitted tokens never drained")

        # ---- the verdict: exact-once, through fresh verifiers ------
        losses, dupes = [], []
        for c in everyone:
            v = TokenClient(c.doc, core_port_for(c.doc),
                            random.Random(0))
            v.connect()
            ok = _wait(lambda: "default" in v.container.runtime.data_stores
                       and "text" in v.container.runtime.get_data_store(
                           "default").channels, 20)
            if not ok:
                raise InvariantViolation(
                    f"verifier for {c.doc} never booted")
            text = v.container.runtime.get_data_store(
                "default").get_channel("text").get_text()
            for t in c.tokens:
                n = text.count(t)
                if n == 0:
                    losses.append(t)
                elif n > 1:
                    dupes.append((t, n))
        if losses:
            raise InvariantViolation(
                f"{len(losses)} tokens LOST across the host-kill "
                f"cycles (first: {losses[0]})")
        if dupes:
            raise InvariantViolation(
                f"{len(dupes)} tokens DUPLICATED by tail replay "
                f"(first: {dupes[0]})")

        # ---- exactly one sequencer per partition -------------------
        rec = table.read()
        owners = {int(k): p["owner"] for k, p in rec["parts"].items()}
        if set(owners) != set(range(n_parts)):
            raise InvariantViolation(
                f"partitions unowned after recovery: {owners}")

        # ---- the lazy-boot contract, fleet-wide --------------------
        boot_counts: dict = {}
        for i, port in fl.core_ports.items():
            t = _Transport("127.0.0.1", port)
            _, reply = t.request_rid({"t": "admin_boot_status"})
            t.close()
            for k, v2 in reply["boot"]["counters"].items():
                boot_counts[k] = boot_counts.get(k, 0) + v2
        if boot_counts.get("boot.part.full_replay", 0) != 0:
            raise InvariantViolation(
                "a summarized + checkpointed doc whole-log replayed "
                f"through the remote-table boot path: {boot_counts}")
        if boot_counts.get("boot.part.lazy", 0) < docs_per_host:
            raise InvariantViolation(
                f"expected >= {docs_per_host} lazy boots on the "
                f"respawned host, saw {boot_counts}")

        return {
            "seed": seed,
            "quick": quick,
            "docs": 2 * docs_per_host,
            "tokens": 2 * docs_per_host * tokens_each,
            "resubmitted": resubmitted,
            "owners": {k: owners[k] for k in sorted(owners)},
            "boot": {k: v for k, v in sorted(boot_counts.items())
                     if k.startswith("boot.")},
            "counters": {k: v for k, v in sorted(
                counters.snapshot().items()) if k.startswith("chaos.")},
        }
    finally:
        if fl is not None:
            fl.stop()
        shutil.rmtree(work_dir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos host-kill campaign: kill -9 one host group "
                    "mid-traffic, respawn it from its spec copy, audit "
                    "exact-once delivery through the remote table door")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="fewer docs/tokens (CI smoke)")
    args = parser.parse_args(argv)
    counters = tier_counters("chaos")
    try:
        result = run_campaign(args.seed, counters, quick=args.quick)
    except InvariantViolation as e:
        print(f"HOST-KILL CAMPAIGN FAILED (seed {args.seed}): {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
