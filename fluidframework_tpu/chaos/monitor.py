"""InvariantMonitor: ride the sequenced stream, assert the protocol.

Attached directly to a document's deltas topic (the authoritative
sequenced stream — what scriptorium sees, not the lossy fan-out), the
monitor checks, per first delivery:

- ``seq`` strictly increasing with no gaps;
- ``msn`` monotone non-decreasing and ≤ ``seq``;
- clientSeq rules: ops only from joined clients, clientSeq exactly
  previous+1 per client (deli's dedupe/gap contract), joins and leaves
  sequenced at most once per client id;
- every submitted op (registered via :meth:`note_submit`) resolves
  exactly once — sequenced, nacked, or explicitly resubmitted under a
  new incarnation after a reconnect — and never twice.

Redelivery (a rewound subscriber, a crash-replayed raw log re-ticketing
the same window) is *expected* under chaos: the monitor dedupes
deliveries whose seq is not beyond the high-water mark, counting them as
observed recoveries. ``dedupe=False`` deliberately breaks that check —
the soak's self-test mode, proving replay faults are detected when the
dedupe layer is gone.

Violations are recorded, not raised, so the monitor is safe inside
server-side handlers (including other threads); :meth:`check` /
:meth:`check_quiescent` raise :class:`InvariantViolation` at the end.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from ..obs import get_recorder, tier_counters
from ..protocol.messages import MessageType
from ..utils.telemetry import Counters


class InvariantViolation(AssertionError):
    """A protocol invariant did not hold over the observed stream."""


def doc_fingerprint(text: str, props: list[dict]) -> str:
    """Order-independent-of-representation digest of a replica's visible
    state: the text plus the property map of every visible position.
    Replicas (clients, device applier, log-replayed oracle) must agree
    on this at quiescence."""
    canon = [text, [sorted((str(k), str(v)) for k, v in p.items())
                    for p in props]]
    return hashlib.sha1(
        json.dumps(canon, separators=(",", ":")).encode()).hexdigest()


class InvariantMonitor:
    def __init__(self, counters: Optional[Counters] = None,
                 dedupe: bool = True):
        self.counters = (counters if counters is not None
                         else tier_counters("chaos"))
        self.dedupe = dedupe
        self.violations: list[str] = []
        self.last_seq = 0
        self.last_msn = 0
        self.observed = 0       # first deliveries checked
        self.redelivered = 0    # deduped replays/re-tickets
        self._clients: dict[str, int] = {}       # live id → last clientSeq
        self._joined: set[str] = set()           # every id ever joined
        self._left: set[str] = set()
        # (client_id, clientSeq) → "pending"|"acked"|"nacked"|"resubmitted"
        self._submitted: dict[tuple[str, int], str] = {}

    # ------------------------------------------------------------ wiring

    def attach(self, log, topic: str) -> None:
        """Subscribe to a deltas topic on an OrderedLogBase-shaped log."""
        log.subscribe(topic, self.handler, from_offset=0)

    def handler(self, message) -> None:
        """Log-subscriber entry point: one deltas-topic record."""
        record = message.value
        batch = record.get("abatch")
        if batch is not None:
            msgs = batch.messages()
        else:
            batch = record.get("boxcar")
            msgs = batch if batch is not None else [record["message"]]
        for m in msgs:
            self.observe(m)

    # ------------------------------------------------------- the invariants

    def observe(self, m) -> None:
        seq = m.sequence_number
        if seq <= self.last_seq:
            # redelivery: a rewound subscriber or a crash-replay
            # re-ticketing an already-sequenced window. Consumers dedupe
            # by seq; so does the monitor — unless self-testing with the
            # dedupe check broken, in which case the replay falls through
            # and trips the monotonicity invariant (as it should).
            if self.dedupe:
                self.redelivered += 1
                self.counters.inc("chaos.recovered.monitor_dedup")
                return
            self._violate(f"seq not strictly increasing: "
                          f"{self.last_seq} then {seq}")
        elif seq != self.last_seq + 1:
            self._violate(f"seq gap: {self.last_seq} -> {seq}")
        msn = m.minimum_sequence_number
        if msn < self.last_msn:
            self._violate(f"msn decreased: {self.last_msn} -> {msn} "
                          f"at seq {seq}")
        if msn > seq:
            self._violate(f"msn {msn} > seq {seq}")
        self.last_seq = max(self.last_seq, seq)
        self.last_msn = max(self.last_msn, msn)
        self.observed += 1

        if m.type == MessageType.CLIENT_JOIN:
            cid = (m.contents or {}).get("clientId")
            if cid in self._joined:
                self._violate(f"duplicate join sequenced for {cid}")
            elif cid is not None:
                self._joined.add(cid)
                self._clients[cid] = 0
        elif m.type == MessageType.CLIENT_LEAVE:
            cid = (m.contents or {}).get("clientId")
            if cid is not None:
                if cid in self._left:
                    self._violate(f"duplicate leave sequenced for {cid}")
                self._left.add(cid)
                self._clients.pop(cid, None)
        elif m.type == MessageType.OPERATION and m.client_id is not None:
            self._observe_op(m.client_id, m.client_sequence_number, seq)

    def _observe_op(self, cid: str, cseq: int, seq: int) -> None:
        last = self._clients.get(cid)
        if last is None:
            self._violate(f"op at seq {seq} from non-joined client {cid}")
            return
        if cseq != last + 1:
            kind = "duplicate" if cseq <= last else "gap"
            self._violate(f"clientSeq {kind} for {cid}: expected "
                          f"{last + 1}, sequenced {cseq} at seq {seq}")
        self._clients[cid] = max(last, cseq)
        key = (cid, cseq)
        state = self._submitted.get(key)
        if state == "acked":
            self._violate(f"op {key} sequenced twice (dedupe broken)")
        elif state is not None:
            self._submitted[key] = "acked"

    # ----------------------------------------------- submission accounting

    def note_submit(self, cid: str, cseq: int) -> None:
        self._submitted[(cid, cseq)] = "pending"

    def note_nack(self, cid: str, cseq: Optional[int]) -> None:
        if cseq is None:
            return
        key = (cid, cseq)
        if self._submitted.get(key) == "acked":
            self._violate(f"op {key} nacked after being sequenced")
        elif key in self._submitted:
            self._submitted[key] = "nacked"

    def note_resubmitted(self, cid: str, cseq: int) -> None:
        """The client abandoned this (unacked, possibly lost) submission
        and resubmitted its effect under a new incarnation; the new
        incarnation's note_submit carries the accountability forward."""
        key = (cid, cseq)
        if self._submitted.get(key) == "pending":
            self._submitted[key] = "resubmitted"

    # -------------------------------------------------------------- verdict

    def _violate(self, msg: str) -> None:
        self.violations.append(msg)
        self.counters.inc("chaos.invariants.violated")
        if len(self.violations) == 1:
            # first violation triggers the flight-recorder dump: the
            # event/frame rings still hold what led up to it (later
            # violations are usually the same failure cascading)
            try:
                get_recorder().dump("invariant_violation", detail=msg)
            except Exception:
                pass

    def check(self) -> None:
        if self.violations:
            head = "\n  ".join(self.violations[:20])
            raise InvariantViolation(
                f"{len(self.violations)} invariant violation(s):\n  {head}")

    def check_quiescent(self, fingerprints: dict[str, str]) -> None:
        """Final gate: every submission resolved exactly once, every
        replica fingerprint identical. Raises on any recorded violation."""
        for key, state in sorted(self._submitted.items()):
            if state == "pending":
                self._violate(f"op {key} neither acked, nacked, nor "
                              f"resubmitted at quiescence")
        if len(set(fingerprints.values())) > 1:
            detail = ", ".join(f"{name}={fp[:12]}"
                               for name, fp in sorted(fingerprints.items()))
            self._violate(f"replicas diverged at quiescence: {detail}")
        self.check()
