"""Chaos soak: a seeded fault campaign against the full pipeline.

``python -m fluidframework_tpu.chaos.soak --seed N`` runs two phases and
asserts every invariant the monitor knows about, plus replica/device
fingerprint identity at quiescence:

- **Phase A** (in-proc, ``auto_drain=False`` — fully deterministic):
  merge-tree clients edit one document through a LocalServer while the
  fault plane tears/duplicates/rewinds log appends, drops/repeats
  broadcaster fan-out, hard-crashes the orderer (deli replays the raw
  log and re-tickets), crashes an in-soak device stage in both
  checkpoint windows, and forces the applier's wide-dispatch and
  overflow-to-host escalations. Same seed ⇒ same injections in the same
  places ⇒ the same failure reproduces exactly.
- **Phase B** (socket): clients drive a NetworkFrontEnd over real TCP
  while the driver transport drops / duplicates / reorders / truncates
  their submit frames mid-stream; recovery is the reconnect + rebase +
  resubmit path. One client rides a relay-tier gateway that gets
  kill -9'd mid-campaign and respawned on the same port (resubscribe +
  gap repair), and every client publishes presence cursors through the
  armed transport — dropped/duplicated cursor frames must be invisible
  because the coalescing lane is LWW (asserted by a post-disarm burst
  whose final state every peer must converge to). The phase then commits a service summary under a
  mid-upload crash (retry recovers), and boots late joiners through the
  columnar snapshot plane while served chunk bytes arrive torn or
  withheld — the joiners' hash checks must trip, fall back to the
  legacy tree shim (``boot.snapshot.fallback``), and still converge to
  the oracle fingerprint; a clean joiner must complete the columnar
  fast boot with a bounded backfill.

The run fails (exit 1) on any invariant violation, on missing boundary
coverage (every class — network, log, fanout, stage, device, snapshot —
must see at least one injection), or when an injected fault class shows
no matching recovery in telemetry. ``--break-dedupe`` and ``--no-recover``
are self-tests: each disables one recovery layer and the soak MUST fail,
proving the monitor actually detects what the faults inject.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import replace
from typing import Optional

from ..mergetree.client import MergeTreeClient
from ..mergetree.ops import op_to_wire
from ..obs import get_recorder, tier_counters
from ..protocol.messages import DocumentMessage, MessageType
from ..utils.telemetry import Counters
from .hooks import install
from .monitor import InvariantMonitor, InvariantViolation, doc_fingerprint
from .plane import FaultPlane, SimulatedCrash

TENANT = "chaos"
DOC = "soak"
DS_ID = "default"
CHANNEL_ID = "text"

BOUNDARY_REQUIRED = ("network", "log", "fanout", "stage", "device",
                     "snapshot", "history")

_TEXT_POOL = "abcdefgh" * 4


def _chan_msg(cseq: int, ref_seq: int, wire_op: dict) -> DocumentMessage:
    return DocumentMessage(
        client_sequence_number=cseq,
        reference_sequence_number=ref_seq,
        type=MessageType.OPERATION,
        contents={"kind": "chanop", "address": DS_ID,
                  "contents": {"address": CHANNEL_ID, "contents": wire_op}})


def _chan_contents(m):
    """The merge-tree wire op inside a sequenced message, or None."""
    if m.type != MessageType.OPERATION:
        return None
    env = m.contents
    if type(env) is not dict or env.get("kind") != "chanop" \
            or env.get("address") != DS_ID:
        return None
    inner = env["contents"]
    if inner.get("address") != CHANNEL_ID or "attach" in inner:
        return None
    return inner["contents"]


def _replica_fingerprint(replica: MergeTreeClient) -> str:
    text = replica.get_text()
    props = [replica.get_properties_at(i) or {} for i in range(len(text))]
    return doc_fingerprint(text, props)


def _container_fingerprint(container) -> str:
    """Fingerprint a full loader-stack container (the snapshot-booted
    late joiners) through its shared-string channel."""
    ss = container.runtime.get_data_store(DS_ID).get_channel(CHANNEL_ID)
    text = ss.get_text()
    props = [ss.client.get_properties_at(i) or {} for i in range(len(text))]
    return doc_fingerprint(text, props)


def wait_for(pred, timeout: float = 20.0, interval: float = 0.005) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


# =====================================================================
# Phase A: deterministic in-proc campaign
# =====================================================================


class SoakClient:
    """One editing client: a MergeTreeClient replica over a LocalServer
    connection, with the full recovery protocol — seq dedupe, gap repair
    through delta storage, and reconnect + rebase + resubmit."""

    def __init__(self, server, monitor: InvariantMonitor, counters: Counters,
                 rng: random.Random, recover: bool = True):
        self.server = server
        self.monitor = monitor
        self.counters = counters
        self.rng = rng
        self.recover = recover
        self.replica: MergeTreeClient | None = None
        self.conn = None
        self.cseq = 0
        self.last_seq = 0
        self.nacked = False
        self.unresolved: list[int] = []  # this incarnation's open cseqs
        self.reconnects = 0
        self.connect()

    # ---------------------------------------------------------- lifecycle

    def connect(self) -> None:
        conn = self.server.connect(TENANT, DOC)
        self.conn = conn
        if self.replica is None:
            self.replica = MergeTreeClient(conn.client_id)
        else:
            self.replica.update_client_id(conn.client_id)
        self.cseq = 0
        self.nacked = False
        self.unresolved = []
        conn.on_ops = self._on_ops
        conn.on_nack = self._on_nack

    def reconnect(self) -> None:
        """Call only at drain quiescence: abandon open submissions, take a
        new incarnation, rebase pending ops, resubmit."""
        old_id = self.conn.client_id
        self.conn.disconnect()
        for cseq in self.unresolved:
            self.monitor.note_resubmitted(old_id, cseq)
        self.connect()
        self.reconnects += 1
        self.counters.inc("chaos.recovered.reconnect")
        self.catch_up()
        for op in self.replica.regenerate_pending_ops():
            self._submit_wire(op_to_wire(op))

    def catch_up(self) -> None:
        """Backfill any sequenced ops this replica missed (dropped
        broadcasts, disconnect windows) from delta storage."""
        missed = self.server.get_deltas(TENANT, DOC, self.last_seq, 10 ** 9)
        if missed:
            self.counters.inc("chaos.recovered.gap_repair")
        for m in missed:
            if m.sequence_number > self.last_seq:
                self._apply(m)

    # ------------------------------------------------------------ inbound

    def _on_ops(self, batch) -> None:
        for m in batch:
            seq = m.sequence_number
            if seq <= self.last_seq:
                # redelivered (rewound subscriber / crash re-ticket /
                # repeated broadcast): clients dedupe by seq
                self.counters.inc("chaos.recovered.client_dedup")
                continue
            if seq > self.last_seq + 1:
                # a dropped broadcast left a gap: repair from delta
                # storage before applying the new message
                self.counters.inc("chaos.recovered.gap_repair")
                for g in self.server.get_deltas(TENANT, DOC,
                                                self.last_seq, seq):
                    if g.sequence_number > self.last_seq:
                        self._apply(g)
            self._apply(m)

    def _apply(self, m) -> None:
        self.last_seq = m.sequence_number
        wire = _chan_contents(m)
        if wire is not None:
            if self.replica.is_own_message(m.client_id):
                self.unresolved = [c for c in self.unresolved
                                   if c != m.client_sequence_number]
            self.replica.apply_msg(replace(m, contents=wire))
        else:
            # join/leave/noop/summary traffic: advance the window only
            self.replica.tree.current_seq = max(
                self.replica.tree.current_seq, m.sequence_number)
            self.replica.tree.update_min_seq(m.minimum_sequence_number)

    def _on_nack(self, nack) -> None:
        self.nacked = True
        op = getattr(nack, "operation", None)
        cseq = getattr(op, "client_sequence_number", None)
        self.monitor.note_nack(self.conn.client_id, cseq)
        if cseq is not None:
            self.unresolved = [c for c in self.unresolved if c != cseq]

    # ----------------------------------------------------------- outbound

    def _submit_wire(self, wire_op: dict) -> None:
        self.cseq += 1
        self.monitor.note_submit(self.conn.client_id, self.cseq)
        self.unresolved.append(self.cseq)
        self.conn.submit([_chan_msg(
            self.cseq, self.replica.tree.current_seq, wire_op)])

    def edit(self, n_ops: int) -> None:
        if self.nacked:
            return  # wedged until the next quiescent reconnect
        rng = self.rng
        for _ in range(n_ops):
            length = self.replica.get_length()
            r = rng.random()
            if length > 4 and r < 0.3:
                start = rng.randrange(length - 1)
                end = start + 1 + rng.randrange(min(length - start - 1, 4))
                op = self.replica.remove_range_local(start, end)
            elif length > 1 and r < 0.35:
                start = rng.randrange(length - 1)
                end = start + 1 + rng.randrange(min(length - start - 1, 4))
                op = self.replica.annotate_range_local(
                    start, end, {"k": rng.randrange(4)})
            else:
                off = rng.randrange(8)
                text = _TEXT_POOL[off:off + 1 + rng.randrange(6)]
                op = self.replica.insert_text_local(
                    rng.randrange(length + 1), text)
            self._submit_wire(op_to_wire(op))

    @property
    def settled(self) -> bool:
        return not self.unresolved and not self.nacked \
            and not self.replica.pending


class DeviceStage:
    """In-soak stand-in for stage_runner.ApplierStage: a TPU applier
    consuming the deltas topic with the same checkpoint protocol (farm
    save BEFORE offset save), stepped synchronously so the soak can kill
    it exactly inside either crash window and run the real restore."""

    def __init__(self, server, plane: FaultPlane, counters: Counters,
                 state_dir: str, mesh_shards: int = 0):
        from ..service.tpu_applier import TpuDocumentApplier

        self.server = server
        self.plane = plane
        self.counters = counters
        self.ckpt = os.path.join(state_dir, "applier")
        self.topic = f"deltas/{TENANT}/{DOC}"
        # mesh_shards > 0 runs the stage's applier over a doc-sharded
        # device mesh (the multi-chip fast lane) — the whole
        # crash/checkpoint/restore protocol must hold there too
        self.mesh_shards = mesh_shards
        self.applier = TpuDocumentApplier(
            max_docs=8, max_slots=64,
            **({"mesh": mesh_shards} if mesh_shards else {}))
        self.applier.set_replay_source(self._replay_from_log)
        self._offset = -1   # highest offset consumed
        self._handler = None
        self._subscribe(0)

    def _replay_from_log(self, tenant_id, document_id):
        """Escalation replay source reading the deltas LOG, not the
        scriptorium db: the log record is durable before any subscriber
        (scriptorium included) sees it, so this source can never lag the
        applier's own subscription the way a db-backed channel_stream can
        when this stage's handler is dispatched ahead of scriptorium's.
        Re-ticketed duplicate windows (orderer hard-crash) are deduped by
        sequence number."""
        topic = f"deltas/{tenant_id}/{document_id}"
        last = 0
        for off in range(self.server.log.length(topic)):
            value = self.server.log.read(topic, off)
            batch = value.get("boxcar")
            for m in (batch if batch is not None else [value["message"]]):
                if m.sequence_number <= last:
                    continue
                wire = _chan_contents(m)
                if wire is None:
                    continue
                last = m.sequence_number
                yield replace(m, contents=wire)

    def _subscribe(self, from_offset: int) -> None:
        def on_deltas(message):
            self._offset = message.offset
            value = message.value
            batch = value.get("boxcar")
            msgs = batch if batch is not None else [value["message"]]
            applied = self.applier.applied_seq(TENANT, DOC)
            pairs = []
            for m in msgs:
                # replay idempotency: the farm checkpoint lands before
                # the offset checkpoint, so a crash between them replays
                # already-applied ops — skip by sequence number
                if m.sequence_number <= applied:
                    continue
                wire = _chan_contents(m)
                if wire is not None:
                    pairs.append((m, wire))
            if pairs:
                self.applier.ingest_batch(TENANT, DOC, pairs)

        self._handler = on_deltas
        self.server.log.subscribe(self.topic, on_deltas,
                                  from_offset=from_offset)

    def checkpoint(self) -> None:
        from ..service.tpu_applier import save_applier_checkpoint

        # crash window 1: consumed but nothing saved
        self.plane("stage.pre_checkpoint", stage="DeviceStage")
        self.applier.flush()
        self.applier.finalize()
        save_applier_checkpoint(self.applier, self.ckpt)
        # crash window 2: farm saved, offsets not — restart replays a
        # window of already-applied ops against the NEWER farm
        self.plane("stage.post_checkpoint", stage="DeviceStage")
        with open(self.ckpt + ".off", "w") as f:
            json.dump({"offset": self._offset}, f)

    def restore(self) -> None:
        """The post-kill restart: reload the last durable farm + offset,
        re-subscribe; the replayed window is absorbed by skip-by-seq."""
        from ..service.tpu_applier import (TpuDocumentApplier,
                                           load_applier_checkpoint)

        self.server.log.unsubscribe(self.topic, self._handler)
        kw = {"mesh": self.mesh_shards} if self.mesh_shards else {}
        if os.path.exists(self.ckpt + ".json"):
            self.applier = load_applier_checkpoint(self.ckpt, **kw)
        else:
            self.applier = TpuDocumentApplier(max_docs=8, max_slots=64, **kw)
        self.applier.set_replay_source(self._replay_from_log)
        start = 0
        if os.path.exists(self.ckpt + ".off"):
            with open(self.ckpt + ".off") as f:
                start = json.load(f)["offset"] + 1
        self._offset = start - 1
        self._subscribe(start)
        self.counters.inc("chaos.recovered.stage_restart")

    def fingerprint(self) -> str:
        self.applier.finalize()
        text = self.applier.get_text(TENANT, DOC)
        props = [self.applier.get_properties_at(TENANT, DOC, i) or {}
                 for i in range(len(text))]
        return doc_fingerprint(text, props)


def _schedule_phase_a(plane: FaultPlane) -> None:
    def client_boxcar(ctx):
        return ctx["topic"].startswith("rawops/") \
            and type(ctx["record"]).__name__ == "RawBoxcar"

    def deltas(ctx):
        return ctx["topic"].startswith("deltas/")

    plane.rule("log.append", "torn", every=9, times=2, when=client_boxcar)
    plane.rule("log.append", "dup", every=13, times=2, when=client_boxcar)
    plane.rule("log.append", "rewind", every=11, times=2, when=deltas)
    plane.rule("broadcast.publish", "drop", every=10, times=2)
    plane.rule("broadcast.publish", "dup", every=7, times=2)
    plane.rule("applier.dispatch", "force_wide", at=1)
    # escalation late enough (ingest consult ~26 ≈ round 8 of quick's 10)
    # that the overlap-window crash rules below see the doc still on the
    # DEVICE lane — the earlier at=6 escalated the soak's single doc to
    # host in round 1 and starved every later dispatch seam
    plane.rule("applier.ingest", "escalate_host", at=26)
    plane.rule("stage.pre_checkpoint", "crash", at=3)
    plane.rule("stage.post_checkpoint", "crash", at=5)
    plane.rule("stage.crash", "orderer_hard", at=4)
    # overlap-window crashes, BOTH orders: "staged" kills the stage host
    # after wave N+1 is staged (device buffers resident, step not issued)
    # — restore must replay exactly that unexecuted wave; "inflight"
    # kills it after wave N's step is issued but before the next wave
    # stages — the restored farm reloads the last durable checkpoint and
    # skip-by-seq absorbs the already-applied window (no double-apply)
    plane.rule("applier.stage.staged", "crash", at=2)
    plane.rule("applier.stage.inflight", "crash", at=3)
    # crash-mid-fork, BOTH windows: "commit" kills after the pending
    # fork commit record lands but before the doc is seeded (recovery
    # must DISCARD — the fork doc does not exist); "seeded" kills after
    # seeding but before the ref flips (recovery must ADOPT — the doc
    # is durable, only the refs are missing). Either way no ref dangles.
    plane.rule("history.fork", "crash", at=1,
               when=lambda ctx: ctx.get("stage") == "commit")
    plane.rule("history.fork", "crash", at=1,
               when=lambda ctx: ctx.get("stage") == "seeded")


def run_phase_a(seed: int, counters: Counters, rounds: int = 24,
                n_clients: int = 3, recover: bool = True,
                break_dedupe: bool = False,
                mesh_shards: int = 0) -> tuple[FaultPlane,
                                               InvariantMonitor]:
    from ..service.local_server import LocalServer

    monitor = InvariantMonitor(counters, dedupe=not break_dedupe)
    plane = FaultPlane(seed, counters)
    _schedule_phase_a(plane)

    server = LocalServer(auto_drain=False)
    monitor.attach(server.log, f"deltas/{TENANT}/{DOC}")
    uninstall = install(plane, server=server)
    try:
        with tempfile.TemporaryDirectory(prefix="chaos-soak-") as state_dir:
            device = DeviceStage(server, plane, counters, state_dir,
                                 mesh_shards=mesh_shards)
            install(plane, appliers=[device.applier])
            rng = random.Random(seed)
            clients = [SoakClient(server, monitor, counters,
                                  random.Random(seed * 1000 + i),
                                  recover=recover)
                       for i in range(n_clients)]
            server.drain()

            for rnd in range(rounds):
                for c in clients:
                    c.edit(1 + rng.randrange(2))
                server.drain()
                if plane("stage.crash", round=rnd) == "orderer_hard":
                    # kill -9 of the document pipeline BEFORE this
                    # round's checkpoint lands: the rebuilt deli replays
                    # the raw log from the previous checkpoint and
                    # re-tickets the whole round with identical seqs —
                    # every consumer must dedupe the duplicate window
                    server.crash_orderer(TENANT, DOC)
                    counters.inc("chaos.recovered.orderer_restart")
                    server.drain()
                try:
                    device.checkpoint()
                except SimulatedCrash:
                    device.restore()
                    server.drain()
                    # the freshly-armed restored applier keeps the seam
                    install(plane, appliers=[device.applier])
                server.checkpoint_all()
                if recover:
                    for c in clients:
                        if c.nacked:
                            c.reconnect()
                    server.drain()

            # crash-mid-fork drill: tear a fork at both windows and
            # require restart recovery to adopt-or-discard atomically
            _exercise_fork_crash(server, counters)

            # settle: stop injecting, resolve every open submission
            plane.disarm()
            for _ in range(6):
                server.drain()
                if all(c.settled for c in clients):
                    break
                if recover:
                    for c in clients:
                        if not c.settled:
                            c.reconnect()
            server.drain()
            for c in clients:
                c.catch_up()
            try:
                device.checkpoint()
            except SimulatedCrash:  # pragma: no cover - plane is disarmed
                device.restore()
                server.drain()

            fps = {f"client{i}": _replica_fingerprint(c.replica)
                   for i, c in enumerate(clients)}
            fps["device"] = device.fingerprint()
            fps["oracle"] = _oracle_fingerprint(server)
            monitor.check_quiescent(fps)
            if monitor.observed < 10:
                raise InvariantViolation(
                    f"phase A observed only {monitor.observed} sequenced "
                    "messages — the workload did not run")
    finally:
        uninstall()
    return plane, monitor


def _exercise_fork_crash(server, counters: Counters) -> None:
    """Tear a fork at BOTH crash windows (scheduled in
    ``_schedule_phase_a``), simulate the restart by rebuilding the
    history plane over the same durable records, and require recovery
    to adopt-or-discard atomically. A dangling ref — a fork commit no
    ref covers and no discard marker abandons — is an invariant
    violation, as is adopting an unseeded fork or discarding a seeded
    one."""
    from ..service.history_plane import (
        MAIN_REF,
        HistoryPlane,
        fork_pin_ref,
    )
    from ..service.service_summarizer import (
        HostReplicaSource,
        ServiceSummarizer,
    )

    # forks boot from committed generations: put one on the graph
    ServiceSummarizer(server, HostReplicaSource(server)).summarize_doc(
        TENANT, DOC)

    def torn_fork(new_doc: str) -> None:
        try:
            server.history.fork(TENANT, DOC, new_doc=new_doc)
        except SimulatedCrash:
            return
        raise InvariantViolation(
            f"scheduled crash-mid-fork of {new_doc} did not fire")

    # window 1: commit record written, doc NOT seeded → must discard
    torn_fork("soak-fork-torn")
    rebooted = HistoryPlane(server)  # the restart: fresh in-memory state
    fstore = rebooted._store(TENANT, "soak-fork-torn")
    pstore = rebooted._store(TENANT, DOC)
    dangling = [cid for cid in fstore.commits
                if cid not in set(fstore.refs.values())
                and cid not in fstore.discarded]
    if dangling:
        raise InvariantViolation(
            f"fork recovery left dangling commits {dangling}")
    if fstore.refs or fork_pin_ref(TENANT, "soak-fork-torn") in pstore.refs:
        raise InvariantViolation(
            "recovery adopted an UNSEEDED fork (refs exist for a doc "
            "with no durable v0)")
    counters.inc("chaos.recovered.history_recover")

    # window 2: doc seeded, refs NOT flipped → must adopt
    torn_fork("soak-fork-seeded")
    rebooted = HistoryPlane(server)
    fstore = rebooted._store(TENANT, "soak-fork-seeded")
    pstore = rebooted._store(TENANT, DOC)
    if MAIN_REF not in fstore.refs \
            or fork_pin_ref(TENANT, "soak-fork-seeded") not in pstore.refs:
        raise InvariantViolation(
            "recovery discarded a SEEDED fork (durable v0 exists but "
            "refs were not restored)")
    # the adopted fork must actually serve history reads post-restart
    head = fstore.commits[fstore.refs[MAIN_REF]]
    rebooted.replay_read(TENANT, "soak-fork-seeded", head["base_seq"])
    counters.inc("chaos.recovered.history_recover")


def _oracle_fingerprint(server) -> str:
    """Replay the authoritative sequenced log into a fresh replica — the
    from-scratch consumer every other replica must agree with."""
    from ..service.tpu_applier import channel_stream

    oracle = MergeTreeClient("chaos/oracle")
    for m in channel_stream(server, TENANT, DOC, DS_ID, CHANNEL_ID):
        oracle.apply_msg(m, local=False)
    return _replica_fingerprint(oracle)


# =====================================================================
# Phase B: socket transport campaign
# =====================================================================


class NetSoakClient:
    """A driver-stack client over real TCP whose submit frames are being
    dropped / duplicated / reordered / cut mid-frame."""

    def __init__(self, service, monitor: InvariantMonitor,
                 counters: Counters, rng: random.Random,
                 coalesce_window: float | None = None):
        self.service = service
        self.monitor = monitor
        self.counters = counters
        self.rng = rng
        self.coalesce_window = coalesce_window
        self.replica: MergeTreeClient | None = None
        self.conn = None
        self.cseq = 0
        self.last_seq = 0
        self.dead = False
        self.nacked = False
        self.unresolved: list[int] = []
        self.reconnects = 0
        #: LWW view of peers' presence: (client_id, type) -> content —
        #: exactly the state the coalescing lane guarantees converges
        self.seen_presence: dict = {}
        self.connect()

    def connect(self) -> None:
        conn = self.service.connect_to_delta_stream()
        if self.coalesce_window is not None:
            # force the driver's ingress coalescer on so the fault plane
            # exercises MULTI-OP boxcars, not just per-op frames
            conn.coalesce_window = self.coalesce_window
        self.conn = conn
        self.dead = False
        self.nacked = False
        self.cseq = 0
        self.unresolved = []
        if self.replica is None:
            self.replica = MergeTreeClient(conn.client_id)
        else:
            self.replica.update_client_id(conn.client_id)
        conn.on_disconnect = lambda reason: setattr(self, "dead", True)
        # backfill BEFORE attaching on_op: live pushes buffer until the
        # handler lands, then flush through the same seq-dedupe
        storage = self.service.connect_to_delta_storage()
        for m in storage.get_deltas(self.last_seq, 10 ** 9):
            if m.sequence_number > self.last_seq:
                self._apply(m)
        conn.on_op = self._on_op
        conn.on_nack = self._on_nack
        conn.on_signal = self._on_signal

    def _on_signal(self, sig) -> None:
        self.seen_presence[(sig.client_id, sig.type)] = sig.content

    def publish_presence(self, content) -> None:
        """An ephemeral cursor update through the armed transport; loss
        and duplication must both be invisible (LWW, no sequencing)."""
        if self.dead:
            return
        try:
            self.conn.submit_signal(content, type="cursor")
        except OSError:
            self.dead = True

    def reconnect(self) -> None:
        old_id = self.conn.client_id
        try:
            self.conn.close()
        except OSError:
            pass
        for cseq in self.unresolved:
            self.monitor.note_resubmitted(old_id, cseq)
        self.connect()
        self.reconnects += 1
        self.counters.inc("chaos.recovered.net_reconnect")
        with self.conn.lock:
            wire_ops = [op_to_wire(op)
                        for op in self.replica.regenerate_pending_ops()]
        # resubmit as ONE boxcar: the recovery path must survive the
        # same coalesced-frame faults the original submissions do
        self._submit_wires(wire_ops)

    def _on_op(self, m) -> None:
        # runs on the reader thread, under the connection lock
        if m.sequence_number <= self.last_seq:
            self.counters.inc("chaos.recovered.client_dedup")
            return
        self._apply(m)

    def _apply(self, m) -> None:
        self.last_seq = m.sequence_number
        wire = _chan_contents(m)
        if wire is not None:
            if self.replica.is_own_message(m.client_id):
                self.unresolved = [c for c in self.unresolved
                                   if c != m.client_sequence_number]
            self.replica.apply_msg(replace(m, contents=wire))
        else:
            self.replica.tree.current_seq = max(
                self.replica.tree.current_seq, m.sequence_number)
            self.replica.tree.update_min_seq(m.minimum_sequence_number)

    def _on_nack(self, nack) -> None:
        self.nacked = True
        op = getattr(nack, "operation", None)
        cseq = getattr(op, "client_sequence_number", None)
        self.monitor.note_nack(self.conn.client_id, cseq)
        if cseq is not None:
            self.unresolved = [c for c in self.unresolved if c != cseq]

    def _submit_wires(self, wire_ops: list) -> None:
        """Submit a round's ops as ONE multi-op boxcar frame — the
        coalesced shape the fault plane must tear, duplicate and reorder
        without breaking convergence."""
        if not wire_ops:
            return
        msgs = []
        for w in wire_ops:
            self.cseq += 1
            self.monitor.note_submit(self.conn.client_id, self.cseq)
            self.unresolved.append(self.cseq)
            msgs.append(_chan_msg(
                self.cseq, self.replica.tree.current_seq, w))
        try:
            self.conn.submit(msgs)
        except OSError:
            self.dead = True

    def edit(self, n_ops: int) -> None:
        if self.dead or self.nacked:
            return
        rng = self.rng
        with self.conn.lock:
            wires = []
            for _ in range(n_ops):
                length = self.replica.get_length()
                if length > 4 and rng.random() < 0.3:
                    start = rng.randrange(length - 1)
                    end = start + 1 + rng.randrange(
                        min(length - start - 1, 4))
                    op = self.replica.remove_range_local(start, end)
                else:
                    off = rng.randrange(8)
                    text = _TEXT_POOL[off:off + 1 + rng.randrange(6)]
                    op = self.replica.insert_text_local(
                        rng.randrange(length + 1), text)
                wires.append(op_to_wire(op))
            self._submit_wires(wires)

    @property
    def settled(self) -> bool:
        return not self.dead and not self.nacked and not self.unresolved \
            and not self.replica.pending


def _pick_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_relay(core_port: int, port: int):
    """A relay-tier gateway as a real OS process, so the kill seam is a
    genuine kill -9 of a fan-out tier (not a polite shutdown)."""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.gateway",
         "--core-port", str(core_port), "--port", str(port), "--python"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=repo_root)
    line = proc.stdout.readline().strip()
    if not line.startswith("LISTENING"):
        proc.kill()
        raise RuntimeError(f"relay gateway failed to start: {line!r}")
    return proc


def run_phase_b(seed: int, counters: Counters, rounds: int = 16,
                n_clients: int = 2) -> tuple[FaultPlane, InvariantMonitor]:
    from ..driver.network import (NetworkDocumentService,
                                  NetworkDocumentServiceFactory)
    from ..loader.container import Loader
    from ..service.durable_log import DurableLog
    from ..service.front_end import NetworkFrontEnd
    from ..service.local_server import LocalServer
    from ..service.service_summarizer import (HostReplicaSource,
                                              ServiceSummarizer)

    monitor = InvariantMonitor(counters)
    plane = FaultPlane(seed + 1, counters)

    def submit_frames(ctx):
        return ctx.get("kind") == "submit"

    def deltas_abatch(ctx):
        record = ctx.get("record")
        return ctx["topic"].startswith("deltas/") \
            and isinstance(record, dict) and "abatch" in record

    def signal_frames(ctx):
        return ctx.get("kind") == "signal"

    plane.rule("net.send", "drop", at=4, when=submit_frames)
    plane.rule("net.send", "dup", every=5, times=2, when=submit_frames)
    plane.rule("net.send", "delay", at=9, when=submit_frames)
    plane.rule("net.send", "truncate", at=14, when=submit_frames)
    # presence lane: drop and duplicate ephemeral cursor frames — the
    # LWW coalescing lane must make BOTH invisible (no gap repair, no
    # dedupe bookkeeping; a later publish simply overwrites)
    plane.rule("net.send", "drop", every=4, times=3, when=signal_frames)
    plane.rule("net.send", "dup", every=5, times=3, when=signal_frames)
    # relay-tier kill: one fan-out gateway dies mid-campaign and is
    # respawned on the same port; its clients must ride reconnect +
    # gap repair through the fresh tier
    plane.rule("relay.kill", "down", at=5)
    # columnar segment-tail tears: a power cut mid seg_append leaves
    # ragged bytes the torn-tail scan must cut before the re-append —
    # unlike the rawops torn (record lost, client resubmits), a deltas
    # record is already ticketed and must SURVIVE the tear
    # (abatch records are sparse in quick mode — a handful of coalesced
    # boxcars per run — so schedule by match ordinal, not a wide stride)
    plane.rule("log.append", "torn", at=1, when=deltas_abatch)
    plane.rule("log.append", "torn", every=3, times=1, when=deltas_abatch)

    log_dir = tempfile.mkdtemp(prefix="chaos-soak-seg-")
    server = LocalServer(log=DurableLog(log_dir))
    monitor.attach(server.log, f"deltas/{TENANT}/{DOC}")
    front = NetworkFrontEnd(server).start_background()
    relay_port = _pick_port()
    relay = _spawn_relay(front.port, relay_port)
    uninstall = install(plane, transports=True, server=server)
    uninstall_snap: list = []
    joiners: list = []
    try:
        # the LAST client rides the relay tier; the rest dial the core
        # directly — so a relay kill takes out one subscriber path while
        # the writers keep the stream moving
        ports = [front.port] * (n_clients - 1) + [relay_port]
        clients = [
            NetSoakClient(
                NetworkDocumentService("127.0.0.1", ports[i], TENANT,
                                       DOC, counters=counters),
                monitor, counters, random.Random(seed * 7000 + i),
                coalesce_window=0.02)
            for i in range(n_clients)]
        rng = random.Random(seed + 2)
        for rnd in range(rounds):
            for c in clients:
                if c.dead or c.nacked:
                    c.reconnect()
                c.edit(1 + rng.randrange(2))
                c.publish_presence({"round": rnd})
            if plane("relay.kill", round=rnd) == "down":
                # kill -9 the fan-out tier, then bring a fresh one up on
                # the SAME port: the relay client's reconnect loop must
                # resubscribe through it and gap-repair what it missed
                relay.kill()
                relay.wait(timeout=10)
                relay = _spawn_relay(front.port, relay_port)
                counters.inc("chaos.recovered.relay_respawn")
            time.sleep(0.01)

        # ---- snapshot fast-boot campaign (plane still armed) ----
        # quiesce the stream so the summarizer's coverage gate and its
        # replica ingest observe the same prefix
        orderer = server._get_orderer(TENANT, DOC)

        def _stream_stable():
            s0 = orderer.deli.sequence_number
            time.sleep(0.05)
            return orderer.deli.sequence_number == s0

        wait_for(_stream_stable, timeout=10.0)

        # the first summarize dies mid-upload (chunks + version record
        # durable, commit never ran — the version must stay invisible);
        # recovery is a restarted summarizer redoing the pass, with the
        # content-addressed chunk store absorbing the re-upload
        plane.rule("snapshot.upload", "crash", every=1, times=1)
        summarizer = ServiceSummarizer(server, HostReplicaSource(server))
        uninstall_snap.append(install(plane, fronts=[front],
                                      summarizers=[summarizer]))
        version = None
        for _ in range(5):
            try:
                version = summarizer.summarize_doc(TENANT, DOC)
                break
            except SimulatedCrash:
                counters.inc("chaos.recovered.summary_retry")
                summarizer = ServiceSummarizer(server,
                                               HostReplicaSource(server))
                uninstall_snap.append(
                    install(plane, summarizers=[summarizer]))
            except RuntimeError:
                # stream advanced between gate scan and ingest: re-wait
                wait_for(_stream_stable, timeout=10.0)
        if version is None:
            raise InvariantViolation(
                "phase B never committed a service summary — the "
                "snapshot campaign has nothing to boot from")

        def _join():
            # each joiner gets a COLD factory (fresh snapshot/chunk
            # cache) sharing the campaign counters, so every boot pulls
            # real chunk frames through the armed serving seam
            factory = NetworkDocumentServiceFactory(
                "127.0.0.1", front.port, counters=counters)
            return Loader(factory).resolve(TENANT, DOC)

        plane.rule("snapshot.chunk", "torn", every=1, times=1)
        joiners.append(_join())   # torn wire bytes: hash check → fallback
        plane.rule("snapshot.chunk", "drop", every=1, times=1)
        joiners.append(_join())   # withheld chunk: hole → fallback
        joiners.append(_join())   # clean columnar fast boot

        # settle: stop injecting, then resolve every open submission
        plane.disarm()
        for _ in range(8):
            for c in clients:
                if c.dead or c.nacked or c.unresolved:
                    c.reconnect()
            if wait_for(lambda: all(c.settled for c in clients),
                        timeout=5.0):
                break
        server_seq = server._get_orderer(TENANT, DOC).deli.sequence_number
        wait_for(lambda: all(c.last_seq >= server_seq for c in clients))
        for c in clients:
            if c.last_seq < server_seq:
                with c.conn.lock:
                    storage = c.service.connect_to_delta_storage()
                    for m in storage.get_deltas(c.last_seq, 10 ** 9):
                        if m.sequence_number > c.last_seq:
                            c._apply(m)

        # joiners are live containers: wait until each has processed the
        # whole sequenced stream before fingerprinting
        for j in joiners:
            wait_for(lambda: j.delta_manager.last_processed_seq
                     >= server_seq)

        # ---- presence lane: post-disarm final burst, LWW convergence.
        # Every armed-phase drop/dup of a cursor frame must be invisible
        # BY DESIGN: a later publish overwrites, so after a clean final
        # burst every client's last-seen state per peer is the peer's
        # final publish — no gap repair, no dedupe, no sequencing.
        ids = [c.conn.client_id for c in clients]

        def _final(i):
            return {"final": ids[i], "k": 9}

        def _presence_converged():
            return all(
                cj.seen_presence.get((ids[i], "cursor")) == _final(i)
                for i, _ in enumerate(clients)
                for j, cj in enumerate(clients) if j != i)

        def _burst_and_check():
            for k in range(10):
                for i, c in enumerate(clients):
                    c.publish_presence({"final": ids[i], "k": k})
            time.sleep(0.05)  # two flush ticks
            return _presence_converged()

        if not wait_for(_burst_and_check, timeout=20.0, interval=0.05):
            raise InvariantViolation(
                "presence lane failed LWW convergence after the "
                "post-disarm burst — a dropped/duplicated cursor frame "
                "left visible damage")
        counters.inc("chaos.recovered.presence_lww")
        psnap = front.counters.snapshot()
        if not psnap.get("presence.lane.signals", 0):
            raise InvariantViolation(
                "phase B published cursor frames but the presence lane "
                "never saw one — signals bypassed the coalescing tier")
        if not psnap.get("presence.lane.coalesced", 0):
            raise InvariantViolation(
                "the presence bursts never coalesced — the LWW lane "
                "went unexercised under faults")

        fps = {}
        for i, c in enumerate(clients):
            with c.conn.lock:
                fps[f"net-client{i}"] = _replica_fingerprint(c.replica)
        for i, j in enumerate(joiners):
            fps[f"joiner{i}"] = _container_fingerprint(j)
        fps["oracle"] = _oracle_fingerprint(server)
        monitor.check_quiescent(fps)
        snap = counters.snapshot()
        fallbacks = snap.get("boot.snapshot.fallback", 0)
        if fallbacks < 2:
            raise InvariantViolation(
                "phase B injected torn + dropped snapshot chunks but "
                f"the boot fallback fired only {fallbacks} times — a "
                "corrupted chunk boot went unnoticed")
        if not snap.get("boot.snapshot.used", 0):
            raise InvariantViolation(
                "phase B never completed a clean columnar snapshot "
                "boot — the fast-boot path went unexercised under "
                "faults")
        if not snap.get("boot.backfill.bounded", 0):
            raise InvariantViolation(
                "the clean snapshot boot never took the bounded "
                "backfill — catch-up degenerated to whole-log replay")
        fsnap = front.counters.snapshot()
        if fsnap.get("storage.snapshot.encodes", 0) != 1:
            raise InvariantViolation(
                "snapshot serving re-encoded per join under faults "
                f"(encodes={fsnap.get('storage.snapshot.encodes', 0)}"
                ", expected the one-time framed-cache fill)")
        if monitor.observed < 10:
            raise InvariantViolation(
                f"phase B observed only {monitor.observed} sequenced "
                "messages — the workload did not run")
        snap = counters.snapshot()
        frames = snap.get("driver.submit.frames", 0)
        ops = snap.get("driver.submit.ops", 0)
        if not frames or ops <= frames:
            raise InvariantViolation(
                "phase B never drove a multi-op boxcar through the "
                f"fault plane (frames={frames}, ops={ops}) — the "
                "coalesced submit path went unexercised")
        if not snap.get("driver.submit.columnar", 0):
            # columnar frames keep kind="submit" on the net.send seam,
            # so the drop/dup/delay/truncate rules above faulted them;
            # a zero counter means the fast path silently disengaged
            # and the soak stopped covering it
            raise InvariantViolation(
                "phase B never drove a COLUMNAR boxcar through the "
                "fault plane — the columnar ingress path went "
                "unexercised under faults")
        seg = server.log.counters.snapshot()
        if not seg.get("storage.segment.appends", 0):
            raise InvariantViolation(
                "phase B ran over a DurableLog but no columnar segment "
                "block was ever appended — the segment lane went "
                "unexercised under faults")
        torn = seg.get("storage.segment.torn", 0)
        if not torn:
            raise InvariantViolation(
                "phase B never tore a columnar segment tail — the "
                "torn-tail recovery scan went unexercised")
        # the tear left physical ragged bytes and the untear+re-append
        # cycle recovered every one (the record survived: convergence
        # above already proved no seq gap) — record the recovery so the
        # injected↔recovered cross-check can pair it
        counters.inc("chaos.recovered.segment_untear", torn)
        for c in clients:
            c.conn.close()
    finally:
        for j in joiners:
            j.close()
        relay.terminate()
        relay.wait(timeout=10)
        while uninstall_snap:
            uninstall_snap.pop()()
        uninstall()
        front.stop()
        # Deliberately NOT server.log.close(): lingering session-close
        # callbacks on the front's (now stopped) loop still run at task
        # destruction and append their disconnect records; a closed log
        # turns that into interpreter-exit OSError noise. The open fds
        # keep the unlinked files writable until process exit.
        server.log.flush()
        shutil.rmtree(log_dir, ignore_errors=True)
    return plane, monitor


# =====================================================================
# The campaign
# =====================================================================


def _check_coverage(planes: list[FaultPlane]) -> dict[str, int]:
    merged = planes[0]
    for p in planes[1:]:
        merged.merge_ledger(p)
    by_class = merged.injected_by_class()
    missing = [cls for cls in BOUNDARY_REQUIRED if not by_class.get(cls)]
    if missing:
        raise InvariantViolation(
            f"boundary coverage incomplete: no fault injected for "
            f"{missing}; got {by_class}")
    return by_class


def _cross_check(counters: Counters) -> None:
    """Faults injected must show matching recoveries in telemetry — an
    injection point nobody recovers from is a silent hole."""
    snap = counters.snapshot()

    def count(prefix):
        return sum(v for k, v in snap.items()
                   if k.startswith(prefix) and isinstance(v, int))

    expectations = [
        # torn has TWO recovery paths by design: a rawops tear loses the
        # record (client reconnect+resubmit); a columnar segment tear
        # leaves physical ragged bytes the untear scan cuts before the
        # re-append (record survives)
        ("chaos.injected.log.append.torn",
         ("chaos.recovered.reconnect", "chaos.recovered.segment_untear")),
        ("chaos.injected.log.append.rewind",
         "chaos.recovered.monitor_dedup"),
        ("chaos.injected.broadcast.publish.drop",
         "chaos.recovered.gap_repair"),
        ("chaos.injected.broadcast.publish.dup",
         "chaos.recovered.client_dedup"),
        ("chaos.injected.stage.pre_checkpoint",
         "chaos.recovered.stage_restart"),
        ("chaos.injected.stage.post_checkpoint",
         "chaos.recovered.stage_restart"),
        # overlap-window crashes (both orders) recover through the same
        # checkpoint+replay restart path — dropping either seam or its
        # recovery would open a silent hole in the stage/execute split
        ("chaos.injected.applier.stage.staged",
         "chaos.recovered.stage_restart"),
        ("chaos.injected.applier.stage.inflight",
         "chaos.recovered.stage_restart"),
        ("chaos.injected.stage.crash", "chaos.recovered.orderer_restart"),
        ("chaos.injected.net.send.truncate",
         "chaos.recovered.net_reconnect"),
        # a dropped frame is either a submit (reconnect + resubmit) or a
        # presence cursor (the LWW lane makes the loss invisible — the
        # convergence check stamps presence_lww when it proves it)
        ("chaos.injected.net.send.drop",
         ("chaos.recovered.net_reconnect",
          "chaos.recovered.presence_lww")),
        # a duplicated frame is absorbed by seq-dedupe (submits) or by
        # the presence lane's LWW overwrite (signals)
        ("chaos.injected.net.send.dup",
         ("chaos.recovered.client_dedup",
          "chaos.recovered.presence_lww")),
        # the relay-tier kill recovers through respawn + the relay
        # client's reconnect loop
        ("chaos.injected.relay.kill.down",
         ("chaos.recovered.relay_respawn",
          "chaos.recovered.net_reconnect")),
        # snapshot plane: a torn/withheld served chunk must trip the
        # booting client's verify and route it down the legacy-tree
        # fallback; a mid-upload summarizer crash must be absorbed by
        # the restarted pass
        ("chaos.injected.snapshot.chunk.torn", "boot.snapshot.fallback"),
        ("chaos.injected.snapshot.chunk.drop", "boot.snapshot.fallback"),
        ("chaos.injected.snapshot.upload.crash",
         "chaos.recovered.summary_retry"),
        # a crash mid-fork (either window) recovers through the history
        # plane's adopt-or-discard pass on the next load
        ("chaos.injected.history.fork.crash",
         "chaos.recovered.history_recover"),
    ]
    problems = []
    for injected, recovered in expectations:
        alternatives = (recovered,) if isinstance(recovered, str) \
            else recovered
        if count(injected) > 0 and not any(count(r) for r in alternatives):
            problems.append(f"{injected}={count(injected)} but "
                            f"{'/'.join(alternatives)}=0")
    if problems:
        raise InvariantViolation(
            "faults injected without observed recoveries: "
            + "; ".join(problems))


def run_soak(seed: int, quick: bool = False, break_dedupe: bool = False,
             no_recover: bool = False, phases: str = "ab",
             mesh_shards: int = 0) -> dict:
    counters = tier_counters("chaos")
    planes = []
    monitors = []
    if "a" in phases:
        plane_a, mon_a = run_phase_a(
            seed, counters,
            rounds=10 if quick else 24,
            recover=not no_recover, break_dedupe=break_dedupe,
            mesh_shards=mesh_shards)
        planes.append(plane_a)
        monitors.append(mon_a)
    if "b" in phases:
        plane_b, mon_b = run_phase_b(seed, counters,
                                     rounds=8 if quick else 16)
        planes.append(plane_b)
        monitors.append(mon_b)
    coverage = _check_coverage(planes) if phases == "ab" else \
        planes[0].injected_by_class()
    _cross_check(counters)
    flight_dump = _check_flight_dump(counters) if "a" in phases else None
    return {
        "seed": seed,
        "coverage": coverage,
        "observed": sum(m.observed for m in monitors),
        "redelivered": sum(m.redelivered for m in monitors),
        "flight_dump": flight_dump,
        "counters": {k: v for k, v in sorted(counters.snapshot().items())
                     if k.startswith("chaos.")},
    }


def _check_flight_dump(counters: Counters) -> Optional[str]:
    """Phase A injects an orderer crash (stage.crash → orderer_hard); the
    crash path must have dumped the flight recorder, and the dump's tail
    must carry the telemetry preceding the crash — a dump that exists but
    is empty would be a recorder that armed too late to matter."""
    if counters.snapshot().get(
            "chaos.injected.stage.crash.orderer_hard", 0) == 0:
        return None
    path = get_recorder().last_dump
    if path is None or not os.path.exists(path):
        raise InvariantViolation(
            "orderer crash injected but no flight-recorder dump written")
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    header = json.loads(lines[0]) if lines else {}
    if header.get("flight") != "orderer_crash" or len(lines) < 2:
        raise InvariantViolation(
            f"flight dump {path} missing the pre-crash telemetry tail "
            f"(header={header.get('flight')}, lines={len(lines)})")
    return path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="deterministic chaos soak (tier-1 entry point)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="shorter campaign (CI smoke)")
    parser.add_argument("--phases", default="ab", choices=["a", "b", "ab"])
    parser.add_argument("--break-dedupe", action="store_true",
                        help="self-test: disable the monitor's seq dedupe "
                             "(the soak MUST fail)")
    parser.add_argument("--no-recover", action="store_true",
                        help="self-test: clients never resubmit "
                             "(the soak MUST fail)")
    parser.add_argument("--mesh-shards", type=int, default=0,
                        help="run phase A's applier stage over a "
                             "doc-sharded device mesh of this many shards "
                             "(forces host virtual devices if needed)")
    args = parser.parse_args(argv)
    if args.mesh_shards > 1:
        # XLA parses the virtual-device flag once, at first backend init
        # (same dance as __graft_entry__.dryrun_multichip)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.mesh_shards}").strip()
        import jax

        if len(jax.devices()) < args.mesh_shards:
            from jax.extend import backend as _jax_backend

            jax.config.update("jax_platforms", "cpu")
            _jax_backend.clear_backends()
    try:
        result = run_soak(args.seed, quick=args.quick,
                          break_dedupe=args.break_dedupe,
                          no_recover=args.no_recover, phases=args.phases,
                          mesh_shards=args.mesh_shards)
    except InvariantViolation as e:
        # attach the flight-recorder dump (if one fired) so the failure
        # report carries the telemetry that preceded the trigger
        dump = get_recorder().last_dump
        where = f"\n  flight recorder: {dump}" if dump else ""
        print(f"SOAK FAILED (seed {args.seed}): {e}{where}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
