"""Chaos rebalance campaign: the self-driving placement loop under
hostile load shapes.

``python -m fluidframework_tpu.chaos.rebalance --seed N`` runs a seeded
in-proc campaign against service/rebalancer.py: four doc partitions in
one shard dir, ShardHost "cores" with a short lease TTL, seeded
merge-tree clients editing through whichever core owns their partition
(chaos/migrate.py's MigrateClient — submits bounced by a mid-migration
seal resubmit in cseq order), and one Rebalancer per core ticked
deterministically by the campaign (no ticker threads):

- **hotspot storm** — one core starts owning everything with a viral
  partition; a cold core joins. The armed loop must spread the load
  (``placement.rebalance.migrations_issued`` > 0, every live core ends
  up owning partitions) without losing an op.
- **flap bait** — synthetic heat oscillates so yesterday's move looks
  reversible every tick. The dwell gate must hold: suppression counted
  (``placement.rebalance.suppressed_hysteresis`` > 0), migrations
  bounded by one-move-per-part, flap count (re-migration of the same
  partition inside its dwell window) exactly zero.
- **core kill -9 + auto-heal** (full mode) — the busiest core is
  abandoned without releasing leases or closing logs; the survivors
  take its partitions over on the lease TTL and the loop re-spreads.
  The dead core stays registered in the membership — unreachability
  alone must keep it off the target list.
- **elastic 2→4→2** — two cold cores join under steady traffic and the
  loop drains load onto them (per-core heat spread narrows,
  counter-verified); ``set_core_state(draining)`` then evacuates them
  — every partition migrated away dwell/threshold-exempt — and each
  marks itself drained for clean decommission.

The run settles and replays every partition's multi-owner durable log
from offset 0 through an :class:`InvariantMonitor`: no gap, no dupe, no
lost or double-resolved submission, every replica converging to the
log-replay oracle. Same seed ⇒ same edit streams. Exit 1 on violation.
"""

from __future__ import annotations

import argparse
import json
import random
import shutil
import sys
import tempfile
import time

from ..obs import MetricsRegistry, tier_counters
from ..utils.telemetry import Counters
from .migrate import TENANT, TTL, MigrateClient, _doc_for_partition, \
    _log_fingerprint
from .monitor import InvariantMonitor, InvariantViolation
from .soak import _replica_fingerprint

N_PARTS = 4


def run_campaign(seed: int, counters: Counters,
                 quick: bool = False) -> dict:
    from ..service.front_end import ShardHost
    from ..service.placement_plane import (
        CORE_DRAINED,
        CORE_DRAINING,
        EpochTable,
        MigrationEngine,
    )
    from ..service.rebalancer import (
        HEAT_OPS,
        PartHeat,
        Rebalancer,
        read_local_heat,
    )

    rng = random.Random(seed)
    pc = tier_counters("placement")
    # campaign-held registry: the REAL windowed heat machinery, but
    # isolated from the process-global registry other chaos runs share
    reg = MetricsRegistry()
    shard_dir = tempfile.mkdtemp(prefix="chaos-rebalance-")
    n = N_PARTS
    hosts: list = []
    rebs: dict = {}
    dead: set = set()  # id() of killed hosts — abandoned, never closed
    dead_owners: set = set()
    # when set, heat_readers serve this synthetic map instead of the
    # registry — the flap-bait phase needs per-tick oscillation faster
    # than any real window
    synth = {"heat": None}
    try:
        docs = [_doc_for_partition(k, n) for k in range(n)]
        table = EpochTable.for_shard_dir(shard_dir)

        def alive() -> list:
            return [h for h in hosts if id(h) not in dead]

        def owner_server(k: int):
            for h in alive():
                s = h.servers.get(k)
                if s is not None and not s.sealed:
                    return s
            return None

        def drain_alive() -> None:
            for h in alive():
                for s in list(h.servers.values()):
                    s.drain()

        def poll_alive() -> None:
            for h in alive():
                h.poll()

        def await_owner(k: int, timeout: float = 15.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                poll_alive()
                s = owner_server(k)
                if s is not None:
                    return s
                time.sleep(0.05)
            raise InvariantViolation(
                f"no live owner for partition {k} within {timeout}s — "
                "lease takeover did not happen")

        def make_rebalancer(h, dwell_s: float) -> "Rebalancer":
            def heat_reader(owners, cores, now):
                if synth["heat"] is not None:
                    heat = {k: PartHeat(ops=synth["heat"].get(k, 0.0))
                            for k in owners}
                else:
                    heat = read_local_heat(list(owners), now=now,
                                           registry=reg)
                return heat, {o for o in cores if o not in dead_owners}

            def actuate(k, target_addr, h=h):
                tgt = next(x for x in alive()
                           if x.address == target_addr)
                eng_s = MigrationEngine(h, counters=pc)
                eng_t = MigrationEngine(tgt, counters=pc)
                eng_s.migrate(
                    k, target_addr,
                    adopt=lambda kk, addr: eng_t.adopt(kk, h.owner_id))

            # cooldown_s=0: the injected heat reader is instant truth
            # (shared registry / synthetic map), so the signal-lag
            # cool-down would only slow the deterministic tick script
            return Rebalancer(h, None, heat_reader=heat_reader,
                              actuate=actuate, counters=pc,
                              dwell_s=dwell_s, cooldown_s=0.0,
                              budget=1, improvement=0.25)

        def spawn(prefer=(), dwell_s: float = 1.0) -> "ShardHost":
            h = ShardHost(shard_dir, n, prefer=prefer, ttl_s=TTL)
            h.address = f"inproc/{h.owner_id}"
            h.table.counters = pc
            hosts.append(h)
            h.poll()
            rebs[id(h)] = make_rebalancer(h, dwell_s)
            return h

        def tick_all() -> None:
            for h in alive():
                plan = rebs[id(h)].tick()
                err = rebs[id(h)].last_error
                if err is not None:
                    raise InvariantViolation(
                        f"rebalancer tick failed on {h.owner_id}: {err}")
                del plan

        def all_flaps() -> int:
            return sum(r.flap_count() for r in rebs.values())

        def live_loads() -> dict:
            """Per-core heat sums from the registry — the spread the
            counters verify (exact windowed sums, no sampling)."""
            heat = read_local_heat(range(n), registry=reg)
            return {h.owner_id:
                    sum(heat[k].load for k in h.servers)
                    for h in alive()}

        def spread() -> float:
            """Relative heat spread (max-min over total) across live
            cores: 1.0 = one core carries everything, 0.0 = flat.
            Normalized because window sums keep growing while the
            campaign runs."""
            loads = list(live_loads().values())
            if len(loads) < 2 or sum(loads) <= 0:
                return 0.0
            return (max(loads) - min(loads)) / sum(loads)

        # ---- topology: one core owns EVERYTHING, one cold joiner -----
        src0 = spawn(prefer=tuple(range(n)))
        if sorted(src0.servers) != list(range(n)):
            raise InvariantViolation("preferring core failed to claim")
        spawn()  # the storm's cold joiner

        monitors = [InvariantMonitor(counters) for _ in range(n)]
        clients = []
        for k in range(n):
            c = MigrateClient(docs[k], (lambda k=k: owner_server(k)),
                              monitors[k], counters,
                              random.Random(seed * 1000 + k))
            c.part_k = k
            clients.append(c)
        for c in clients:
            if not c.connect():
                raise InvariantViolation("initial connect failed")
        drain_alive()

        hot = {"k": 0}

        def rounds(nr: int) -> None:
            for _ in range(nr):
                for c in clients:
                    n_ops = 6 if c.part_k == hot["k"] \
                        else 1 + rng.randrange(2)
                    before = c.cseq
                    c.edit(n_ops)
                    submitted = c.cseq - before
                    if submitted:
                        reg.observe_windowed(HEAT_OPS, float(submitted),
                                             part=str(c.part_k))
                drain_alive()
                poll_alive()
                for c in clients:
                    if c.conn is None or c.severed or c.nacked:
                        c.reconnect()
                drain_alive()

        # ---------------------------------------------- hotspot storm
        rounds(3)  # warm the heat window before the loop is armed
        spread_at_start = spread()  # one core carries everything: ~1.0
        storm_rounds = 12 if quick else 24
        for i in range(storm_rounds):
            rounds(1)
            tick_all()
            if all(h.servers for h in alive()) and i >= 2:
                break
        issued = pc.snapshot().get(
            "placement.rebalance.migrations_issued", 0)
        if issued < 1:
            raise InvariantViolation(
                "hotspot storm: the armed loop issued no migrations")
        if any(not h.servers for h in alive()):
            raise InvariantViolation(
                "hotspot storm: a live core ended up owning nothing — "
                "load did not spread")
        if spread() >= spread_at_start:
            raise InvariantViolation(
                f"hotspot storm: heat spread did not narrow "
                f"({spread_at_start:.2f} -> {spread():.2f})")

        # ------------------------------------------------- flap bait
        # oscillating synthetic heat: the hot partition alternates, so
        # every tick yesterday's move looks tempting to undo. Fresh
        # rebalancers with an effectively infinite dwell: each part may
        # move at most once, the rest is counted suppression.
        for h in alive():
            rebs[id(h)] = make_rebalancer(h, dwell_s=10_000.0)
        supp_before = pc.snapshot().get(
            "placement.rebalance.suppressed_hysteresis", 0)
        issued_before = pc.snapshot().get(
            "placement.rebalance.migrations_issued", 0)
        bait = sorted(range(n))
        for i in range(14):
            hot_k = bait[i % 2]  # partitions 0/1 alternate as viral
            synth["heat"] = {k: (40.0 if k == hot_k else 10.0)
                             for k in range(n)}
            tick_all()
            poll_alive()
        synth["heat"] = None
        snap = pc.snapshot()
        flap_migrations = snap.get(
            "placement.rebalance.migrations_issued", 0) - issued_before
        if snap.get("placement.rebalance.suppressed_hysteresis",
                    0) <= supp_before:
            raise InvariantViolation(
                "flap bait: no hysteresis suppression counted — the "
                "dwell gate never engaged")
        if flap_migrations > n:
            raise InvariantViolation(
                f"flap bait: {flap_migrations} migrations in the bait "
                f"phase (> one per partition) — the loop is flapping")
        if all_flaps() != 0:
            raise InvariantViolation(
                f"flap count {all_flaps()} != 0 — a partition "
                "re-migrated inside its dwell window")
        for h in alive():  # back to the live-load loop
            rebs[id(h)] = make_rebalancer(h, dwell_s=1.0)
        rounds(2)

        # ------------------------------------- kill -9 + auto-heal
        killed = 0
        if not quick:
            victim = max(alive(), key=lambda h: (len(h.servers),
                                                 h.owner_id))
            lost = sorted(victim.servers)
            dead.add(id(victim))
            dead_owners.add(victim.owner_id)
            for c in clients:
                if c.server is not None and any(
                        s is c.server for s in victim.servers.values()):
                    c.sever()
            if len(alive()) < 2:
                spawn()  # keep a rebalance target alive
            for k in lost:
                await_owner(k)
            killed = 1
            # the dead core is still registered active in the table:
            # unreachability must keep it off the target list while the
            # survivors re-spread
            for _ in range(8 if quick else 12):
                rounds(1)
                tick_all()
                if all(h.servers for h in alive()):
                    break
            owned = {k for h in alive() for k in h.servers}
            if owned != set(range(n)):
                raise InvariantViolation(
                    f"auto-heal: partitions {set(range(n)) - owned} "
                    "unowned after the kill")
            table.remove_core(victim.owner_id)  # operator cleanup

        # --------------------------------------- elastic join (…→4)
        hot["k"] = None  # steady traffic: every partition equally warm
        joiners = [spawn(), spawn()]
        rounds(3)
        spread_joined = spread()
        for _ in range(10 if quick else 16):
            rounds(1)
            tick_all()
            if all(j.servers for j in joiners):
                break
        if any(not j.servers for j in joiners):
            raise InvariantViolation(
                "elastic join: a cold joiner absorbed nothing")
        if spread() >= spread_joined:
            raise InvariantViolation(
                f"elastic join: heat spread did not narrow "
                f"({spread_joined:.1f} -> {spread():.1f})")

        # -------------------------------------- elastic drain (4→…)
        for j in joiners:
            if not table.set_core_state(j.owner_id, CORE_DRAINING):
                raise InvariantViolation("drain mark refused for a "
                                         "registered core")
        for _ in range(12 if quick else 20):
            rounds(1)
            poll_alive()  # pick up the drain mark
            tick_all()
            if all(not j.servers for j in joiners):
                break
        for j in joiners:
            if j.servers:
                raise InvariantViolation(
                    f"drain: core {j.owner_id} still owns "
                    f"{sorted(j.servers)} — evacuation incomplete")
        rounds(1)
        poll_alive()
        tick_all()  # the empty tick flips draining → drained
        for j in joiners:
            if table.core_state(j.owner_id) != CORE_DRAINED:
                raise InvariantViolation(
                    f"drain: core {j.owner_id} never marked drained")
            dead.add(id(j))  # decommission: stop polling it
            table.remove_core(j.owner_id)

        # ------------------------------------------ settle + verdict
        for _ in range(30):
            drain_alive()
            poll_alive()
            if all(c.settled for c in clients):
                break
            for c in clients:
                if not c.settled:
                    c.reconnect()
            time.sleep(0.02)
        drain_alive()
        for c in clients:
            if c.conn is not None:
                c.catch_up()

        sequenced = {}
        for k in range(n):
            final = owner_server(k)
            if final is None:
                raise InvariantViolation(
                    f"no live owner for partition {k} at quiescence")
            monitors[k].attach(final.log, f"deltas/{TENANT}/{docs[k]}")
            final.drain()
            monitors[k].check_quiescent({
                f"client{k}": _replica_fingerprint(clients[k].replica),
                "oracle": _log_fingerprint(final, docs[k])})
            sequenced[docs[k]] = monitors[k].observed
        if sum(sequenced.values()) < 40:
            raise InvariantViolation(
                f"observed only {sum(sequenced.values())} sequenced "
                "messages — the workload did not run")

        delta = {k: v for k, v in pc.snapshot().items() if v}
        if delta.get("placement.rebalance.ticks", 0) < 10:
            raise InvariantViolation("the loop barely ticked")
        if delta.get("placement.rebalance.migrations_issued", 0) < 3:
            raise InvariantViolation(
                "fewer than 3 automatic migrations across storm + "
                "join + drain")
        if delta.get("placement.rebalance.suppressed_hysteresis", 0) < 1:
            raise InvariantViolation("no hysteresis suppression counted")
        if delta.get("placement.migration.committed", 0) < \
                delta.get("placement.rebalance.migrations_issued", 0):
            raise InvariantViolation(
                "issued migrations were not all committed")
        if all_flaps() != 0:
            raise InvariantViolation("flap count nonzero at verdict")

        return {
            "seed": seed,
            "quick": quick,
            "killed": killed,
            "reconnects": sum(c.reconnects for c in clients),
            "sequenced": sequenced,
            "spread_final": round(spread(), 2),
            "placement": dict(sorted(delta.items())),
            "counters": {k: v for k, v in sorted(
                counters.snapshot().items()) if k.startswith("chaos.")},
        }
    finally:
        for h in hosts:
            for s in list(h.servers.values()):
                try:
                    s.log.close()
                except Exception:
                    pass
        shutil.rmtree(shard_dir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos rebalance campaign: hotspot storm, flap "
                    "bait, core kill + auto-heal, elastic 2→4→2 "
                    "(tier-1 entry point)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="storm + flap + elastic, no kill (CI smoke)")
    args = parser.parse_args(argv)
    counters = tier_counters("chaos")
    try:
        result = run_campaign(args.seed, counters, quick=args.quick)
    except InvariantViolation as e:
        print(f"REBALANCE CAMPAIGN FAILED (seed {args.seed}): {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
