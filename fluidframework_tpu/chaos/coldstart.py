"""Chaos cold-start campaign: kill -9 the WHOLE fleet mid-traffic,
restart it from its topology spec, and audit exact-once delivery.

``python -m fluidframework_tpu.chaos.coldstart --seed N`` runs a seeded
campaign against a real subprocess fleet (service/topology.py): cores +
storage tier started from one TopologySpec on pinned ports, seeded
clients inserting globally-unique tokens, then three acts:

1. **The kill.** SIGKILL every process at once — cores, storage — with
   the last submissions still in flight. No checkpoint, no goodbye.
2. **The crashed recovery.** Restart from the SAME spec with the
   rehydration crash seam armed (``FLUID_CHAOS_BOOT_CRASH=K``): each
   core dies with exactly K doc boots admitted, mid-storm — proving a
   crash INSIDE lazy rehydration is just another cold start.
3. **The clean recovery.** Restart again, seam disarmed. Clients
   reconnect, catch up, and resubmit only the tokens the sequenced
   history does NOT already hold (content-filtered resubmission — an
   op can be durably sequenced but unacked at kill time, so blind
   resubmit would double it).

The verdict, per doc, through a fresh verifier client booting from the
rehydrated state: every token appears in the final text EXACTLY once —
no token lost by the kill, none doubled by tail replay across two
crash/restart cycles. The campaign also asserts the lazy-boot
contract fleet-wide via ``admin_boot_status``: every summarized +
checkpointed doc rehydrates lazily (``boot.part.full_replay == 0``)
and at least one crash-seam core actually died with exit code 9.
Same seed ⇒ same token streams and kill points. Exit 1 on violation.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import socket
import sys
import tempfile
import time

from ..utils.telemetry import Counters
from ..obs import tier_counters
from .monitor import InvariantViolation

TENANT = "chaos"

#: lease TTL — short, so the restarted generation claims the dead
#: generation's partitions in well under a second
TTL = 0.75

#: the crash seam: each core of the crashed generation dies after this
#: many doc boots have been admitted by its rehydration executor
BOOT_CRASH_AFTER = 2


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait(cond, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TokenClient:
    """One doc's client: inserts unique tokens, survives fleet death by
    re-resolving, and resubmits content-filtered after recovery."""

    def __init__(self, doc: str, port: int, rng: random.Random):
        self.doc = doc
        self.port = port
        self.rng = rng
        self.tokens: list[str] = []  # every token this client ever sent
        self.container = None
        self.string = None

    def connect(self) -> None:
        from ..driver.network import NetworkDocumentServiceFactory
        from ..loader import Loader

        loader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", self.port))
        self.container = loader.resolve(TENANT, self.doc)
        rt = self.container.runtime
        if "default" not in rt.data_stores:
            ds = rt.create_data_store("default")
        else:
            ds = rt.get_data_store("default")
        if "text" not in ds.channels:
            self.string = ds.create_channel("text", "shared-string")
        else:
            self.string = ds.get_channel("text")

    def _boundary(self) -> int:
        # only ever insert at token boundaries — a mid-token insert
        # would split an earlier token and break the substring audit
        text = self.string.get_text()
        spots = [0] + [i + 1 for i, ch in enumerate(text) if ch == " "]
        return self.rng.choice(spots)

    def insert(self, token: str) -> None:
        self.tokens.append(token)
        self.string.insert_text(self._boundary(), token + " ")

    def drained(self) -> bool:
        return self.container.runtime.pending.count == 0

    def abandon(self) -> None:
        self.container = None
        self.string = None

    def resubmit_missing(self) -> int:
        """Content-filtered recovery: re-insert only tokens the
        sequenced history does not hold. Returns how many."""
        text = self.string.get_text()
        missing = [t for t in self.tokens if t not in text]
        for t in missing:
            self.string.insert_text(self._boundary(), t + " ")
        return len(missing)


def run_campaign(seed: int, counters: Counters,
                 quick: bool = False) -> dict:
    from ..driver.network import _Transport
    from ..service.stage_runner import doc_partition
    from ..service.topology import Fleet, default_spec

    rng = random.Random(seed)
    n_docs = 4 if quick else 8
    tokens_each = 6 if quick else 10
    n_parts, n_cores = 4, 2
    work_dir = tempfile.mkdtemp(prefix="chaos-coldstart-")
    fl = None
    try:
        spec = default_spec(os.path.join(work_dir, "fleet"),
                            n_cores=n_cores, n_partitions=n_parts,
                            lease_ttl=TTL, summarize_every=1000,
                            boot_rate=50.0, boot_burst=2)
        # pinned ports: reconnecting clients must find the RESTARTED
        # generation at the address the spec declares
        for core, port in zip(spec.cores, _free_ports(n_cores)):
            core.port = port
        fl = Fleet(spec, subprocess=True, env={}).start()
        fl.wait_claimed()

        def core_port_for(doc: str) -> int:
            # route by the ACTUAL owner in the epoch table, not the
            # spec's prefer map — after a kill/restart cycle stale-lease
            # takeover may land a partition on a non-prefer core
            from ..service.placement_plane import EpochTable

            part = doc_partition(TENANT, doc, n_parts)
            rec = EpochTable.for_shard_dir(
                spec.shard_dir).read()["parts"][str(part)]
            return int(rec["addr"].rsplit(":", 1)[1])

        def reroute_and_connect(c: "TokenClient") -> None:
            # ownership can still churn for a beat after wait_claimed;
            # re-resolve the owner and retry briefly on routing errors
            deadline = time.monotonic() + 20.0
            while True:
                c.port = core_port_for(c.doc)
                try:
                    c.connect()
                    return
                except RuntimeError as e:
                    if ("not the owner" not in str(e)
                            or time.monotonic() >= deadline):
                        raise
                    time.sleep(0.2)

        clients = []
        for i in range(n_docs):
            doc = f"cs{i}"
            c = TokenClient(doc, core_port_for(doc),
                            random.Random(seed * 1000 + i))
            c.connect()
            clients.append(c)

        # ---- seeded traffic, then summaries + checkpoints ----------
        for j in range(tokens_each - 2):
            for i, c in enumerate(clients):
                c.insert(f"T{seed}d{i}n{j:03d}")
        if not _wait(lambda: all(c.drained() for c in clients)):
            raise InvariantViolation("pre-kill traffic never drained")
        for c in clients:
            t = _Transport("127.0.0.1", c.port)
            t.request_rid({"t": "admin_summarize", "tenant": TENANT,
                           "doc": c.doc})
            t.close()
        time.sleep(2.5)  # one checkpoint-ticker pass past the summary

        # ---- the kill: last submissions still in flight ------------
        for j in range(tokens_each - 2, tokens_each):
            for i, c in enumerate(clients):
                c.insert(f"T{seed}d{i}n{j:03d}")
        counters.inc("chaos.injected.fleet_kill")
        fl.kill()
        for c in clients:
            c.abandon()

        # ---- act 2: recovery that itself crashes mid-rehydration ---
        fl.env = {"FLUID_CHAOS_BOOT_CRASH": str(BOOT_CRASH_AFTER)}
        fl.start()
        fl.wait_claimed()
        crash_procs = dict(fl.procs)
        # reconnecting clients ARE the boot storm; the seam kills each
        # core after BOOT_CRASH_AFTER admitted boots
        for c in clients:
            try:
                c.port = core_port_for(c.doc)
                c.connect()
            except Exception:  # noqa: BLE001 — core died mid-storm
                pass
        crashed = 0
        for p in crash_procs.values():
            try:
                if p.wait(timeout=30) == 9:
                    crashed += 1
            except Exception:
                pass
        if crashed == 0:
            raise InvariantViolation(
                "FLUID_CHAOS_BOOT_CRASH armed but no core died with "
                "exit code 9 — the rehydration crash seam never fired")
        counters.inc("chaos.injected.boot_crash", crashed)
        for c in clients:
            c.abandon()

        # ---- act 3: the clean recovery -----------------------------
        fl.env = {}
        fl.restart()
        fl.wait_claimed()
        resubmitted = 0
        for c in clients:
            reroute_and_connect(c)
            counters.inc("chaos.recovered.reconnect")
        # catch-up settles (the driver boots from snapshot + fetches
        # the tail) before the content filter decides what to resend
        if not _wait(lambda: all(c.drained() for c in clients)):
            raise InvariantViolation("post-restart catch-up never "
                                     "drained")
        for c in clients:
            n = c.resubmit_missing()
            resubmitted += n
            if n:
                counters.inc("chaos.recovered.resubmit", n)
        if not _wait(lambda: all(c.drained() for c in clients)):
            raise InvariantViolation("resubmitted tokens never drained")

        # ---- the verdict: exact-once, through fresh verifiers ------
        losses, dupes = [], []
        detail: dict = {}
        for c in clients:
            v = TokenClient(c.doc, c.port, random.Random(0))
            v.connect()
            ok = _wait(lambda: "default" in v.container.runtime.data_stores
                       and "text" in v.container.runtime.get_data_store(
                           "default").channels, 20)
            if not ok:
                raise InvariantViolation(
                    f"verifier for {c.doc} never booted")
            text = v.container.runtime.get_data_store(
                "default").get_channel("text").get_text()
            lost_here = []
            for t in c.tokens:
                n = text.count(t)
                if n == 0:
                    losses.append(t)
                    lost_here.append(t)
                elif n > 1:
                    dupes.append((t, n))
            detail[c.doc] = {"lost": lost_here, "len": len(text)}
        if losses:
            raise InvariantViolation(
                f"{len(losses)} tokens LOST across the crash/restart "
                f"cycles (first: {losses[0]}; detail: {detail})")
        if dupes:
            raise InvariantViolation(
                f"{len(dupes)} tokens DUPLICATED by tail replay "
                f"(first: {dupes[0]})")

        # ---- the lazy-boot contract, fleet-wide --------------------
        boot_counts: dict = {}
        for i in range(n_cores):
            t = _Transport("127.0.0.1", spec.cores[i].port)
            _, reply = t.request_rid({"t": "admin_boot_status"})
            t.close()
            for k, v in reply["boot"]["counters"].items():
                boot_counts[k] = boot_counts.get(k, 0) + v
        if boot_counts.get("boot.part.full_replay", 0) != 0:
            raise InvariantViolation(
                "a summarized + checkpointed doc whole-log replayed: "
                f"{boot_counts}")
        if boot_counts.get("boot.part.lazy", 0) < n_docs:
            raise InvariantViolation(
                f"expected >= {n_docs} lazy boots in the final "
                f"generation, saw {boot_counts}")

        return {
            "seed": seed,
            "quick": quick,
            "docs": n_docs,
            "tokens": n_docs * tokens_each,
            "boot_crashed_cores": crashed,
            "resubmitted": resubmitted,
            "boot": {k: v for k, v in sorted(boot_counts.items())
                     if k.startswith("boot.")},
            "counters": {k: v for k, v in sorted(
                counters.snapshot().items()) if k.startswith("chaos.")},
        }
    finally:
        if fl is not None:
            fl.stop()
        shutil.rmtree(work_dir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos cold-start campaign: kill -9 the whole "
                    "fleet mid-traffic, restart it from its topology "
                    "spec, audit exact-once delivery")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="fewer docs/tokens (CI smoke)")
    args = parser.parse_args(argv)
    counters = tier_counters("chaos")
    try:
        result = run_campaign(args.seed, counters, quick=args.quick)
    except InvariantViolation as e:
        print(f"COLD-START CAMPAIGN FAILED (seed {args.seed}): {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
