"""chaos: deterministic fault injection + protocol invariant monitoring.

The durability claims the service makes (ARCHITECTURE.md "Durability &
recovery") are exercised mechanically here, the way fluidlint exercises
the architecture claims: a seeded :class:`FaultPlane` schedules faults at
named injection points the service seams consult when armed (a single
predictable branch when disarmed), and an :class:`InvariantMonitor`
rides the sequenced stream asserting the protocol invariants — seq
strictly increasing, msn monotone and ≤ seq, clientSeq gap/dup rules,
every submitted op acked-or-nacked exactly once after dedupe, and all
replicas fingerprint-identical at quiescence.

``python -m fluidframework_tpu.chaos.soak --seed N`` runs a recorded
multi-client session under a fault schedule; the same seed reproduces
the same injections exactly.

Layering: chaos sits ABOVE service/driver (it may import them; nothing
outside tests may import chaos) — the seams it arms are duck-typed
``fault_plane`` attributes, so the service never imports this package.
"""

from .monitor import InvariantMonitor, InvariantViolation, doc_fingerprint
from .plane import FaultPlane, FaultRule, SimulatedCrash

__all__ = [
    "FaultPlane",
    "FaultRule",
    "SimulatedCrash",
    "InvariantMonitor",
    "InvariantViolation",
    "doc_fingerprint",
]
