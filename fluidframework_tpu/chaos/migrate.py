"""Chaos migration campaign: crash the source core mid-migration.

``python -m fluidframework_tpu.chaos.migrate --seed N`` runs a seeded
in-proc campaign against the placement control plane
(service/placement_plane.py): two doc partitions in one shard dir,
multiple ShardHost "cores" with a short lease TTL, seeded merge-tree
clients editing through whichever core owns their partition, and a
scripted sequence of live migrations where the source core is killed at
each of the engine's crash windows:

- ``placement.pre_fence``   — before the seal: the migration simply
  never happened; the lease goes stale and the target takes the
  partition over on its poll (the single-core kill -9 restart path).
- ``placement.pre_handoff`` — after seal + fence + checkpoint, before
  the lease moved: same takeover recovery, but the target resumes from
  the freshly shipped checkpoint.
- ``placement.post_handoff`` — after the atomic lease transfer: the
  target already owns the log; the dead source merely fails to push the
  route flip and clients discover the new owner via reconnect.

A "kill" abandons the source host object without closing its logs or
releasing its leases — the in-proc stand-in for kill -9. After every
crash the campaign also proves the fence: the zombie source's partition
server must refuse a new connect (lease-freshness clock / seal /
revocation), so a doc mid-migration is never sequenced by two cores.

The run ends with one clean (uncrashed) migration under live traffic —
the partition-1 control client must not be disturbed by it — then
settles and replays the ENTIRE multi-owner durable log from offset 0
through an :class:`InvariantMonitor`: no sequence gap, no duplicate, no
lost or double-resolved submission, and every client replica converges
to the log-replay oracle fingerprint. Same seed ⇒ same edit streams and
the same crash points. Exit 1 on any violation.
"""

from __future__ import annotations

import argparse
import json
import random
import shutil
import sys
import tempfile
import time
from typing import Callable, Optional

from ..mergetree.client import MergeTreeClient
from ..obs import tier_counters
from ..mergetree.ops import op_to_wire
from ..utils.telemetry import Counters
from .monitor import InvariantMonitor, InvariantViolation
from .plane import FaultPlane, SimulatedCrash
from .soak import (CHANNEL_ID, DS_ID, _chan_contents, _chan_msg,
                   _replica_fingerprint)

TENANT = "chaos"

#: lease TTL for the campaign cores — short, so takeover of a killed
#: source completes in well under a second
TTL = 0.5

#: the engine's crash windows, in protocol order
SEAMS = ("pre_fence", "pre_handoff", "post_handoff")


def _doc_for_partition(k: int, n: int) -> str:
    """Smallest ``mig<i>`` doc id that hashes onto partition ``k``."""
    from ..service.stage_runner import doc_partition

    i = 0
    while True:
        doc = f"mig{i}"
        if doc_partition(TENANT, doc, n) == k:
            return doc
        i += 1


class MigrateClient:
    """A SoakClient variant that follows its doc across cores.

    ``resolve()`` returns the live owner's LocalServer for the doc's
    partition (or None mid-takeover); a submit refused by a sealed,
    revoked, or lease-stale server marks the client severed, and the
    next quiescent :meth:`reconnect` rejoins the current owner, rebases
    the pending ops, and resubmits them in client-sequence order.
    """

    def __init__(self, doc: str, resolve: Callable, monitor: InvariantMonitor,
                 counters: Counters, rng: random.Random):
        self.doc = doc
        self.resolve = resolve
        self.monitor = monitor
        self.counters = counters
        self.rng = rng
        self.replica: Optional[MergeTreeClient] = None
        self.server = None
        self.conn = None
        self.cseq = 0
        self.last_seq = 0
        self.nacked = False
        self.severed = False
        self.unresolved: list[int] = []  # this incarnation's open cseqs
        self.reconnects = 0

    # ---------------------------------------------------------- lifecycle

    def connect(self) -> bool:
        server = self.resolve()
        if server is None:
            return False
        try:
            conn = server.connect(TENANT, self.doc)
        except RuntimeError:
            return False  # sealed / fenced: the owner is still flipping
        self.server = server
        self.conn = conn
        if self.replica is None:
            self.replica = MergeTreeClient(conn.client_id)
        else:
            self.replica.update_client_id(conn.client_id)
        self.cseq = 0
        self.nacked = False
        self.severed = False
        self.unresolved = []
        conn.on_ops = self._on_ops
        conn.on_nack = self._on_nack
        return True

    def sever(self) -> None:
        """The connection died under us (core crash / session drop):
        abandon this incarnation's open submissions — the reconnect
        resubmits their effect under the next one."""
        if self.conn is None:
            return
        old_id = self.conn.client_id
        try:
            self.conn.disconnect()
        except RuntimeError:
            pass  # dead/sealed core: the leave can't be sequenced
        self.conn = None
        for cseq in self.unresolved:
            self.monitor.note_resubmitted(old_id, cseq)
        self.unresolved = []
        self.severed = True

    def reconnect(self) -> bool:
        """Call at drain quiescence: rejoin the CURRENT owner, catch up
        on the seqs this replica missed, rebase + resubmit pending ops."""
        self.sever()
        if not self.connect():
            return False  # no live owner yet (mid-takeover): retry later
        self.reconnects += 1
        self.counters.inc("chaos.recovered.reconnect")
        self.catch_up()
        for op in self.replica.regenerate_pending_ops():
            self._submit_wire(op_to_wire(op))
        return True

    def catch_up(self) -> None:
        missed = self.server.get_deltas(TENANT, self.doc,
                                        self.last_seq, 10 ** 9)
        if missed:
            self.counters.inc("chaos.recovered.gap_repair")
        for m in missed:
            if m.sequence_number > self.last_seq:
                self._apply(m)

    # ------------------------------------------------------------ inbound

    def _on_ops(self, batch) -> None:
        for m in batch:
            seq = m.sequence_number
            if seq <= self.last_seq:
                self.counters.inc("chaos.recovered.client_dedup")
                continue
            if seq > self.last_seq + 1:
                self.counters.inc("chaos.recovered.gap_repair")
                for g in self.server.get_deltas(TENANT, self.doc,
                                                self.last_seq, seq):
                    if g.sequence_number > self.last_seq:
                        self._apply(g)
            self._apply(m)

    def _apply(self, m) -> None:
        from dataclasses import replace

        self.last_seq = m.sequence_number
        wire = _chan_contents(m)
        if wire is not None:
            if self.replica.is_own_message(m.client_id):
                self.unresolved = [c for c in self.unresolved
                                   if c != m.client_sequence_number]
            self.replica.apply_msg(replace(m, contents=wire))
        else:
            self.replica.tree.current_seq = max(
                self.replica.tree.current_seq, m.sequence_number)
            self.replica.tree.update_min_seq(m.minimum_sequence_number)

    def _on_nack(self, nack) -> None:
        self.nacked = True
        op = getattr(nack, "operation", None)
        cseq = getattr(op, "client_sequence_number", None)
        self.monitor.note_nack(self.conn.client_id, cseq)
        if cseq is not None:
            self.unresolved = [c for c in self.unresolved if c != cseq]

    # ----------------------------------------------------------- outbound

    def _submit_wire(self, wire_op: dict) -> None:
        self.cseq += 1
        self.monitor.note_submit(self.conn.client_id, self.cseq)
        self.unresolved.append(self.cseq)
        try:
            self.conn.submit([_chan_msg(
                self.cseq, self.replica.tree.current_seq, wire_op)])
        except RuntimeError:
            # sealed / revoked / lease-stale: the op stays pending in the
            # replica; the quiescent reconnect rebases + resubmits it
            self.counters.inc("chaos.recovered.migrate_bounce")
            self.sever()

    def edit(self, n_ops: int) -> None:
        if self.conn is None or self.nacked or self.severed:
            return  # wedged until the next quiescent reconnect
        rng = self.rng
        pool = "abcdefgh" * 4
        for _ in range(n_ops):
            if self.severed:
                return
            length = self.replica.get_length()
            r = rng.random()
            if length > 4 and r < 0.3:
                start = rng.randrange(length - 1)
                end = start + 1 + rng.randrange(min(length - start - 1, 4))
                op = self.replica.remove_range_local(start, end)
            elif length > 1 and r < 0.35:
                start = rng.randrange(length - 1)
                end = start + 1 + rng.randrange(min(length - start - 1, 4))
                op = self.replica.annotate_range_local(
                    start, end, {"k": rng.randrange(4)})
            else:
                off = rng.randrange(8)
                text = pool[off:off + 1 + rng.randrange(6)]
                op = self.replica.insert_text_local(
                    rng.randrange(length + 1), text)
            self._submit_wire(op_to_wire(op))

    @property
    def settled(self) -> bool:
        return (self.conn is not None and not self.severed
                and not self.unresolved and not self.nacked
                and not self.replica.pending)


def _log_fingerprint(server, doc: str) -> str:
    """Replay the authoritative sequenced log (all owners' appends) into
    a fresh replica — the oracle every client must agree with."""
    from ..service.tpu_applier import channel_stream

    oracle = MergeTreeClient("chaos/migrate-oracle")
    for m in channel_stream(server, TENANT, doc, DS_ID, CHANNEL_ID):
        oracle.apply_msg(m, local=False)
    return _replica_fingerprint(oracle)


def run_campaign(seed: int, counters: Counters,
                 quick: bool = False) -> dict:
    from ..service.front_end import ShardHost
    from ..service.placement_plane import EpochTable, MigrationEngine

    plane = FaultPlane(seed, counters)
    rng = random.Random(seed)
    scenarios = (["pre_handoff", None] if quick
                 else list(SEAMS) + [None])
    # campaign-held placement Counters: the process-global tier sum is
    # a weak aggregate (instances die with their owners), so the verdict
    # reads an instance IT holds, wired into every table/engine below
    pc = tier_counters("placement")
    shard_dir = tempfile.mkdtemp(prefix="chaos-migrate-")
    n = 2
    hosts: list = []
    dead: set = set()  # id() of killed hosts — abandoned, never closed
    try:
        doc0 = _doc_for_partition(0, n)
        doc1 = _doc_for_partition(1, n)
        table = EpochTable.for_shard_dir(shard_dir)

        def spawn(prefer=()) -> ShardHost:
            h = ShardHost(shard_dir, n, prefer=prefer, ttl_s=TTL)
            h.address = f"inproc/{h.owner_id}"
            h.table.counters = pc
            hosts.append(h)
            h.poll()
            return h

        def alive() -> list:
            return [h for h in hosts if id(h) not in dead]

        def owner_server(k: int):
            for h in alive():
                s = h.servers.get(k)
                if s is not None and not s.sealed:
                    return s
            return None

        def drain_alive() -> None:
            for h in alive():
                for s in list(h.servers.values()):
                    s.drain()

        def poll_alive() -> None:
            for h in alive():
                h.poll()

        def await_owner(k: int, timeout: float = 15.0):
            """Lease-TTL takeover: poll the survivors until one owns k."""
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                poll_alive()
                s = owner_server(k)
                if s is not None:
                    return s
                time.sleep(0.05)
            raise InvariantViolation(
                f"no live owner for partition {k} within {timeout}s of "
                "the source crash — lease takeover did not happen")

        src0 = spawn(prefer=(0, 1))
        spawn()  # standby: claims only by takeover / adoption
        if 0 not in src0.servers or 1 not in src0.servers:
            raise InvariantViolation("preferring core failed to claim")

        mon0 = InvariantMonitor(counters)
        mon1 = InvariantMonitor(counters)
        clients = [MigrateClient(doc0, lambda: owner_server(0), mon0,
                                 counters, random.Random(seed * 1000 + i))
                   for i in range(3)]
        control = MigrateClient(doc1, lambda: owner_server(1), mon1,
                                counters, random.Random(seed * 1000 + 99))
        for c in clients + [control]:
            if not c.connect():
                raise InvariantViolation("initial connect failed")
        drain_alive()

        def rounds(nr: int) -> None:
            for _ in range(nr):
                for c in clients:
                    c.edit(1 + rng.randrange(2))
                control.edit(1)
                drain_alive()
                poll_alive()
                for c in clients + [control]:
                    if c.conn is None or c.severed or c.nacked:
                        c.reconnect()
                drain_alive()

        recoveries = 0
        epochs_seen = [table.global_epoch()]
        for scen in scenarios:
            rounds(4)
            src = next(h for h in alive() if 0 in h.servers)
            tgt = next(h for h in alive() if h is not src)
            eng_src = MigrationEngine(src, counters=pc)
            eng_tgt = MigrationEngine(tgt, counters=pc)
            eng_src.fault_plane = plane
            zombie = src.servers.get(0)
            if scen is None:
                # the clean migration: seal → fence → checkpoint →
                # atomic handoff; partition 1's control client must not
                # notice
                control_reconnects = control.reconnects
                eng_src.migrate(
                    0, tgt.address,
                    adopt=lambda k, addr: eng_tgt.adopt(k, src.owner_id))
                rounds(3)
                if control.reconnects != control_reconnects:
                    raise InvariantViolation(
                        "partition-1 control client was disturbed by the "
                        "partition-0 migration")
            else:
                plane.rule(f"placement.{scen}", "crash", at=1)
                try:
                    eng_src.migrate(
                        0, tgt.address,
                        adopt=lambda k, addr: eng_tgt.adopt(
                            k, src.owner_id))
                except SimulatedCrash:
                    pass
                else:
                    raise InvariantViolation(
                        f"scheduled crash at placement.{scen} never fired")
                # kill -9: abandon the source — leases unreleased, logs
                # unclosed, no flip pushed. Its sockets die with it, so
                # every client on it is severed.
                dead.add(id(src))
                for c in clients + [control]:
                    if c.server is not None and (
                            c.server is zombie or id_owner(c.server, src)):
                        c.sever()
                await_owner(0)
                await_owner(1)
                # fencing proof: the zombie source (still resident
                # in-proc) must refuse orders — seal, revocation, or the
                # lease-freshness clock, whichever fired first
                if zombie is not None:
                    try:
                        zombie.connect(TENANT, doc0)
                    except RuntimeError:
                        counters.inc("chaos.recovered.zombie_fenced")
                    else:
                        raise InvariantViolation(
                            "zombie source accepted a connect after the "
                            "takeover — two cores could sequence the doc")
                spawn()  # replacement core: keep two alive
                recoveries += 1
            for _ in range(100):
                if all(c.conn is not None for c in clients + [control]):
                    break
                poll_alive()
                for c in clients + [control]:
                    if c.conn is None:
                        c.reconnect()
                drain_alive()
                time.sleep(0.02)
            rounds(2)
            ep = table.global_epoch()
            if ep <= epochs_seen[-1]:
                raise InvariantViolation(
                    f"table epoch did not advance across the migration "
                    f"({epochs_seen[-1]} → {ep})")
            epochs_seen.append(ep)

        # settle: stop injecting, resolve every open submission
        plane.disarm()
        for _ in range(20):
            drain_alive()
            poll_alive()
            if all(c.settled for c in clients) and control.settled:
                break
            for c in clients + [control]:
                if not c.settled:
                    c.reconnect()
            time.sleep(0.02)
        drain_alive()
        for c in clients + [control]:
            if c.conn is not None:
                c.catch_up()

        final0 = owner_server(0)
        final1 = owner_server(1)
        if final0 is None or final1 is None:
            raise InvariantViolation("no live owner at quiescence")

        # the verdict: replay the WHOLE multi-owner history from offset 0
        # — seq contiguity and dedupe across every owner change — and
        # check every replica against the log-replay oracle
        mon0.attach(final0.log, f"deltas/{TENANT}/{doc0}")
        final0.drain()
        mon1.attach(final1.log, f"deltas/{TENANT}/{doc1}")
        final1.drain()
        fps = {f"client{i}": _replica_fingerprint(c.replica)
               for i, c in enumerate(clients)}
        fps["oracle"] = _log_fingerprint(final0, doc0)
        mon0.check_quiescent(fps)
        mon1.check_quiescent({
            "control": _replica_fingerprint(control.replica),
            "oracle": _log_fingerprint(final1, doc1)})
        if mon0.observed < 20:
            raise InvariantViolation(
                f"observed only {mon0.observed} sequenced messages — the "
                "workload did not run")

        # coverage + recovery cross-check
        hit = {p for p, _, _ in plane.injected}
        want = {f"placement.{s}" for s in scenarios if s}
        if not want <= hit:
            raise InvariantViolation(
                f"missing crash coverage: {sorted(want - hit)}")
        delta = {k: v for k, v in pc.snapshot().items() if v}
        if delta.get("placement.migration.committed", 0) < 1:
            raise InvariantViolation("no clean migration committed")
        if delta.get("placement.migration.adopted", 0) < 1:
            raise InvariantViolation("no adoption recorded")
        if delta.get("placement.epoch.bumps", 0) < len(scenarios):
            raise InvariantViolation("epoch did not bump per ownership "
                                     "change")
        snap = counters.snapshot()
        if recoveries and snap.get("chaos.recovered.reconnect", 0) == 0:
            raise InvariantViolation("source crashes injected but no "
                                     "client reconnect recovery observed")
        if recoveries and snap.get(
                "chaos.recovered.zombie_fenced", 0) < recoveries:
            raise InvariantViolation("a crashed source was never probed "
                                     "for fencing")

        return {
            "seed": seed,
            "quick": quick,
            "scenarios": [s or "clean" for s in scenarios],
            "recoveries": recoveries,
            "reconnects": (sum(c.reconnects for c in clients)
                           + control.reconnects),
            "sequenced": {"doc0": mon0.observed, "doc1": mon1.observed},
            "epochs": epochs_seen,
            "placement": dict(sorted(delta.items())),
            "counters": {k: v for k, v in sorted(snap.items())
                         if k.startswith("chaos.")},
        }
    finally:
        for h in hosts:
            for s in list(h.servers.values()):
                try:
                    s.log.close()
                except Exception:
                    pass
        shutil.rmtree(shard_dir, ignore_errors=True)


def id_owner(server, host) -> bool:
    """Is ``server`` one of ``host``'s partition servers?"""
    return any(s is server for s in host.servers.values())


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos migration campaign: crash the source core at "
                    "each migration seam (tier-1 entry point)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="one crash scenario + the clean migration "
                             "(CI smoke)")
    args = parser.parse_args(argv)
    counters = tier_counters("chaos")
    try:
        result = run_campaign(args.seed, counters, quick=args.quick)
    except InvariantViolation as e:
        print(f"MIGRATION CAMPAIGN FAILED (seed {args.seed}): {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
