"""Seam installers: arm/disarm a FaultPlane across the service seams.

Every seam is a duck-typed ``fault_plane`` attribute (``None`` when
disarmed — one predictable branch on the hot path, see BENCH_r05
criterion) or, for the socket transport, a module-global hook captured
at connection construction. The service never imports chaos; chaos
reaches down and installs itself — which is exactly the layering the
fluidlint DAG enforces (``chaos`` may import service/driver/utils;
nothing outside tests may import ``chaos``).

Use :func:`armed` as a context manager in tests; the soak process uses
:func:`install`/the returned uninstaller directly.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Iterable, Optional

from ..driver import network as _network
from ..service.broadcaster import BroadcasterLambda
from ..service.history_plane import HistoryPlane
from .plane import FaultPlane


def install(plane: FaultPlane, *, server=None, appliers: Iterable = (),
            stages: Iterable = (), partitions: Iterable = (),
            fronts: Iterable = (), summarizers: Iterable = (),
            transports: bool = False) -> Callable[[], None]:
    """Arm ``plane`` at the requested seams; returns an uninstaller.

    - ``server``: a LocalServer — arms its ordered log (append faults)
      and, class-wide, the broadcaster fan-out (orderers build their
      BroadcasterLambda lazily, so the hook must be on the class).
    - ``appliers`` / ``stages`` / ``partitions``: instances to arm.
    - ``fronts``: NetworkFrontEnd instances — arms the snapshot serving
      seam (``snapshot.chunk`` torn/drop on served chunk wire bytes).
    - ``summarizers``: ServiceSummarizer instances — arms the
      mid-upload crash window (``snapshot.upload``).
    - ``transports=True``: arms driver/network frame delivery for every
      transport constructed while installed.
    """
    undo: list[Callable[[], None]] = []

    def _set(obj, attr: str, value) -> None:
        had = attr in vars(obj) if not isinstance(obj, type) else True
        prev = getattr(obj, attr, None)
        setattr(obj, attr, value)
        if isinstance(obj, type) or had:
            undo.append(lambda: setattr(obj, attr, prev))
        else:
            undo.append(lambda: delattr(obj, attr))

    if server is not None:
        _set(server.log, "fault_plane", plane)
        _set(BroadcasterLambda, "fault_plane", plane)
        # the history plane is built lazily (server.history property), so
        # the hook must sit on the class like the broadcaster's
        _set(HistoryPlane, "fault_plane", plane)
    for applier in appliers:
        _set(applier, "fault_plane", plane)
    for stage in stages:
        _set(stage, "fault_plane", plane)
    for part in partitions:
        _set(part, "fault_plane", plane)
    for front in fronts:
        _set(front, "fault_plane", plane)
    for summ in summarizers:
        _set(summ, "fault_plane", plane)
    if transports:
        prev_hook = _network.FRAME_FAULT_HOOK
        _network.FRAME_FAULT_HOOK = plane
        undo.append(lambda: setattr(_network, "FRAME_FAULT_HOOK",
                                    prev_hook))

    def uninstall() -> None:
        while undo:
            undo.pop()()

    return uninstall


@contextlib.contextmanager
def armed(plane: FaultPlane, **seams):
    """``with armed(plane, server=s): ...`` — install, then always
    uninstall (tests must not leak class-level hooks)."""
    uninstall = install(plane, **seams)
    try:
        yield plane
    finally:
        uninstall()


def arm_log(log, plane: Optional[FaultPlane]) -> None:
    """Arm just an ordered log instance (torn/dup/rewind append faults)."""
    log.fault_plane = plane
