"""Noisy-neighbor chaos scenario: one tenant floods, the other rides.

``python -m fluidframework_tpu.chaos.noisy --seed N`` drives two
driver-stack tenants against one in-process NetworkFrontEnd with the
overload control loop armed:

- ``flood`` has a configured admission budget (token bucket) and
  submits ~10× it in a burst;
- ``steady`` has NO configured rate — structurally unsheddable — and
  trickles ops before, during, and after the flood.

The SLO engine runs WITHOUT its ticker thread: the scenario calls
``evaluate()`` itself on a hair-trigger spec (p99 budget 0 ms on the
``submit_to_admit`` leg, one burn tick), so the shed signal arms at a
deterministic point instead of racing a 500 ms ticker. The run fails
(exit 1, flight-recorder dump path attached) unless:

- every steady op AND every flood op eventually resolves (the driver's
  transparent shed-retry lane must drain the backlog through the
  server's resume watermark without gapping clientSeq at deli);
- ``net.admission.shed`` rose, and every label set it carries names the
  FLOOD tenant only — a single shed op attributed to the steady tenant
  is an isolation violation;
- the flood connection's driver counted ``driver.submit.shed_retries``
  while the steady connection counted none;
- ``obs.slo.state{slo=...}`` reached ``violated``,
  ``obs.slo.violations`` counted the transition, and the engine wrote
  its flight-recorder dump.

Same seed ⇒ same op contents and batch shapes. Green is required at
seeds 0, 7 and 42; ``--quick`` (CI) shrinks the flood.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

FLOOD_TENANT = "flood"
STEADY_TENANT = "steady"
DOC = "noisy"

#: flood tenant's admission budget (ops/s and burst)
CAP = 400.0

_TEXT_POOL = "abcdefgh" * 4


def wait_for(pred, timeout: float = 30.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


class _Tenant:
    """One tenant's driver connection + ack ledger (own factory so the
    driver counters — shed_retries above all — stay per-tenant)."""

    def __init__(self, host: str, port: int, tenant: str,
                 rng: random.Random):
        from ..driver.network import NetworkDocumentServiceFactory

        self.rng = rng
        self.factory = NetworkDocumentServiceFactory(host, port)
        self.conn = self.factory.create_document_service(
            tenant, DOC).connect_to_delta_stream()
        # every boxcar sampled: the hair-trigger SLO needs windowed
        # submit_to_admit observations from the very first submit
        self.conn.trace_sample_n = 1
        self.cseq = 0
        self.submitted = 0
        self.acked = 0
        #: hard refusals (anything that is NOT a transparent shed
        #: retry); a single one wedges the stream, so the scenario
        #: surfaces them by name instead of timing out blind
        self.hard_nacks: list[str] = []
        me = self.conn.client_id

        def on_op(m):
            if m.client_id == me:
                self.acked += 1

        def on_nack(m):
            self.hard_nacks.append(
                f"code={m.code} type={getattr(m.type, 'value', m.type)} "
                f"msg={m.message!r}")
        self.conn.on_op = on_op
        self.conn.on_nack = on_nack

    def submit_boxcar(self, n: int) -> None:
        from ..protocol.messages import DocumentMessage, MessageType

        ops = []
        for _ in range(n):
            self.cseq += 1
            off = self.rng.randrange(8)
            text = _TEXT_POOL[off:off + 1 + self.rng.randrange(6)]
            ops.append(DocumentMessage(
                client_sequence_number=self.cseq,
                reference_sequence_number=0,
                type=MessageType.OPERATION,
                contents={"kind": "chanop", "address": "default",
                          "contents": {"address": "text",
                                       "contents": {"type": 0, "pos": 0,
                                                    "text": text}}}))
        self.conn.submit(ops)
        self.submitted += n

    @property
    def settled(self) -> bool:
        return self.acked >= self.submitted

    def shed_retries(self) -> int:
        return self.factory.counters.snapshot().get(
            "driver.submit.shed_retries", 0)

    def close(self) -> None:
        self.conn.close()


def run_noisy(seed: int, quick: bool = False) -> dict:
    from ..obs import get_recorder, get_registry, parse_prometheus
    from ..obs.slo import STATE_VIOLATED, SloEngine, SloSpec
    from ..service.front_end import NetworkFrontEnd
    from ..service.local_server import LocalServer
    from ..service.tenants import TenantManager

    flood_ops = 800 if quick else 2000
    boxcar = 20

    tm = TenantManager()
    tm.set_rate(FLOOD_TENANT, CAP, burst=CAP)
    front = NetworkFrontEnd(LocalServer(tenants=tm)).start_background()
    engine = SloEngine([SloSpec(
        name="noisy_admit", pair="submit_to_admit", p99_budget_ms=0.0,
        window_s=10.0, burn_ticks=1, min_count=1)])
    front.attach_slo(engine, shedding=True)

    problems: list[str] = []
    try:
        steady = _Tenant("127.0.0.1", front.port, STEADY_TENANT,
                         random.Random(seed * 1000 + 1))
        flood = _Tenant("127.0.0.1", front.port, FLOOD_TENANT,
                        random.Random(seed * 1000 + 2))

        # prime: a few steady boxcars populate the windowed series, then
        # one manual tick trips the hair-trigger spec — the shed signal
        # is armed BEFORE the flood, deterministically
        for _ in range(3):
            steady.submit_boxcar(2)
        if not wait_for(lambda: steady.settled):
            problems.append("steady prime ops never resolved")
        engine.evaluate()
        if not engine.shed_signal:
            problems.append(
                f"hair-trigger SLO did not arm shedding: {engine.status()}")

        # flood ~10× the budget in one burst, steady trickling through
        # it; periodic manual ticks stand in for the disabled ticker
        sent = 0
        while sent < flood_ops:
            flood.submit_boxcar(boxcar)
            sent += boxcar
            if sent % (boxcar * 10) == 0:
                steady.submit_boxcar(2)
                engine.evaluate()
        engine.evaluate()
        if engine._state["noisy_admit"] != STATE_VIOLATED:
            problems.append(
                f"SLO never reached violated: {engine.status()}")

        # drain: the steady tenant must resolve promptly; the flood
        # backlog must drain through the shed-retry lane (bucket refill
        # + the full-bucket oversize admission, see admission.py)
        steady.submit_boxcar(2)
        if not wait_for(lambda: steady.settled, timeout=30.0):
            problems.append(
                f"steady ops unresolved: {steady.acked}/{steady.submitted}")
        if not wait_for(lambda: flood.settled, timeout=120.0):
            problems.append(
                f"flood ops unresolved: {flood.acked}/{flood.submitted}")
        for name, t in (("steady", steady), ("flood", flood)):
            if t.hard_nacks:
                problems.append(
                    f"{name} took {len(t.hard_nacks)} hard nack(s), "
                    f"first: {t.hard_nacks[0]}")

        series = parse_prometheus(get_registry().scrape())
        shed = series.get("fluid_net_admission_shed", {})
        shed_total = sum(shed.values())
        shed_tenants = sorted({dict(k).get("tenant") for k in shed})
        if shed_total <= 0:
            problems.append("flood never shed (net.admission.shed == 0)")
        if shed_tenants not in ([], [FLOOD_TENANT]):
            problems.append(
                f"shed series leaked beyond the flood tenant: "
                f"{shed_tenants}")
        if flood.shed_retries() <= 0:
            problems.append(
                "flood driver never exercised the shed-retry lane")
        if steady.shed_retries() != 0:
            problems.append(
                f"STEADY driver retried sheds "
                f"({steady.shed_retries()}) — isolation broken")
        violations = sum(
            series.get("fluid_obs_slo_violations", {}).values())
        if violations < 1:
            problems.append("obs.slo.violations never counted")
        dump = get_recorder().last_dump
        if not dump:
            problems.append("no flight-recorder dump on the violation")

        result = {
            "seed": seed,
            "flood": {"submitted": flood.submitted, "acked": flood.acked,
                      "shed_retries": flood.shed_retries()},
            "steady": {"submitted": steady.submitted,
                       "acked": steady.acked},
            "shed_ops": shed_total,
            "shed_tenants": shed_tenants,
            "slo": engine.status(),
            "flight_dump": dump,
        }
        steady.close()
        flood.close()
    finally:
        engine.stop()
        front.stop()
    if problems:
        raise AssertionError("; ".join(problems))
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="noisy-neighbor overload-control scenario")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="smaller flood (CI smoke)")
    args = parser.parse_args(argv)
    try:
        result = run_noisy(args.seed, quick=args.quick)
    except AssertionError as e:
        from ..obs import get_recorder

        dump = get_recorder().last_dump
        where = f"\n  flight recorder: {dump}" if dump else ""
        print(f"NOISY FAILED (seed {args.seed}): {e}{where}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
