"""Sequenced-op stream generator for benchmarks and load tests.

Generates valid server-side op streams (seq strictly increasing, ref_seq =
previous seq, positions within the tracked visible length) without running
the oracle — the analytic twin of the reference's load generator
(packages/test/service-load-test/src/nodeStressTest.ts). Because every op's
ref_seq sees all prior ops, the visible length after each op is exact:
+text_len on insert, -(end-start) on remove, unchanged on annotate.

Each op carries an msn that trails its seq by ``msn_lag`` (deli's
collaboration-window floor), driving device zamboni in the benched step.
"""

from __future__ import annotations

import numpy as np

from .apply import OP_ANNOTATE, OP_FIELDS, OP_INSERT, OP_NOOP, OP_REMOVE, make_op


def generate_doc_ops(
    rng: np.random.Generator,
    n_ops: int,
    start_seq: int = 0,
    start_len: int = 0,
    n_clients: int = 4,
    remove_fraction: float = 0.3,
    annotate_fraction: float = 0.0,
    max_insert: int = 16,
    arena_base: int = 0,
    msn_lag: int = 16,
    n_prop_keys: int = 4,
    n_prop_vals: int = 8,
) -> tuple[np.ndarray, int, int]:
    """Return (ops[n_ops, OP_FIELDS], end_len, arena_used)."""
    ops = np.zeros((n_ops, OP_FIELDS), np.int32)
    length = start_len
    arena = arena_base
    seq = start_seq
    for k in range(n_ops):
        seq += 1
        msn = max(0, seq - msn_lag)
        client = int(rng.integers(0, n_clients))
        r = rng.random()
        do_remove = length > 4 and r < remove_fraction
        do_annotate = (
            not do_remove and length > 1 and r < remove_fraction + annotate_fraction
        )
        if do_remove:
            start = int(rng.integers(0, length - 1))
            end = int(rng.integers(start + 1, min(length, start + max_insert) + 1))
            ops[k] = make_op(
                OP_REMOVE, pos=start, end=end, seq=seq, ref_seq=seq - 1,
                client=client, msn=msn,
            )
            length -= end - start
        elif do_annotate:
            start = int(rng.integers(0, length - 1))
            end = int(rng.integers(start + 1, min(length, start + max_insert) + 1))
            ops[k] = make_op(
                OP_ANNOTATE, pos=start, end=end, seq=seq, ref_seq=seq - 1,
                client=client, msn=msn,
                key=int(rng.integers(0, n_prop_keys)),
                val=int(rng.integers(0, n_prop_vals)),
            )
        else:
            tlen = int(rng.integers(1, max_insert + 1))
            pos = int(rng.integers(0, length + 1))
            ops[k] = make_op(
                OP_INSERT,
                pos=pos,
                seq=seq,
                ref_seq=seq - 1,
                client=client,
                text_len=tlen,
                text_start=arena,
                msn=msn,
            )
            arena += tlen
            length += tlen
    return ops, length, arena - arena_base


def generate_batch_ops(
    rng: np.random.Generator,
    n_docs: int,
    ops_per_doc: int,
    **kw,
) -> np.ndarray:
    """[n_docs, ops_per_doc, OP_FIELDS] independent valid streams."""
    out = np.zeros((n_docs, ops_per_doc, OP_FIELDS), np.int32)
    for d in range(n_docs):
        out[d], _, _ = generate_doc_ops(rng, ops_per_doc, **kw)
    return out
