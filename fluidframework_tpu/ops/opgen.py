"""Sequenced-op stream generator for benchmarks and load tests.

Generates valid server-side op streams (seq strictly increasing, ref_seq =
previous seq, positions within the tracked visible length) without running
the oracle — the analytic twin of the reference's load generator
(packages/test/service-load-test/src/nodeStressTest.ts). Because every op's
ref_seq sees all prior ops, the visible length after each op is exact:
+text_len on insert, -(end-start) on remove.
"""

from __future__ import annotations

import numpy as np

from .apply import OP_FIELDS, OP_INSERT, OP_NOOP, OP_REMOVE, make_op


def generate_doc_ops(
    rng: np.random.Generator,
    n_ops: int,
    start_seq: int = 0,
    start_len: int = 0,
    n_clients: int = 4,
    remove_fraction: float = 0.3,
    max_insert: int = 16,
    arena_base: int = 0,
) -> tuple[np.ndarray, int, int]:
    """Return (ops[n_ops, OP_FIELDS], end_len, arena_used)."""
    ops = np.zeros((n_ops, OP_FIELDS), np.int32)
    length = start_len
    arena = arena_base
    seq = start_seq
    for k in range(n_ops):
        seq += 1
        client = int(rng.integers(0, n_clients))
        do_remove = length > 4 and rng.random() < remove_fraction
        if do_remove:
            start = int(rng.integers(0, length - 1))
            end = int(rng.integers(start + 1, min(length, start + max_insert) + 1))
            ops[k] = make_op(
                OP_REMOVE, pos=start, end=end, seq=seq, ref_seq=seq - 1, client=client
            )
            length -= end - start
        else:
            tlen = int(rng.integers(1, max_insert + 1))
            pos = int(rng.integers(0, length + 1))
            ops[k] = make_op(
                OP_INSERT,
                pos=pos,
                seq=seq,
                ref_seq=seq - 1,
                client=client,
                text_len=tlen,
                text_start=arena,
            )
            arena += tlen
            length += tlen
    return ops, length, arena - arena_base


def generate_batch_ops(
    rng: np.random.Generator,
    n_docs: int,
    ops_per_doc: int,
    **kw,
) -> np.ndarray:
    """[n_docs, ops_per_doc, OP_FIELDS] independent valid streams."""
    out = np.zeros((n_docs, ops_per_doc, OP_FIELDS), np.int32)
    for d in range(n_docs):
        out[d], _, _ = generate_doc_ops(rng, ops_per_doc, **kw)
    return out
