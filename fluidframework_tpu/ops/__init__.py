"""TPU tensor kernels for the merge-tree hot path.

The server-side replicas (deli sequencing validation, scribe summaries,
catch-up replay) apply SEQUENCED ops only — no pending local state — so
segment visibility is a pure function of int32 stamps and the concurrent-
insert tie-break degenerates to "earliest boundary" (ops arrive in seq
order, so no existing stamp can exceed the incoming seq). That makes the
whole apply step masks + prefix sums + gathers: exactly what vectorizes.

Layout: structure-of-arrays per document, vmapped across a ragged batch of
documents (ref: the PartialSequenceLengths prefix-sum structure this
vectorizes, packages/dds/merge-tree/src/partialLengths.ts:62).
"""

from .doc_state import DocState, TextArena, encode_tree, decode_state, NO_SEQ
from .apply import (
    apply_op,
    apply_op_batch,
    apply_ops_scan,
    compact,
    make_op,
    OP_NOOP,
    OP_INSERT,
    OP_REMOVE,
    OP_FIELDS,
)

__all__ = [
    "DocState",
    "TextArena",
    "encode_tree",
    "decode_state",
    "NO_SEQ",
    "apply_op",
    "apply_op_batch",
    "apply_ops_scan",
    "compact",
    "make_op",
    "OP_NOOP",
    "OP_INSERT",
    "OP_REMOVE",
    "OP_FIELDS",
]
