"""Device-resident document state: structure-of-arrays segment store.

Per document, ``max_slots`` fixed-capacity int32 arrays (XLA needs static
shapes; capacity overflow raises a per-doc flag for host escalation):

- ``length``     segment length (0 ⇒ unused slot; markers have length 1)
- ``text_start`` offset into the host-side text arena; segment splits are
                 pure arithmetic (tail start = head start + offset), so the
                 device never touches text bytes
- ``flags``      bit 0 = marker. Marker-ness is out-of-band — the arena
                 byte is NOT the classifier, so user text containing the
                 marker glyph U+FFFC round-trips correctly
- ``ins_seq``, ``ins_client``          insert stamp
- ``rem_seq``    earliest remove seq (NO_SEQ = never removed)
- ``rem_client_a``, ``rem_client_b``   up to two removing clients; a third
                 concurrent remover of the same segment sets ``overflow``
                 and the host replays that doc on the scalar oracle
- ``prop_key``, ``prop_val``  [S, P] per-slot annotation table: up to P
                 interned (key, value) property pairs (key -1 = empty
                 slot). LWW per key falls out of seq-ordered apply; a slot
                 needing a (P+1)th distinct key sets ``overflow``.
                 Ref: annotateRange mergeTree.ts:2598 +
                 segmentPropertiesManager.ts, tensorized
- ``count``      used slots (slots [0, count) are ordered and contiguous)

Ref: this is the tensorized form of the segment metadata in
packages/dds/merge-tree/src/mergeTree.ts (insert/remove stamps) with the
per-block PartialSequenceLengths cache (partialLengths.ts:62) replaced by
on-the-fly masked prefix sums.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree.mergetree import MergeTree
from ..mergetree.segments import NO_CLIENT, Segment

NO_SEQ = -1  # "never removed" sentinel
NO_KEY = -1  # empty property-table slot
FLAG_MARKER = 1  # flags bit 0

DEFAULT_MAX_PROPS = 8  # P: per-slot property-table capacity


class PropTable:
    """Host-side interning of annotation keys and values to dense int32
    ids. Dense interning (not hashing) — no collisions by construction.
    Values are canonicalised through JSON so equal values share one id."""

    def __init__(self):
        self._keys: list[str] = []
        self._key_ids: dict[str, int] = {}
        self._vals: list[Any] = []
        self._val_ids: dict[str, int] = {}

    def intern_key(self, key: str) -> int:
        kid = self._key_ids.get(key)
        if kid is None:
            kid = len(self._keys)
            self._key_ids[key] = kid
            self._keys.append(key)
        return kid

    def intern_val(self, value: Any) -> int:
        canon = json.dumps(value, sort_keys=True)
        vid = self._val_ids.get(canon)
        if vid is None:
            vid = len(self._vals)
            self._val_ids[canon] = vid
            self._vals.append(value)
        return vid

    def key(self, kid: int) -> str:
        return self._keys[kid]

    def val(self, vid: int) -> Any:
        return self._vals[vid]

    def snapshot(self) -> dict:
        return {"keys": list(self._keys), "vals": list(self._vals)}

    @classmethod
    def load(cls, snap: dict) -> "PropTable":
        t = cls()
        for k in snap["keys"]:
            t.intern_key(k)
        for v in snap["vals"]:
            t.intern_val(v)
        return t


@jax.tree_util.register_dataclass
@dataclass
class DocState:
    """One document (or, with a leading batch dim, D documents)."""

    length: jax.Array  # [S] int32
    text_start: jax.Array  # [S] int32
    flags: jax.Array  # [S] int32 (bit 0: marker)
    ins_seq: jax.Array  # [S] int32
    ins_client: jax.Array  # [S] int32
    rem_seq: jax.Array  # [S] int32
    rem_client_a: jax.Array  # [S] int32
    rem_client_b: jax.Array  # [S] int32
    prop_key: jax.Array  # [S, P] int32 (NO_KEY = empty)
    prop_val: jax.Array  # [S, P] int32
    count: jax.Array  # [] int32
    overflow: jax.Array  # [] bool — capacity / remove-client / prop overflow

    @property
    def max_slots(self) -> int:
        return self.length.shape[-1]

    @property
    def max_props(self) -> int:
        return self.prop_key.shape[-1]

    @classmethod
    def empty(cls, max_slots: int, max_props: int = DEFAULT_MAX_PROPS) -> "DocState":
        z = jnp.zeros((max_slots,), jnp.int32)
        return cls(
            length=z,
            text_start=z,
            flags=z,
            ins_seq=z,
            ins_client=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            rem_seq=jnp.full((max_slots,), NO_SEQ, jnp.int32),
            rem_client_a=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            rem_client_b=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            prop_key=jnp.full((max_slots, max_props), NO_KEY, jnp.int32),
            prop_val=jnp.zeros((max_slots, max_props), jnp.int32),
            count=jnp.asarray(0, jnp.int32),
            overflow=jnp.asarray(False, jnp.bool_),
        )


class TextArena:
    """Host-side append-only text store; the device sees only offsets."""

    def __init__(self):
        self._chunks: list[str] = []
        self._len = 0

    def append(self, text: str) -> int:
        start = self._len
        self._chunks.append(text)
        self._len += len(text)
        return start

    def text(self) -> str:
        if len(self._chunks) > 1:
            self._chunks = ["".join(self._chunks)]
        return self._chunks[0] if self._chunks else ""

    def slice(self, start: int, length: int) -> str:
        return self.text()[start : start + length]


def encode_tree(
    tree: MergeTree,
    arena: TextArena,
    max_slots: int,
    max_props: int = DEFAULT_MAX_PROPS,
    prop_table: Optional[PropTable] = None,
) -> DocState:
    """Encode a (fully-acked) oracle MergeTree into device arrays.

    Used to upload a doc snapshot to the device batch and by the
    kernel-vs-oracle validation tests. Segment properties require a
    ``prop_table`` to intern into (omitted ⇒ props raise).
    """
    n = len(tree.segments)
    if n > max_slots:
        raise ValueError(f"{n} segments exceed {max_slots} slots")
    length = np.zeros(max_slots, np.int32)
    text_start = np.zeros(max_slots, np.int32)
    flags = np.zeros(max_slots, np.int32)
    ins_seq = np.zeros(max_slots, np.int32)
    ins_client = np.full(max_slots, NO_CLIENT, np.int32)
    rem_seq = np.full(max_slots, NO_SEQ, np.int32)
    rem_a = np.full(max_slots, NO_CLIENT, np.int32)
    rem_b = np.full(max_slots, NO_CLIENT, np.int32)
    prop_key = np.full((max_slots, max_props), NO_KEY, np.int32)
    prop_val = np.zeros((max_slots, max_props), np.int32)
    overflow = False
    for i, seg in enumerate(tree.segments):
        if seg.is_pending():
            raise ValueError("cannot encode pending local state")
        length[i] = seg.length
        if seg.is_marker:
            # a 1-char placeholder keeps arena offsets consistent; the
            # flag, not the byte, marks it as a marker
            text_start[i] = arena.append("￼")
            flags[i] |= FLAG_MARKER
        else:
            text_start[i] = arena.append(seg.text)
        ins_seq[i] = seg.ins_seq
        ins_client[i] = seg.ins_client
        if seg.rem_seq is not None:
            rem_seq[i] = seg.rem_seq
            removers = sorted(seg.rem_clients)
            rem_a[i] = removers[0]
            if len(removers) > 1:
                rem_b[i] = removers[1]
            if len(removers) > 2:
                overflow = True
        if seg.props:
            if prop_table is None:
                raise ValueError("segment has props but no prop_table given")
            items = list(seg.props.items())
            if len(items) > max_props:
                overflow = True
                items = items[:max_props]
            for p, (k, v) in enumerate(items):
                prop_key[i, p] = prop_table.intern_key(k)
                prop_val[i, p] = prop_table.intern_val(v)
    return DocState(
        length=jnp.asarray(length),
        text_start=jnp.asarray(text_start),
        flags=jnp.asarray(flags),
        ins_seq=jnp.asarray(ins_seq),
        ins_client=jnp.asarray(ins_client),
        rem_seq=jnp.asarray(rem_seq),
        rem_client_a=jnp.asarray(rem_a),
        rem_client_b=jnp.asarray(rem_b),
        prop_key=jnp.asarray(prop_key),
        prop_val=jnp.asarray(prop_val),
        count=jnp.asarray(n, jnp.int32),
        overflow=jnp.asarray(overflow, jnp.bool_),
    )


def decode_state(
    state: DocState,
    arena: TextArena,
    prop_table: Optional[PropTable] = None,
) -> MergeTree:
    """Decode device arrays back into an oracle MergeTree (for comparison,
    summaries, and host escalation)."""
    tree = MergeTree()
    count = int(state.count)
    length = np.asarray(state.length)
    text_start = np.asarray(state.text_start)
    flags = np.asarray(state.flags)
    ins_seq = np.asarray(state.ins_seq)
    ins_client = np.asarray(state.ins_client)
    rem_seq = np.asarray(state.rem_seq)
    rem_a = np.asarray(state.rem_client_a)
    rem_b = np.asarray(state.rem_client_b)
    prop_key = np.asarray(state.prop_key)
    prop_val = np.asarray(state.prop_val)
    for i in range(count):
        is_marker = bool(flags[i] & FLAG_MARKER)
        text = "" if is_marker else arena.slice(int(text_start[i]), int(length[i]))
        props = {}
        for p in range(prop_key.shape[1]):
            if prop_key[i, p] != NO_KEY:
                if prop_table is None:
                    raise ValueError("state has props but no prop_table given")
                props[prop_table.key(int(prop_key[i, p]))] = prop_table.val(
                    int(prop_val[i, p])
                )
        seg = Segment(
            text=text,
            marker={"refType": 1} if is_marker else None,
            props=props,
            ins_seq=int(ins_seq[i]),
            ins_client=int(ins_client[i]),
        )
        if rem_seq[i] != NO_SEQ:
            seg.rem_seq = int(rem_seq[i])
            seg.rem_client = int(rem_a[i])
            seg.rem_clients = {int(rem_a[i])}
            if rem_b[i] != NO_CLIENT:
                seg.rem_clients.add(int(rem_b[i]))
        tree.segments.append(seg)
    return tree
