"""Device-resident document state: structure-of-arrays segment store.

Per document, ``max_slots`` fixed-capacity int32 arrays (XLA needs static
shapes; capacity overflow raises a per-doc flag for host escalation):

- ``length``     segment length (0 ⇒ unused slot; markers have length 1)
- ``text_start`` offset into the host-side text arena; segment splits are
                 pure arithmetic (tail start = head start + offset), so the
                 device never touches text bytes
- ``ins_seq``, ``ins_client``          insert stamp
- ``rem_seq``    earliest remove seq (NO_SEQ = never removed)
- ``rem_client_a``, ``rem_client_b``   up to two removing clients; a third
                 concurrent remover of the same segment sets ``overflow``
                 and the host replays that doc on the scalar oracle
- ``count``      used slots (slots [0, count) are ordered and contiguous)

Ref: this is the tensorized form of the segment metadata in
packages/dds/merge-tree/src/mergeTree.ts (insert/remove stamps) with the
per-block PartialSequenceLengths cache (partialLengths.ts:62) replaced by
on-the-fly masked prefix sums.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree.mergetree import MergeTree
from ..mergetree.segments import NO_CLIENT, Segment

NO_SEQ = -1  # "never removed" sentinel


@jax.tree_util.register_dataclass
@dataclass
class DocState:
    """One document (or, with a leading batch dim, D documents)."""

    length: jax.Array  # [S] int32
    text_start: jax.Array  # [S] int32
    ins_seq: jax.Array  # [S] int32
    ins_client: jax.Array  # [S] int32
    rem_seq: jax.Array  # [S] int32
    rem_client_a: jax.Array  # [S] int32
    rem_client_b: jax.Array  # [S] int32
    count: jax.Array  # [] int32
    overflow: jax.Array  # [] bool — capacity or remove-client overflow

    @property
    def max_slots(self) -> int:
        return self.length.shape[-1]

    @classmethod
    def empty(cls, max_slots: int) -> "DocState":
        z = jnp.zeros((max_slots,), jnp.int32)
        return cls(
            length=z,
            text_start=z,
            ins_seq=z,
            ins_client=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            rem_seq=jnp.full((max_slots,), NO_SEQ, jnp.int32),
            rem_client_a=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            rem_client_b=jnp.full((max_slots,), NO_CLIENT, jnp.int32),
            count=jnp.asarray(0, jnp.int32),
            overflow=jnp.asarray(False, jnp.bool_),
        )


class TextArena:
    """Host-side append-only text store; the device sees only offsets."""

    def __init__(self):
        self._chunks: list[str] = []
        self._len = 0

    def append(self, text: str) -> int:
        start = self._len
        self._chunks.append(text)
        self._len += len(text)
        return start

    def text(self) -> str:
        if len(self._chunks) > 1:
            self._chunks = ["".join(self._chunks)]
        return self._chunks[0] if self._chunks else ""

    def slice(self, start: int, length: int) -> str:
        return self.text()[start : start + length]


def encode_tree(tree: MergeTree, arena: TextArena, max_slots: int) -> DocState:
    """Encode a (fully-acked) oracle MergeTree into device arrays.

    Used to upload a doc snapshot to the device batch and by the
    kernel-vs-oracle validation tests.
    """
    n = len(tree.segments)
    if n > max_slots:
        raise ValueError(f"{n} segments exceed {max_slots} slots")
    length = np.zeros(max_slots, np.int32)
    text_start = np.zeros(max_slots, np.int32)
    ins_seq = np.zeros(max_slots, np.int32)
    ins_client = np.full(max_slots, NO_CLIENT, np.int32)
    rem_seq = np.full(max_slots, NO_SEQ, np.int32)
    rem_a = np.full(max_slots, NO_CLIENT, np.int32)
    rem_b = np.full(max_slots, NO_CLIENT, np.int32)
    overflow = False
    for i, seg in enumerate(tree.segments):
        if seg.is_pending():
            raise ValueError("cannot encode pending local state")
        length[i] = seg.length
        text_start[i] = arena.append("￼" if seg.is_marker else seg.text)
        ins_seq[i] = seg.ins_seq
        ins_client[i] = seg.ins_client
        if seg.rem_seq is not None:
            rem_seq[i] = seg.rem_seq
            removers = sorted(seg.rem_clients)
            rem_a[i] = removers[0]
            if len(removers) > 1:
                rem_b[i] = removers[1]
            if len(removers) > 2:
                overflow = True
    return DocState(
        length=jnp.asarray(length),
        text_start=jnp.asarray(text_start),
        ins_seq=jnp.asarray(ins_seq),
        ins_client=jnp.asarray(ins_client),
        rem_seq=jnp.asarray(rem_seq),
        rem_client_a=jnp.asarray(rem_a),
        rem_client_b=jnp.asarray(rem_b),
        count=jnp.asarray(n, jnp.int32),
        overflow=jnp.asarray(overflow, jnp.bool_),
    )


def decode_state(state: DocState, arena: TextArena) -> MergeTree:
    """Decode device arrays back into an oracle MergeTree (for comparison,
    summaries, and host escalation)."""
    tree = MergeTree()
    count = int(state.count)
    length = np.asarray(state.length)
    text_start = np.asarray(state.text_start)
    ins_seq = np.asarray(state.ins_seq)
    ins_client = np.asarray(state.ins_client)
    rem_seq = np.asarray(state.rem_seq)
    rem_a = np.asarray(state.rem_client_a)
    rem_b = np.asarray(state.rem_client_b)
    for i in range(count):
        text = arena.slice(int(text_start[i]), int(length[i]))
        is_marker = text == "￼"
        seg = Segment(
            text="" if is_marker else text,
            marker={"refType": 1} if is_marker else None,
            ins_seq=int(ins_seq[i]),
            ins_client=int(ins_client[i]),
        )
        if rem_seq[i] != NO_SEQ:
            seg.rem_seq = int(rem_seq[i])
            seg.rem_client = int(rem_a[i])
            seg.rem_clients = {int(rem_a[i])}
            if rem_b[i] != NO_CLIENT:
                seg.rem_clients.add(int(rem_b[i]))
        tree.segments.append(seg)
    return tree
