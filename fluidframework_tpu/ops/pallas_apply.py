"""Pallas TPU kernel: VMEM-resident batched merge-tree apply.

The XLA scan (`ops/apply.apply_ops_batch`) re-reads and re-writes the
[D, S] doc state from HBM across the K scan steps. This kernel grids
over tiles of R=8 docs (a full VPU sublane tile), loads each tile's slot
arrays into VMEM ONCE, applies all K ops with a `fori_loop` carrying the
state in registers/VMEM, and writes back once — state traffic drops from
O(K·|state|) to O(|state|) per wave. Measured on the v5e chip: ~8%
faster than the XLA scan at K=64-128 (1.56M vs 1.45M ops/s at K=128) —
the apply turns out to be closer to compute-bound than HBM-bound once
XLA's own fusion is accounted for, so residency buys the margin, not a
multiple.

The op semantics are a line-for-line 2D port of `apply._apply_core`
(leading dim R, slot axis last; per-doc scalars as [R, 1] columns;
dynamic extracts as masked row-sums — TPU-safe forms per the Pallas
guide). Parity with the XLA kernel (and through it the scalar oracle) is
enforced by tests/test_pallas_apply.py on fuzzed streams.

Zamboni compaction stays in XLA (`apply.compact_batch`): it runs once
per wave, not per op, so it is not on the K-amplified path.

Mosaic lowering constraints found by bisection on this toolchain (and
baked into the shapes here): bool and 3-D arrays crash the compiler when
loop-carried, and jnp.cumsum / value-level dynamic_slice / argmax do not
lower — hence int32 overflow, prop tables carried as P separate 2-D
planes (statically unrolled), the blocked segmented lane scan (see
_cumsum_lanes), ref-level pl.ds reads, and masked-min first-True
selection.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .apply import (
    F_CLIENT,
    F_END,
    F_FLAGS,
    F_KEY,
    F_MSN,
    F_POS,
    F_REFSEQ,
    F_SEQ,
    F_TLEN,
    F_TSTART,
    F_TYPE,
    F_VAL,
    NO_CLIENT,
    NO_VAL,
    OP_ANNOTATE,
    OP_FIELDS,
    OP_INSERT,
    OP_REMOVE,
)
from ..utils.contracts import kernel_contract
from .doc_state import NO_KEY, NO_SEQ, DocState

R = 8  # docs per grid instance: one full VPU sublane tile

_FIELDS_1D = ("length", "text_start", "flags", "ins_seq", "ins_client",
              "rem_seq", "rem_client_a", "rem_client_b")


def _rowtake(col, a, j):
    """a[row, j[row]] as a masked row-sum ([R, S] × [R, 1] → [R, 1])."""
    return jnp.sum(jnp.where(col == j, a, 0), axis=1, keepdims=True)


#: segmented-scan block width: one vector register row of lanes. Strides
#: below this stay inside a single register rotate on the VPU; strides at
#: or above it cross register boundaries and pay a real shuffle.
SCAN_BLOCK = 128


def _cumsum_lanes(x, col, S, block=None):
    """Inclusive prefix sum along the lane axis as a BLOCKED segmented
    scan (SURVEY §5.7: per-block partial sums are a segmented
    prefix-sum), replacing the flat Hillis-Steele lane scan.

    Three phases over blocks of B = min(block, S) lanes:

    1. within-block inclusive scan — log2(B) Hillis-Steele rounds whose
       mask confines every roll to its own block (``lane >= n`` kills
       both the circular wrap and cross-block bleed);
    2. block partial sums (the §5.7 partial-lengths table) live at each
       block's last lane; an inter-block Hillis-Steele at strides
       B..S/2 turns them into an inclusive scan of block totals —
       block-end lanes map to block-end lanes under multiples of B, so
       non-end lanes only ever accumulate rolled zeros;
    3. each block j>0 picks up block j-1's scanned total (roll by 1
       lands it on the block's first lane) and broadcasts it across the
       block with one more masked prefix pass.

    Round count is 2·log2(B) + log2(S/B) + 1 vs the flat scan's
    log2(S) — MORE rounds, but all except the log2(S/B) carry rounds
    run at stride < B, i.e. inside one vector register row; the flat
    scan's large-stride rolls (up to S/2 lanes) are the ones that cost
    cross-register shuffles on real TPUs. Off-TPU (interpret mode) the
    two are numerically identical; parity with jnp.cumsum is pinned by
    tests/test_pallas_apply.py. jnp.cumsum itself does not lower in
    Pallas TPU, hence the roll+mask formulation throughout."""
    B = min(block or SCAN_BLOCK, S)
    assert S % B == 0, (S, B)
    lane = col % B  # col is an iota, so this is plain int arithmetic
    n = 1
    while n < B:
        x = x + jnp.where(lane >= n, pltpu.roll(x, n, 1), 0)
        n *= 2
    if B == S:
        return x
    # phase 2: scan the per-block totals (resident at block-end lanes)
    tot = jnp.where(lane == B - 1, x, 0)
    n = B
    while n < S:
        tot = tot + jnp.where(col >= n, pltpu.roll(tot, n, 1), 0)
        n *= 2
    # phase 3: block j's carry = scanned total through block j-1; the
    # col >= B mask keeps block 0 carry-free (roll is circular)
    carry = jnp.where((lane == 0) & (col >= B), pltpu.roll(tot, 1, 1), 0)
    n = 1
    while n < B:
        carry = carry + jnp.where(lane >= n, pltpu.roll(carry, n, 1), 0)
        n *= 2
    return x + carry


def _apply_one(carry, op_row, S):
    """One op across the R-doc tile; mirrors apply._apply_core in 2D."""
    (length, tstart, flags, iseq, icl, rseq, rca, rcb, pk, pv,
     count, ovf) = carry
    col = lax.broadcasted_iota(jnp.int32, (R, S), 1)

    def f(i):
        return op_row[:, i][:, None]  # [R, 1]

    typ = f(F_TYPE)
    is_ins = typ == OP_INSERT
    is_rem = typ == OP_REMOVE
    is_ann = typ == OP_ANNOTATE
    active = is_ins | is_rem | is_ann
    pos, end = f(F_POS), f(F_END)
    seq, ref, client = f(F_SEQ), f(F_REFSEQ), f(F_CLIENT)
    p2 = jnp.where(is_ins, pos, end)

    # visibility at the op's perspective (apply._visibility, 2D)
    in_use = col < count
    ins_seen = (icl == client) | (iseq <= ref)
    removed = (rseq != NO_SEQ) & (
        (rca == client) | (rcb == client) | (rseq <= ref))
    vis = in_use & ins_seen & ~removed
    vlen = jnp.where(vis, length, 0)
    cum = _cumsum_lanes(vlen, col, S) - vlen
    total = jnp.sum(vlen, axis=1, keepdims=True)
    inc = cum + vlen

    # pure logic form: jnp.where with BOOL branches crashes this Mosaic
    # toolchain (as does pltpu.roll on bools — see vis_r below)
    bad_shape = (is_ins & (pos > total)) | (
        ~is_ins & ((end > total) | (end <= pos)))
    inside1 = vis & (cum < pos) & (pos < inc)
    inside2 = vis & (cum < p2) & (p2 < inc)
    s1_raw = jnp.any(inside1, axis=1, keepdims=True)
    s2_raw = (~is_ins) & jnp.any(inside2, axis=1, keepdims=True)
    needed = (s1_raw.astype(jnp.int32) + s2_raw.astype(jnp.int32)
              + is_ins.astype(jnp.int32))
    bad = active & (bad_shape | (count + needed > S))
    ok = active & ~bad
    s1 = s1_raw & ok
    s2 = s2_raw & ok
    do_ins = is_ins & ok

    # first-True via masked min (argmax-free: reliably lowers on TPU);
    # the no-match sentinel S is safe — every use is gated on s1/s2/ok
    j1 = jnp.min(jnp.where(inside1, col, S), axis=1, keepdims=True)
    j2 = jnp.min(jnp.where(inside2, col, S), axis=1, keepdims=True)
    o1 = pos - _rowtake(col, cum, j1)
    o2 = p2 - _rowtake(col, cum, j2)
    l1 = _rowtake(col, length, j1)
    ts1 = _rowtake(col, tstart, j1)
    l2 = _rowtake(col, length, j2)
    ts2 = _rowtake(col, tstart, j2)
    same = s1 & s2 & (j1 == j2)

    s1i = s1.astype(jnp.int32)
    idx0 = jnp.min(jnp.where(cum >= pos, col, S), axis=1, keepdims=True)
    p_ins = jnp.where(s1, j1 + 1, idx0)
    p_n1 = jnp.where(do_ins, p_ins + 1, j1 + 1)
    p_h2 = j2 + s1i
    p_n2 = j2 + 1 + s1i

    delta = ((s1 & (col >= p_n1)).astype(jnp.int32)
             + (s2 & (col >= p_n2)).astype(jnp.int32)
             + (do_ins & (col >= p_ins)).astype(jnp.int32))
    d1 = delta == 1
    d2 = delta == 2
    head1_at = s1 & (col == j1)
    n1_at = s1 & (col == p_n1)
    h2_at = s2 & ~same & (col == p_h2)
    n2_at = s2 & (col == p_n2)
    new_at = do_ins & (col == p_ins)

    tlen, tst = f(F_TLEN), f(F_TSTART)
    new_len = jnp.where(tlen > 0, tlen, 1)
    n1_len = jnp.where(same, o2 - o1, l1 - o1)

    def sh1(a):
        return pltpu.roll(a, 1, 1)

    def sh2(a):
        return pltpu.roll(a, 2, 1)

    def rebuild(a, new_val=None, patches=()):
        out = jnp.where(d1, sh1(a), jnp.where(d2, sh2(a), a))
        for mask, val in patches:
            out = jnp.where(mask, val, out)
        if new_val is not None:
            out = jnp.where(new_at, new_val, out)
        return out

    length_o = rebuild(length, new_len,
                       [(head1_at, o1), (n1_at, n1_len), (h2_at, o2),
                        (n2_at, l2 - o2)])
    tstart_o = rebuild(tstart, tst, [(n1_at, ts1 + o1), (n2_at, ts2 + o2)])
    flags_o = rebuild(flags, f(F_FLAGS))
    iseq_o = rebuild(iseq, seq)
    icl_o = rebuild(icl, client)
    rseq_o = rebuild(rseq, NO_SEQ)
    rca_o = rebuild(rca, NO_CLIENT)
    rcb_o = rebuild(rcb, NO_CLIENT)
    # prop tables ride as P separate [R, S] planes (3-D loop carries
    # crash Mosaic); the lane axis unrolls statically
    pk_o = tuple(
        jnp.where(new_at, NO_KEY,
                  jnp.where(d1, sh1(a), jnp.where(d2, sh2(a), a)))
        for a in pk)
    pv_o = tuple(
        jnp.where(new_at, 0,
                  jnp.where(d1, sh1(a), jnp.where(d2, sh2(a), a)))
        for a in pv)
    count_o = count + s1i + s2.astype(jnp.int32) + do_ins.astype(jnp.int32)

    # remove/annotate coverage on the ROLLED perspective arrays; vis
    # rides as an int mask (bool rolls crash Mosaic here)
    vism = vis.astype(jnp.int32)
    vis_r = jnp.where(d1, sh1(vism), jnp.where(d2, sh2(vism), vism)) > 0
    cum_r = jnp.where(d1, sh1(cum), jnp.where(d2, sh2(cum), cum))
    cum_r = jnp.where(n1_at, _rowtake(col, cum, j1) + o1, cum_r)
    cum_r = jnp.where(n2_at, _rowtake(col, cum, j2) + o2, cum_r)
    vlen_r = jnp.where(vis_r, length_o, 0)
    covered = vis_r & (cum_r >= pos) & (cum_r + vlen_r <= end)
    rm = is_rem & ~bad & covered
    fresh = rm & (rseq_o == NO_SEQ)
    over = rm & (rseq_o != NO_SEQ)
    add_b = over & (rca_o != client) & (rcb_o == NO_CLIENT)
    third = over & (rca_o != client) & (rcb_o != client) & \
        (rcb_o != NO_CLIENT)

    key, val = f(F_KEY), f(F_VAL)
    an = is_ann & ~bad & covered
    P_ = len(pk_o)
    match = [a == key for a in pk_o]
    empty = [a == NO_KEY for a in pk_o]
    has_key = functools.reduce(jnp.logical_or, match)
    has_empty = functools.reduce(jnp.logical_or, empty)
    # first matching (else first empty) lane, as a static priority walk
    big = jnp.int32(P_)
    tgt_m = big
    tgt_e = big
    for lane in range(P_ - 1, -1, -1):
        tgt_m = jnp.where(match[lane], lane, tgt_m)
        tgt_e = jnp.where(empty[lane], lane, tgt_e)
    tgt = jnp.where(has_key, tgt_m, tgt_e)
    is_delete = val == NO_VAL
    do_write = an & (has_key | (~is_delete & has_empty))
    table_full = jnp.any(an & ~has_key & ~has_empty & ~is_delete,
                         axis=1, keepdims=True)
    pk_o = tuple(
        jnp.where(do_write & (tgt == lane),
                  jnp.where(is_delete, NO_KEY, key), a)
        for lane, a in enumerate(pk_o))
    pv_o = tuple(
        jnp.where(do_write & (tgt == lane),
                  jnp.where(is_delete, 0, val), a)
        for lane, a in enumerate(pv_o))

    # overflow rides as int32: a bool loop carry crashes the Mosaic
    # compiler (bisected on the tunneled toolchain)
    ovf_o = ovf | (jnp.any(third, axis=1, keepdims=True)
                   | table_full | bad).astype(jnp.int32)

    return (length_o, tstart_o, flags_o, iseq_o, icl_o,
            jnp.where(fresh, seq, rseq_o),
            jnp.where(fresh, client, rca_o),
            jnp.where(add_b, client, rcb_o),
            pk_o, pv_o, count_o, ovf_o)


def _kernel(ops_ref, length, tstart, flags, iseq, icl, rseq, rca, rcb,
            pk, pv, count, ovf,
            o_length, o_tstart, o_flags, o_iseq, o_icl, o_rseq, o_rca,
            o_rcb, o_pk, o_pv, o_count, o_ovf, *, S, K):
    P = pk.shape[-1]
    carry = (length[:, :], tstart[:, :], flags[:, :], iseq[:, :],
             icl[:, :], rseq[:, :], rca[:, :], rcb[:, :],
             tuple(pk[:, :, p] for p in range(P)),
             tuple(pv[:, :, p] for p in range(P)),
             count[:, :], ovf[:, :])
    def body(k, carry):
        # dynamic-sliced REF read (value-level dynamic_slice does not
        # lower in Pallas TPU)
        op_row = ops_ref[:, pl.ds(k, 1), :][:, 0, :]  # [R, F]
        return _apply_one(carry, op_row, S)

    out = lax.fori_loop(0, K, body, carry)
    for ref, arr in zip(
        (o_length, o_tstart, o_flags, o_iseq, o_icl, o_rseq, o_rca,
         o_rcb), out[:8]):
        ref[...] = arr
    for p in range(P):
        o_pk[:, :, p] = out[8][p]
        o_pv[:, :, p] = out[9][p]
    o_count[...] = out[10]
    o_ovf[...] = out[11]


def _contract_example():
    """One R-tile wave in interpret mode (the checker runs on CPU)."""
    D, S, K = R, 16, 4
    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    ops = jnp.zeros((D, K, OP_FIELDS), jnp.int32)
    return (state, ops), {"interpret": True}


# contract: the VMEM-resident apply must stay roll/select like its XLA
# twin — the checker walks INTO the pallas_call kernel jaxpr, so a
# gather smuggled into the Mosaic body fails the same way
@kernel_contract(
    "ops.pallas_apply_ops_batch",
    example=_contract_example,
    no_gather=True,
    no_scatter=True,
    no_int16_arithmetic=True,
    single_jit=True,
    notes="Pallas VMEM-resident apply (tile of R docs, blocked "
          "segmented lane scan)",
)
@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_apply_ops_batch(state: DocState, ops: jax.Array,
                           interpret: bool = False) -> DocState:
    """Drop-in twin of ``apply.apply_ops_batch`` (no compact): applies a
    NOOP-padded [D, K, F] wave with doc state resident in VMEM."""
    D = state.length.shape[0]
    S = state.length.shape[1]
    P = state.prop_key.shape[-1]
    K = ops.shape[1]
    assert D % R == 0, f"doc count {D} must be a multiple of {R}"
    count2 = state.count.astype(jnp.int32).reshape(D, 1)
    ovf2 = state.overflow.astype(jnp.int32).reshape(D, 1)

    grid = (D // R,)
    row = pl.BlockSpec((R, S), lambda i: (i, 0))
    rowp = pl.BlockSpec((R, S, P), lambda i: (i, 0, 0))
    row1 = pl.BlockSpec((R, 1), lambda i: (i, 0))
    opspec = pl.BlockSpec((R, K, OP_FIELDS), lambda i: (i, 0, 0))

    shapes = (
        [jax.ShapeDtypeStruct((D, S), jnp.int32)] * 8
        + [jax.ShapeDtypeStruct((D, S, P), jnp.int32)] * 2
        + [jax.ShapeDtypeStruct((D, 1), jnp.int32),
           jax.ShapeDtypeStruct((D, 1), jnp.int32)]
    )
    outs = pl.pallas_call(
        functools.partial(_kernel, S=S, K=K),
        grid=grid,
        in_specs=[opspec] + [row] * 8 + [rowp] * 2 + [row1, row1],
        out_specs=[row] * 8 + [rowp] * 2 + [row1, row1],
        out_shape=shapes,
        interpret=interpret,
    )(ops, *(getattr(state, f) for f in _FIELDS_1D),
      state.prop_key, state.prop_val, count2, ovf2)

    return DocState(
        **dict(zip(_FIELDS_1D, outs[:8])),
        prop_key=outs[8], prop_val=outs[9],
        count=outs[10].reshape(D),
        overflow=outs[11].reshape(D).astype(bool),
    )
