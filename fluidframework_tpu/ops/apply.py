"""Batched merge-tree delta-apply: the kernel the whole project exists for.

``apply_op`` applies ONE sequenced op to ONE document as pure array math —
masked prefix-sum position resolution at the op's (refSeq, client)
perspective, then a static-shape gather rebuild. ``vmap`` lifts it across
thousands of documents; ``lax.scan`` chains K ops per doc per dispatch.

Server-side invariants that make this simple (see ops/__init__ docstring):
ops arrive in sequence order, so every existing stamp is below the incoming
seq — the concurrent-insert tie-break ("higher seq leftward",
mergeTree.ts:2281 breakTie) reduces to inserting at the EARLIEST boundary,
and overlapping removes keep the earliest stamp automatically. Annotate
LWW-per-key (segmentPropertiesManager.ts) likewise reduces to in-order
overwrite of the per-slot property table.

Every op carries the msn deli stamped on its sequenced message (F_MSN), so
zamboni compaction can run fused after each wave with the exact per-doc
collaboration-window floor — no host-side msn bookkeeping.

Oracle parity is enforced by tests/test_kernel_vs_oracle.py on fuzzed op
streams (the TPU-build analog of PartialSequenceLengths.options.verify,
partialLengths.ts:63).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .doc_state import NO_KEY, NO_SEQ, DocState

NO_CLIENT = -1
NO_VAL = -1  # annotate value id meaning "delete this key"

# op vector layout (int32[OP_FIELDS])
OP_NOOP = 0
OP_INSERT = 1
OP_REMOVE = 2
OP_ANNOTATE = 3
(
    F_TYPE,
    F_POS,
    F_END,
    F_SEQ,
    F_REFSEQ,
    F_CLIENT,
    F_TLEN,
    F_TSTART,
    F_MSN,
    F_FLAGS,
    F_KEY,
    F_VAL,
) = range(12)
OP_FIELDS = 12


def make_op(
    type: int,
    pos: int = 0,
    end: int = 0,
    seq: int = 0,
    ref_seq: int = 0,
    client: int = 0,
    text_len: int = 0,
    text_start: int = 0,
    msn: int = 0,
    flags: int = 0,
    key: int = 0,
    val: int = 0,
) -> np.ndarray:
    v = np.zeros(OP_FIELDS, np.int32)
    v[F_TYPE], v[F_POS], v[F_END] = type, pos, end
    v[F_SEQ], v[F_REFSEQ], v[F_CLIENT] = seq, ref_seq, client
    v[F_TLEN], v[F_TSTART] = text_len, text_start
    v[F_MSN], v[F_FLAGS] = msn, flags
    v[F_KEY], v[F_VAL] = key, val
    return v


def _visibility(state: DocState, ref_seq, client, count=None):
    """Per-slot visibility at the op's perspective → (vis, vlen, cum).

    The branch-free twin of Segment.visible_in / Perspective (all stamps
    assigned on the server path). ``cum`` is the exclusive prefix sum of
    visible lengths — the masked-prefix-sum replacement for the reference's
    PartialSequenceLengths queries (partialLengths.ts:432).

    ``count`` overrides ``state.count`` for callers whose slot arrays are a
    shard of a larger doc (parallel/long_doc.py passes the local count).
    """
    if count is None:
        count = state.count
    idx = jnp.arange(state.length.shape[-1], dtype=jnp.int32)
    in_use = idx < count
    ins_seen = (state.ins_client == client) | (state.ins_seq <= ref_seq)
    removed = (state.rem_seq != NO_SEQ) & (
        (state.rem_client_a == client)
        | (state.rem_client_b == client)
        | (state.rem_seq <= ref_seq)
    )
    vis = in_use & ins_seen & ~removed
    vlen = jnp.where(vis, state.length, 0)
    cum = jnp.cumsum(vlen) - vlen
    return vis, vlen, cum


_SLOT_FIELDS = (
    "length",
    "text_start",
    "flags",
    "ins_seq",
    "ins_client",
    "rem_seq",
    "rem_client_a",
    "rem_client_b",
    "prop_key",
    "prop_val",
)


def _gather(state: DocState, src, **overrides) -> dict:
    """Gather every per-slot field along the slot axis (2-D prop tables
    gather whole rows)."""
    fields = {}
    for name in _SLOT_FIELDS:
        fields[name] = getattr(state, name)[src]
    fields.update(overrides)
    return fields


def _apply_insert(state: DocState, op) -> DocState:
    S = state.max_slots
    pos, seq, ref_seq = op[F_POS], op[F_SEQ], op[F_REFSEQ]
    client, tlen, tstart = op[F_CLIENT], op[F_TLEN], op[F_TSTART]
    vis, vlen, cum = _visibility(state, ref_seq, client)
    total = jnp.sum(vlen)
    inc = cum + vlen

    inside = vis & (cum < pos) & (pos < inc)
    split = jnp.any(inside)
    j = jnp.argmax(inside)  # containing slot when split
    o = pos - cum[j]  # split offset
    # earliest boundary: first slot whose exclusive prefix reaches pos —
    # lands BEFORE any run of zero-visible slots (tombstones / concurrent
    # inserts), matching MergeTree.resolve
    b = jnp.argmax(cum >= pos)
    idx = jnp.where(split, j + 1, b)

    i = jnp.arange(S, dtype=jnp.int32)
    src_boundary = i - (i > idx)
    src_split = jnp.where(i <= j, i, jnp.where(i <= idx + 1, j, i - 2))
    src = jnp.clip(jnp.where(split, src_split, src_boundary), 0, S - 1)

    f = _gather(state, src)
    head = split & (i == j)
    tail = split & (i == idx + 1)
    new = i == idx
    new2 = new[:, None]  # broadcast over the prop-table axis
    length = jnp.where(head, o, f["length"])
    length = jnp.where(tail, state.length[j] - o, length)
    length = jnp.where(new, jnp.where(tlen > 0, tlen, 1), length)
    text_start = jnp.where(tail, state.text_start[j] + o, f["text_start"])
    text_start = jnp.where(new, tstart, text_start)

    new_count = state.count + 1 + split.astype(jnp.int32)
    bad = (pos > total) | (new_count > S)
    out = DocState(
        length=length,
        text_start=text_start,
        flags=jnp.where(new, op[F_FLAGS], f["flags"]),
        ins_seq=jnp.where(new, seq, f["ins_seq"]),
        ins_client=jnp.where(new, client, f["ins_client"]),
        rem_seq=jnp.where(new, NO_SEQ, f["rem_seq"]),
        rem_client_a=jnp.where(new, NO_CLIENT, f["rem_client_a"]),
        rem_client_b=jnp.where(new, NO_CLIENT, f["rem_client_b"]),
        prop_key=jnp.where(new2, NO_KEY, f["prop_key"]),
        prop_val=jnp.where(new2, 0, f["prop_val"]),
        count=new_count,
        overflow=state.overflow | bad,
    )
    return _select_state(bad, state, out)


def _split_at(state: DocState, pos, ref_seq, client) -> DocState:
    """Split the segment strictly containing visible position ``pos``
    (no-op when pos falls on a boundary). Both halves keep identical
    stamps, flags, and properties (ref: BaseSegment.splitAt)."""
    S = state.max_slots
    vis, vlen, cum = _visibility(state, ref_seq, client)
    inside = vis & (cum < pos) & (pos < cum + vlen)
    has = jnp.any(inside)
    j = jnp.argmax(inside)
    o = pos - cum[j]

    i = jnp.arange(S, dtype=jnp.int32)
    src = jnp.clip(jnp.where(i <= j, i, jnp.where(i == j + 1, j, i - 1)), 0, S - 1)
    f = _gather(state, src)
    head = i == j
    tail = i == (j + 1)
    length = jnp.where(head, o, f["length"])
    length = jnp.where(tail, state.length[j] - o, length)
    text_start = jnp.where(tail, state.text_start[j] + o, f["text_start"])
    out = DocState(
        length=length,
        text_start=text_start,
        flags=f["flags"],
        ins_seq=f["ins_seq"],
        ins_client=f["ins_client"],
        rem_seq=f["rem_seq"],
        rem_client_a=f["rem_client_a"],
        rem_client_b=f["rem_client_b"],
        prop_key=f["prop_key"],
        prop_val=f["prop_val"],
        count=state.count + 1,
        overflow=state.overflow | (has & (state.count + 1 > S)),
    )
    return _select_state(~has, state, out)


def _apply_remove(state: DocState, op) -> DocState:
    start, end = op[F_POS], op[F_END]
    seq, ref_seq, client = op[F_SEQ], op[F_REFSEQ], op[F_CLIENT]

    _, vlen0, _ = _visibility(state, ref_seq, client)
    bad = (end > jnp.sum(vlen0)) | (end <= start) | (state.count + 2 > state.max_slots)

    st = _split_at(state, start, ref_seq, client)
    st = _split_at(st, end, ref_seq, client)

    vis, vlen, cum = _visibility(st, ref_seq, client)
    mask = vis & (cum >= start) & (cum + vlen <= end)
    fresh = mask & (st.rem_seq == NO_SEQ)
    # overlap: ops apply in seq order so the existing stamp is the earliest;
    # just record this client as an additional remover
    over = mask & (st.rem_seq != NO_SEQ)
    add_b = over & (st.rem_client_a != client) & (st.rem_client_b == NO_CLIENT)
    third = over & (st.rem_client_a != client) & (st.rem_client_b != client) & (
        st.rem_client_b != NO_CLIENT
    )
    out = DocState(
        length=st.length,
        text_start=st.text_start,
        flags=st.flags,
        ins_seq=st.ins_seq,
        ins_client=st.ins_client,
        rem_seq=jnp.where(fresh, seq, st.rem_seq),
        rem_client_a=jnp.where(fresh, client, st.rem_client_a),
        rem_client_b=jnp.where(add_b, client, st.rem_client_b),
        prop_key=st.prop_key,
        prop_val=st.prop_val,
        count=st.count,
        overflow=st.overflow | jnp.any(third) | bad,
    )
    return _select_state(bad, state, out)


def _apply_annotate(state: DocState, op) -> DocState:
    """Set ONE property (key, value) on visible span [start, end) — the
    tensorized annotateRange (mergeTree.ts:2598). Multi-key annotates are
    staged as one op per key. ``val == NO_VAL`` deletes the key (frees its
    table slot). In-order apply makes per-key LWW automatic."""
    start, end = op[F_POS], op[F_END]
    ref_seq, client = op[F_REFSEQ], op[F_CLIENT]
    key, val = op[F_KEY], op[F_VAL]
    P = state.max_props

    _, vlen0, _ = _visibility(state, ref_seq, client)
    bad = (end > jnp.sum(vlen0)) | (end <= start) | (state.count + 2 > state.max_slots)

    st = _split_at(state, start, ref_seq, client)
    st = _split_at(st, end, ref_seq, client)

    vis, vlen, cum = _visibility(st, ref_seq, client)
    covered = vis & (cum >= start) & (cum + vlen <= end)

    match = st.prop_key == key  # [S, P]
    has_key = jnp.any(match, axis=-1)
    empty = st.prop_key == NO_KEY
    has_empty = jnp.any(empty, axis=-1)
    tgt = jnp.where(has_key, jnp.argmax(match, axis=-1), jnp.argmax(empty, axis=-1))

    is_delete = val == NO_VAL
    do_write = covered & (has_key | (~is_delete & has_empty))
    onehot = (jnp.arange(P, dtype=jnp.int32)[None, :] == tgt[:, None]) & do_write[
        :, None
    ]
    prop_key = jnp.where(onehot, jnp.where(is_delete, NO_KEY, key), st.prop_key)
    prop_val = jnp.where(onehot, jnp.where(is_delete, 0, val), st.prop_val)
    # a slot that needs a (P+1)th distinct key cannot hold it → escalate
    table_full = jnp.any(covered & ~has_key & ~has_empty & ~is_delete)

    out = DocState(
        length=st.length,
        text_start=st.text_start,
        flags=st.flags,
        ins_seq=st.ins_seq,
        ins_client=st.ins_client,
        rem_seq=st.rem_seq,
        rem_client_a=st.rem_client_a,
        rem_client_b=st.rem_client_b,
        prop_key=prop_key,
        prop_val=prop_val,
        count=st.count,
        overflow=st.overflow | table_full | bad,
    )
    return _select_state(bad, state, out)


def _select_state(pred, a: DocState, b: DocState) -> DocState:
    """pred ? a : b, fieldwise (keeping overflow flags from b)."""
    take = lambda x, y: jnp.where(pred, x, y)
    return DocState(
        length=take(a.length, b.length),
        text_start=take(a.text_start, b.text_start),
        flags=take(a.flags, b.flags),
        ins_seq=take(a.ins_seq, b.ins_seq),
        ins_client=take(a.ins_client, b.ins_client),
        rem_seq=take(a.rem_seq, b.rem_seq),
        rem_client_a=take(a.rem_client_a, b.rem_client_a),
        rem_client_b=take(a.rem_client_b, b.rem_client_b),
        prop_key=take(a.prop_key, b.prop_key),
        prop_val=take(a.prop_val, b.prop_val),
        count=take(a.count, b.count),
        overflow=b.overflow,  # sticky: set by whichever path ran
    )


def apply_op(state: DocState, op) -> DocState:
    """Apply one sequenced op vector (int32[OP_FIELDS]) to one doc."""
    return lax.switch(
        jnp.clip(op[F_TYPE], 0, 3),
        [lambda s, o: s, _apply_insert, _apply_remove, _apply_annotate],
        state,
        op,
    )


# [D docs] × one op each
apply_op_batch = jax.vmap(apply_op)


def apply_ops_scan(state: DocState, ops) -> DocState:
    """Apply K sequenced ops (int32[K, OP_FIELDS]) to one doc, in order."""

    def step(s, op):
        return apply_op(s, op), None

    out, _ = lax.scan(step, state, ops)
    return out


# [D docs] × [K ops each]: the batched hot loop
apply_ops_batch = jax.vmap(apply_ops_scan)


def wave_min_seq(ops) -> jax.Array:
    """Per-doc zamboni floor for a [D, K, OP_FIELDS] wave: the msn of the
    LAST real op applied to each doc. msn is monotone per doc and NOOP
    padding carries msn 0, so this is simply the max over the wave. Using
    the wave's own msn (not a later one) is what keeps compaction safe
    while later-sequenced ops are still staged on the host: deli
    guarantees every future op's refSeq ≥ the msn it stamped HERE, not
    the msn it stamped afterwards."""
    return jnp.max(ops[..., F_MSN], axis=-1)


def compact(state: DocState, min_seq) -> DocState:
    """Zamboni, device-side: drop slots whose remove seq ≤ minSeq (no future
    perspective can see them; ref mergeTree.ts:1455) and re-pack in order."""
    S = state.max_slots
    i = jnp.arange(S, dtype=jnp.int32)
    in_use = i < state.count
    drop = in_use & (state.rem_seq != NO_SEQ) & (state.rem_seq <= min_seq)
    keep = in_use & ~drop
    order = jnp.argsort(jnp.where(keep, i, S + i))  # kept first, stable
    new_count = jnp.sum(keep.astype(jnp.int32))
    live = jnp.arange(S, dtype=jnp.int32) < new_count

    def g(a, fill):
        gathered = a[order]
        mask = live if a.ndim == 1 else live[:, None]
        return jnp.where(mask, gathered, fill)

    return DocState(
        length=g(state.length, 0),
        text_start=g(state.text_start, 0),
        flags=g(state.flags, 0),
        ins_seq=g(state.ins_seq, 0),
        ins_client=g(state.ins_client, NO_CLIENT),
        rem_seq=g(state.rem_seq, NO_SEQ),
        rem_client_a=g(state.rem_client_a, NO_CLIENT),
        rem_client_b=g(state.rem_client_b, NO_CLIENT),
        prop_key=g(state.prop_key, NO_KEY),
        prop_val=g(state.prop_val, 0),
        count=new_count,
        overflow=state.overflow,
    )


compact_batch = jax.vmap(compact)
