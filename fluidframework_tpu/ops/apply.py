"""Batched merge-tree delta-apply: the kernel the whole project exists for.

``apply_op`` applies ONE sequenced op to ONE document as pure array math —
masked prefix-sum position resolution at the op's (refSeq, client)
perspective, then a static-shape gather rebuild. ``vmap`` lifts it across
thousands of documents; ``lax.scan`` chains K ops per doc per dispatch.

Server-side invariants that make this simple (see ops/__init__ docstring):
ops arrive in sequence order, so every existing stamp is below the incoming
seq — the concurrent-insert tie-break ("higher seq leftward",
mergeTree.ts:2281 breakTie) reduces to inserting at the EARLIEST boundary,
and overlapping removes keep the earliest stamp automatically. Annotate
LWW-per-key (segmentPropertiesManager.ts) likewise reduces to in-order
overwrite of the per-slot property table.

Every op carries the msn deli stamped on its sequenced message (F_MSN), so
zamboni compaction can run fused after each wave with the exact per-doc
collaboration-window floor — no host-side msn bookkeeping.

Oracle parity is enforced by tests/test_kernel_vs_oracle.py on fuzzed op
streams (the TPU-build analog of PartialSequenceLengths.options.verify,
partialLengths.ts:63).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.contracts import kernel_contract
from .doc_state import NO_KEY, NO_SEQ, DocState

NO_CLIENT = -1
NO_VAL = -1  # annotate value id meaning "delete this key"

# op vector layout (int32[OP_FIELDS])
OP_NOOP = 0
OP_INSERT = 1
OP_REMOVE = 2
OP_ANNOTATE = 3
(
    F_TYPE,
    F_POS,
    F_END,
    F_SEQ,
    F_REFSEQ,
    F_CLIENT,
    F_TLEN,
    F_TSTART,
    F_MSN,
    F_FLAGS,
    F_KEY,
    F_VAL,
) = range(12)
OP_FIELDS = 12


def make_op(
    type: int,
    pos: int = 0,
    end: int = 0,
    seq: int = 0,
    ref_seq: int = 0,
    client: int = 0,
    text_len: int = 0,
    text_start: int = 0,
    msn: int = 0,
    flags: int = 0,
    key: int = 0,
    val: int = 0,
) -> np.ndarray:
    v = np.zeros(OP_FIELDS, np.int32)
    v[F_TYPE], v[F_POS], v[F_END] = type, pos, end
    v[F_SEQ], v[F_REFSEQ], v[F_CLIENT] = seq, ref_seq, client
    v[F_TLEN], v[F_TSTART] = text_len, text_start
    v[F_MSN], v[F_FLAGS] = msn, flags
    v[F_KEY], v[F_VAL] = key, val
    return v


def _visibility(state: DocState, ref_seq, client, count=None):
    """Per-slot visibility at the op's perspective → (vis, vlen, cum).

    The branch-free twin of Segment.visible_in / Perspective (all stamps
    assigned on the server path). ``cum`` is the exclusive prefix sum of
    visible lengths — the masked-prefix-sum replacement for the reference's
    PartialSequenceLengths queries (partialLengths.ts:432).

    ``count`` overrides ``state.count`` for callers whose slot arrays are a
    shard of a larger doc (parallel/long_doc.py passes the local count).
    """
    if count is None:
        count = state.count
    idx = jnp.arange(state.length.shape[-1], dtype=jnp.int32)
    in_use = idx < count
    ins_seen = (state.ins_client == client) | (state.ins_seq <= ref_seq)
    removed = (state.rem_seq != NO_SEQ) & (
        (state.rem_client_a == client)
        | (state.rem_client_b == client)
        | (state.rem_seq <= ref_seq)
    )
    vis = in_use & ins_seen & ~removed
    vlen = jnp.where(vis, state.length, 0)
    cum = jnp.cumsum(vlen) - vlen
    return vis, vlen, cum


_SLOT_FIELDS = (
    "length",
    "text_start",
    "flags",
    "ins_seq",
    "ins_client",
    "rem_seq",
    "rem_client_a",
    "rem_client_b",
    "prop_key",
    "prop_val",
)


def _shift1(a):
    """out[i] = a[i-1] (out[0] is never selected by callers)."""
    return jnp.roll(a, 1, axis=0)


def _shift2(a):
    """out[i] = a[i-2] (out[0..1] are never selected by callers)."""
    return jnp.roll(a, 2, axis=0)


def _fieldwise(state: DocState, fn, count, overflow) -> DocState:
    return DocState(
        **{name: fn(name, getattr(state, name)) for name in _SLOT_FIELDS},
        count=count,
        overflow=overflow,
    )


def _apply_unified(state: DocState, op) -> DocState:
    """One shared path for insert/remove/annotate (noop passes through),
    FUSED: a single visibility/prefix-sum pass and a single roll+select
    rebuild cover both potential splits and the insert shift.

    An op creates at most two new slots — the tail halves of up to two
    splits, or one tail half plus the inserted segment — so every output
    slot is one of {a[o], a[o-1], a[o-2]} (gather-free: static rolls and
    selects vectorize onto the VPU; computed-index gathers are the TPU
    slow path), plus point patches at the split/insert indices. The
    sequential form (split(p1) → split(p2) → insert-shift, each with its
    own visibility recompute) cost 4 prefix-sum passes and 3 full-state
    rebuilds per op; fused it is 1 and 1, which is what sets the K-step
    scan's per-op device cost.

    Semantics (unchanged, fuzz-checked against the scalar oracle):
    - insert lands at the EARLIEST boundary reaching pos (before
      tombstone runs, matching MergeTree.resolve + breakTie);
    - remove mask-stamps covered slots (overlap keeps the earliest
      stamp, later removers recorded as extra remove clients);
    - annotate is LWW per key into per-slot property tables.
    """
    vis, vlen, cum = _visibility(state, op[F_REFSEQ], op[F_CLIENT])
    return _apply_core(state, op, vis, vlen, cum, jnp.sum(vlen))


def _apply_core(
    state: DocState,
    op,
    vis,
    vlen,
    cum,
    total,
    insert_here=True,
    reduce_any=None,
):
    """The unified apply body over PRECOMPUTED visibility.

    Single-doc callers pass locally-computed (vis, vlen, cum, total).
    The segment-sharded giant-doc path (parallel/long_doc.py) passes a
    GLOBAL prefix (local cum + shard offset, global total), masks the
    insert to the boundary-owning shard via ``insert_here``, and supplies
    ``reduce_any`` (a pmax over the 'seg' axis) so a capacity/shape
    problem on ANY shard aborts the op on EVERY shard — the op either
    applies everywhere or flags overflow everywhere.
    """
    if reduce_any is None:
        def reduce_any(x):
            return x
    S = state.max_slots
    typ = op[F_TYPE]
    is_ins = typ == OP_INSERT
    is_rem = typ == OP_REMOVE
    is_ann = typ == OP_ANNOTATE
    active = is_ins | is_rem | is_ann
    pos, end = op[F_POS], op[F_END]
    seq, client = op[F_SEQ], op[F_CLIENT]
    p2 = jnp.where(is_ins, pos, end)

    bad_shape = jnp.where(is_ins, pos > total, (end > total) | (end <= pos))
    inc = cum + vlen

    # split demand: a split happens iff the position falls STRICTLY
    # inside a visible segment (exact on the pre-split state: adding the
    # p1 boundary cannot move p2 strictly into/out of a segment)
    inside1 = vis & (cum < pos) & (pos < inc)
    inside2 = vis & (cum < p2) & (p2 < inc)
    s1_raw = jnp.any(inside1)
    s2_raw = (~is_ins) & jnp.any(inside2)
    needed = (
        s1_raw.astype(jnp.int32)
        + s2_raw.astype(jnp.int32)
        + (is_ins & insert_here).astype(jnp.int32)
    )
    bad = active & reduce_any(bad_shape | (state.count + needed > S))
    ok = active & ~bad
    insert_ok = ok & insert_here
    s1 = s1_raw & ok
    s2 = s2_raw & ok
    do_ins = is_ins & insert_ok

    j1 = jnp.argmax(inside1)
    j2 = jnp.argmax(inside2)
    # split-segment field extracts as one-hot masked sums, NOT a[j]:
    # inside1/inside2 are one-hot (positions strictly inside a visible
    # segment match at most one slot), and a[j] with a batched j lowers
    # to lax.gather under vmap — the computed-index path the kernel
    # contract forbids (tools/fluidlint jaxpr pass, no_gather)
    c1 = jnp.sum(jnp.where(inside1, cum, 0))
    c2 = jnp.sum(jnp.where(inside2, cum, 0))
    o1 = pos - c1
    o2 = p2 - c2
    l1 = jnp.sum(jnp.where(inside1, state.length, 0))
    ts1 = jnp.sum(jnp.where(inside1, state.text_start, 0))
    l2 = jnp.sum(jnp.where(inside2, state.length, 0))
    ts2 = jnp.sum(jnp.where(inside2, state.text_start, 0))
    same = s1 & s2 & (j1 == j2)  # both splits inside one segment

    # output indices of the new/patched slots
    s1i = s1.astype(jnp.int32)
    idx0 = jnp.argmax(cum >= pos)  # earliest boundary (unused slots keep
    # cum == total, so append-at-end resolves to the first free slot)
    p_ins = jnp.where(s1, j1 + 1, idx0)  # new insert slot
    p_n1 = jnp.where(do_ins, p_ins + 1, j1 + 1)  # tail half of split 1
    p_h2 = j2 + s1i  # original j2 (head half of split 2), shifted past n1
    p_n2 = j2 + 1 + s1i  # tail half of split 2

    i = jnp.arange(S, dtype=jnp.int32)
    # shift = how many new slots sit at/before each output index
    delta = (
        (s1 & (i >= p_n1)).astype(jnp.int32)
        + (s2 & (i >= p_n2)).astype(jnp.int32)
        + (do_ins & (i >= p_ins)).astype(jnp.int32)
    )
    d1 = delta == 1
    d2 = delta == 2
    head1_at = s1 & (i == j1)
    n1_at = s1 & (i == p_n1)
    h2_at = s2 & ~same & (i == p_h2)
    n2_at = s2 & (i == p_n2)
    new_at = do_ins & (i == p_ins)

    tlen, tstart = op[F_TLEN], op[F_TSTART]
    new_vals = {
        "length": jnp.where(tlen > 0, tlen, 1),
        "text_start": tstart,
        "flags": op[F_FLAGS],
        "ins_seq": seq,
        "ins_client": client,
        "rem_seq": NO_SEQ,
        "rem_client_a": NO_CLIENT,
        "rem_client_b": NO_CLIENT,
    }
    # length/text_start patches for the four split-derived slots
    n1_len = jnp.where(same, o2 - o1, l1 - o1)
    patch_len = [(head1_at, o1), (n1_at, n1_len), (h2_at, o2),
                 (n2_at, l2 - o2)]
    patch_ts = [(n1_at, ts1 + o1), (n2_at, ts2 + o2)]

    def rebuild(name, a):
        if a.ndim == 2:  # prop tables: roll rows, new insert slot empty
            fill = NO_KEY if name == "prop_key" else 0
            out = jnp.where(d1[:, None], _shift1(a),
                            jnp.where(d2[:, None], _shift2(a), a))
            return jnp.where(new_at[:, None], fill, out)
        out = jnp.where(d1, _shift1(a), jnp.where(d2, _shift2(a), a))
        if name == "length":
            for mask, val in patch_len:
                out = jnp.where(mask, val, out)
        elif name == "text_start":
            for mask, val in patch_ts:
                out = jnp.where(mask, val, out)
        return jnp.where(new_at, new_vals[name], out) if name in new_vals \
            else out

    st = _fieldwise(
        state,
        rebuild,
        count=state.count + s1i + s2.astype(jnp.int32)
        + do_ins.astype(jnp.int32),
        overflow=state.overflow,
    )

    # ---- remove/annotate target mask, on ROLLED perspective arrays (no
    # second prefix pass). The insert slot never matters here: do_ins
    # excludes is_rem/is_ann, so the mask is dead in that case.
    vis_out = jnp.where(d1, _shift1(vis), jnp.where(d2, _shift2(vis), vis))
    cum_out = jnp.where(d1, _shift1(cum), jnp.where(d2, _shift2(cum), cum))
    cum_out = jnp.where(n1_at, c1 + o1, cum_out)
    cum_out = jnp.where(n2_at, c2 + o2, cum_out)
    vlen_out = jnp.where(vis_out, st.length, 0)
    covered = vis_out & (cum_out >= pos) & (cum_out + vlen_out <= end)
    rm = is_rem & ~bad & covered
    fresh = rm & (st.rem_seq == NO_SEQ)
    # overlap: ops apply in seq order so the existing stamp is the
    # earliest; just record this client as an additional remover
    over = rm & (st.rem_seq != NO_SEQ)
    add_b = over & (st.rem_client_a != client) & (st.rem_client_b == NO_CLIENT)
    third = over & (st.rem_client_a != client) & (st.rem_client_b != client) & (
        st.rem_client_b != NO_CLIENT
    )

    # ---- annotate: per-key LWW write (val == NO_VAL deletes the key)
    key, val = op[F_KEY], op[F_VAL]
    P = state.max_props
    an = is_ann & ~bad & covered
    match = st.prop_key == key  # [S, P]
    has_key = jnp.any(match, axis=-1)
    empty = st.prop_key == NO_KEY
    has_empty = jnp.any(empty, axis=-1)
    tgt = jnp.where(has_key, jnp.argmax(match, axis=-1), jnp.argmax(empty, axis=-1))
    is_delete = val == NO_VAL
    do_write = an & (has_key | (~is_delete & has_empty))
    onehot = (jnp.arange(P, dtype=jnp.int32)[None, :] == tgt[:, None]) & do_write[
        :, None
    ]
    # a slot that needs a (P+1)th distinct key cannot hold it → escalate
    table_full = jnp.any(an & ~has_key & ~has_empty & ~is_delete)

    return DocState(
        length=st.length,
        text_start=st.text_start,
        flags=st.flags,
        ins_seq=st.ins_seq,
        ins_client=st.ins_client,
        rem_seq=jnp.where(fresh, seq, st.rem_seq),
        rem_client_a=jnp.where(fresh, client, st.rem_client_a),
        rem_client_b=jnp.where(add_b, client, st.rem_client_b),
        prop_key=jnp.where(onehot, jnp.where(is_delete, NO_KEY, key), st.prop_key),
        prop_val=jnp.where(onehot, jnp.where(is_delete, 0, val), st.prop_val),
        count=st.count,
        overflow=st.overflow | jnp.any(third) | table_full | bad,
    )


def apply_op(state: DocState, op) -> DocState:
    """Apply one sequenced op vector (int32[OP_FIELDS]) to one doc."""
    return _apply_unified(state, op)


# [D docs] × one op each
apply_op_batch = jax.vmap(apply_op)


def apply_ops_scan(state: DocState, ops) -> DocState:
    """Apply K sequenced ops (int32[K, OP_FIELDS]) to one doc, in order."""

    def step(s, op):
        return apply_op(s, op), None

    out, _ = lax.scan(step, state, ops)
    return out


def _contract_example():
    """Small representative wave: [D=8 docs, K=4 ops, S=16 slots]."""
    D, S, K = 8, 16, 4
    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    ops = jnp.zeros((D, K, OP_FIELDS), jnp.int32)
    return (state, ops), {}


# [D docs] × [K ops each]: the batched hot loop. The contract IS the
# ARCHITECTURE.md claim: the K-amplified apply is strictly rolls +
# selects — zero computed-index gathers/scatters, zero dynamic slices,
# one compile per wave shape (enforced by tools/fluidlint).
apply_ops_batch = kernel_contract(
    "ops.apply_ops_batch",
    example=_contract_example,
    no_gather=True,
    no_scatter=True,
    max_dynamic_slices=0,
    single_jit=True,
    notes="batched merge-tree apply: the K-amplified hot path",
)(jax.vmap(apply_ops_scan))


def wave_min_seq(ops) -> jax.Array:
    """Per-doc zamboni floor for a [D, K, OP_FIELDS] wave: the msn of the
    LAST real op applied to each doc. msn is monotone per doc and NOOP
    padding carries msn 0, so this is simply the max over the wave. Using
    the wave's own msn (not a later one) is what keeps compaction safe
    while later-sequenced ops are still staged on the host: deli
    guarantees every future op's refSeq ≥ the msn it stamped HERE, not
    the msn it stamped afterwards."""
    return jnp.max(ops[..., F_MSN], axis=-1)


# ------------------------------------------------------ packed wave format
#
# The host↔device link is the op path's bottleneck (measured ~6.5 MB/s
# over a tunneled device vs 71 ms for the apply itself), so the dense
# [D, K] wave ships as int16 DELTAS plus int32 per-doc bases and is
# widened back to the int32 field layout on device. The format lives
# here so both dense lanes — the single-device step
# (service/tpu_applier._dense_step_for) and the doc-sharded mesh step
# (parallel/sharded_apply.make_sharded_packed_step) — encode and decode
# the exact same wire layout. Deltas keep every field in int16 range:
# seq/text_start are per-doc monotone (delta from the wave's first row),
# ref/msn trail seq by at most the collaboration window; the host checks
# the ranges and falls back to the int32 wave when any field escapes.

#: interned id for server/system-originated stamps (never collides with
#: the dense per-doc client table, which grows upward from 0)
SYSTEM_CLIENT = (1 << 30) - 1

#: int16 packed-wave sentinel standing in for SYSTEM_CLIENT on the wire
PACK_SYSTEM = 32767


def unpack_wave16(wave16, bases):
    """Widen a packed int16 [D, K, F] delta wave plus its int32 [D, 2]
    (seq_base, text_base) to the kernel's int32 field layout, on device.

    Gather-free by construction: ``bases[:, :1]`` is a pure slice (a
    None-mixed static index would lower to lax.gather, and the kernel
    contracts budget gathers to compaction only). NOOP padding must not
    lift the per-doc zamboni floor (wave_min_seq is a max), so its msn
    is parked far below any real one."""
    w = wave16.astype(jnp.int32)
    typ = w[..., F_TYPE]
    seq = bases[:, :1] + w[..., F_SEQ]
    ref = seq - w[..., F_REFSEQ]
    msn = jnp.where(typ == OP_NOOP, -(1 << 20), seq - w[..., F_MSN])
    client = w[..., F_CLIENT]
    client = jnp.where(client == PACK_SYSTEM, SYSTEM_CLIENT, client)
    tstart = bases[:, 1:] + w[..., F_TSTART]
    return jnp.stack(
        [typ, w[..., F_POS], w[..., F_END], seq, ref, client,
         w[..., F_TLEN], tstart, msn, w[..., F_FLAGS],
         w[..., F_KEY], w[..., F_VAL]], axis=-1)


def pack_wave_rows(flat, starts, lens_a):
    """Host-side twin of ``unpack_wave16`` over concatenated staged rows.

    ``flat`` is int32 [n, OP_FIELDS] (all docs' rows back to back),
    ``starts``/``lens_a`` delimit each doc's run. Returns
    ``(packed int64 [n, F], seq_base [m], text_base [m])``; the caller
    checks the int16 range and scatters ``packed`` into its wave
    buffers. Bases: seq of the doc's first row; min text_start over its
    insert rows (text_start of non-inserts is unused — packed 0)."""
    seq_base = flat[starts, F_SEQ]
    is_ins = flat[:, F_TYPE] == OP_INSERT
    tstart_or_inf = np.where(is_ins, flat[:, F_TSTART], np.int64(2 ** 62))
    text_base = np.minimum.reduceat(tstart_or_inf, starts)
    text_base = np.where(text_base == 2 ** 62, 0, text_base).astype(np.int64)

    n = len(flat)
    seq = flat[:, F_SEQ].astype(np.int64)
    seq_base_row = np.repeat(seq_base.astype(np.int64), lens_a)
    text_base_row = np.repeat(text_base, lens_a)
    packed = np.empty((n, OP_FIELDS), np.int64)
    packed[:, F_TYPE] = flat[:, F_TYPE]
    packed[:, F_POS] = flat[:, F_POS]
    packed[:, F_END] = flat[:, F_END]
    packed[:, F_SEQ] = seq - seq_base_row
    packed[:, F_REFSEQ] = seq - flat[:, F_REFSEQ]
    client = flat[:, F_CLIENT]
    # a REAL interned id of 32767 would collide with the sentinel and be
    # silently re-attributed to the system client on unpack: force it
    # (vanishingly rare: 32768 distinct clients in one doc) onto the
    # wide path via an out-of-range value
    packed[:, F_CLIENT] = np.where(
        client == SYSTEM_CLIENT, PACK_SYSTEM,
        np.where(client == PACK_SYSTEM, np.int64(1) << 40, client))
    packed[:, F_TLEN] = flat[:, F_TLEN]
    packed[:, F_TSTART] = np.where(
        is_ins, flat[:, F_TSTART] - text_base_row, 0)
    packed[:, F_MSN] = seq - flat[:, F_MSN]
    packed[:, F_FLAGS] = flat[:, F_FLAGS]
    packed[:, F_KEY] = flat[:, F_KEY]
    packed[:, F_VAL] = flat[:, F_VAL]
    return packed, seq_base, text_base


def compact(state: DocState, min_seq) -> DocState:
    """Zamboni, device-side: drop slots whose remove seq ≤ minSeq (no future
    perspective can see them; ref mergeTree.ts:1455) and re-pack in order."""
    S = state.max_slots
    i = jnp.arange(S, dtype=jnp.int32)
    in_use = i < state.count
    drop = in_use & (state.rem_seq != NO_SEQ) & (state.rem_seq <= min_seq)
    keep = in_use & ~drop
    order = jnp.argsort(jnp.where(keep, i, S + i))  # kept first, stable
    new_count = jnp.sum(keep.astype(jnp.int32))
    live = jnp.arange(S, dtype=jnp.int32) < new_count

    def g(a, fill):
        gathered = a[order]
        mask = live if a.ndim == 1 else live[:, None]
        return jnp.where(mask, gathered, fill)

    return DocState(
        length=g(state.length, 0),
        text_start=g(state.text_start, 0),
        flags=g(state.flags, 0),
        ins_seq=g(state.ins_seq, 0),
        ins_client=g(state.ins_client, NO_CLIENT),
        rem_seq=g(state.rem_seq, NO_SEQ),
        rem_client_a=g(state.rem_client_a, NO_CLIENT),
        rem_client_b=g(state.rem_client_b, NO_CLIENT),
        prop_key=g(state.prop_key, NO_KEY),
        prop_val=g(state.prop_val, 0),
        count=new_count,
        overflow=state.overflow,
    )


compact_batch = jax.vmap(compact)
