"""Batched merge-tree delta-apply: the kernel the whole project exists for.

``apply_op`` applies ONE sequenced op to ONE document as pure array math —
masked prefix-sum position resolution at the op's (refSeq, client)
perspective, then a static-shape gather rebuild. ``vmap`` lifts it across
thousands of documents; ``lax.scan`` chains K ops per doc per dispatch.

Server-side invariants that make this simple (see ops/__init__ docstring):
ops arrive in sequence order, so every existing stamp is below the incoming
seq — the concurrent-insert tie-break ("higher seq leftward",
mergeTree.ts:2281 breakTie) reduces to inserting at the EARLIEST boundary,
and overlapping removes keep the earliest stamp automatically. Annotate
LWW-per-key (segmentPropertiesManager.ts) likewise reduces to in-order
overwrite of the per-slot property table.

Every op carries the msn deli stamped on its sequenced message (F_MSN), so
zamboni compaction can run fused after each wave with the exact per-doc
collaboration-window floor — no host-side msn bookkeeping.

Oracle parity is enforced by tests/test_kernel_vs_oracle.py on fuzzed op
streams (the TPU-build analog of PartialSequenceLengths.options.verify,
partialLengths.ts:63).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .doc_state import NO_KEY, NO_SEQ, DocState

NO_CLIENT = -1
NO_VAL = -1  # annotate value id meaning "delete this key"

# op vector layout (int32[OP_FIELDS])
OP_NOOP = 0
OP_INSERT = 1
OP_REMOVE = 2
OP_ANNOTATE = 3
(
    F_TYPE,
    F_POS,
    F_END,
    F_SEQ,
    F_REFSEQ,
    F_CLIENT,
    F_TLEN,
    F_TSTART,
    F_MSN,
    F_FLAGS,
    F_KEY,
    F_VAL,
) = range(12)
OP_FIELDS = 12


def make_op(
    type: int,
    pos: int = 0,
    end: int = 0,
    seq: int = 0,
    ref_seq: int = 0,
    client: int = 0,
    text_len: int = 0,
    text_start: int = 0,
    msn: int = 0,
    flags: int = 0,
    key: int = 0,
    val: int = 0,
) -> np.ndarray:
    v = np.zeros(OP_FIELDS, np.int32)
    v[F_TYPE], v[F_POS], v[F_END] = type, pos, end
    v[F_SEQ], v[F_REFSEQ], v[F_CLIENT] = seq, ref_seq, client
    v[F_TLEN], v[F_TSTART] = text_len, text_start
    v[F_MSN], v[F_FLAGS] = msn, flags
    v[F_KEY], v[F_VAL] = key, val
    return v


def _visibility(state: DocState, ref_seq, client, count=None):
    """Per-slot visibility at the op's perspective → (vis, vlen, cum).

    The branch-free twin of Segment.visible_in / Perspective (all stamps
    assigned on the server path). ``cum`` is the exclusive prefix sum of
    visible lengths — the masked-prefix-sum replacement for the reference's
    PartialSequenceLengths queries (partialLengths.ts:432).

    ``count`` overrides ``state.count`` for callers whose slot arrays are a
    shard of a larger doc (parallel/long_doc.py passes the local count).
    """
    if count is None:
        count = state.count
    idx = jnp.arange(state.length.shape[-1], dtype=jnp.int32)
    in_use = idx < count
    ins_seen = (state.ins_client == client) | (state.ins_seq <= ref_seq)
    removed = (state.rem_seq != NO_SEQ) & (
        (state.rem_client_a == client)
        | (state.rem_client_b == client)
        | (state.rem_seq <= ref_seq)
    )
    vis = in_use & ins_seen & ~removed
    vlen = jnp.where(vis, state.length, 0)
    cum = jnp.cumsum(vlen) - vlen
    return vis, vlen, cum


_SLOT_FIELDS = (
    "length",
    "text_start",
    "flags",
    "ins_seq",
    "ins_client",
    "rem_seq",
    "rem_client_a",
    "rem_client_b",
    "prop_key",
    "prop_val",
)


def _shift1(a):
    """out[i] = a[i-1] (out[0] is never selected by callers)."""
    return jnp.roll(a, 1, axis=0)


def _fieldwise(state: DocState, fn, count, overflow) -> DocState:
    return DocState(
        **{name: fn(name, getattr(state, name)) for name in _SLOT_FIELDS},
        count=count,
        overflow=overflow,
    )


def _split_at(state: DocState, pos, ref_seq, client) -> DocState:
    """Split the segment strictly containing visible position ``pos``
    (no-op when pos falls on a boundary). Both halves keep identical
    stamps, flags, and properties (ref: BaseSegment.splitAt).

    Gather-free: the rebuild is a static roll-by-one plus selects (TPU
    gathers with computed indices are the slow path; rolls and selects
    vectorize onto the VPU).
    """
    S = state.max_slots
    vis, vlen, cum = _visibility(state, ref_seq, client)
    inside = vis & (cum < pos) & (pos < cum + vlen)
    has = jnp.any(inside)
    j = jnp.argmax(inside)
    o = pos - cum[j]

    i = jnp.arange(S, dtype=jnp.int32)
    keep = ~has | (i <= j)  # slots at/before the split point stay put
    is_tail = has & (i == j + 1)

    def rebuild(name, a):
        aj = a[j]  # scalar (or [P] row) dynamic read — cheap
        if a.ndim == 2:
            return jnp.where(keep[:, None], a,
                             jnp.where(is_tail[:, None], aj[None, :],
                                       _shift1(a)))
        out = jnp.where(keep, a, jnp.where(is_tail, aj, _shift1(a)))
        if name == "length":
            out = jnp.where(has & (i == j), o, out)
            out = jnp.where(is_tail, state.length[j] - o, out)
        elif name == "text_start":
            out = jnp.where(is_tail, state.text_start[j] + o, out)
        return out

    return _fieldwise(
        state,
        rebuild,
        count=state.count + has.astype(jnp.int32),
        overflow=state.overflow | (has & (state.count + 1 > S)),
    )


def _apply_unified(state: DocState, op) -> DocState:
    """One shared path for insert/remove/annotate (noop passes through):

    1. split at pos/start, split at end (no-ops on boundaries — for an
       insert both land on the same boundary, so neither splits twice);
    2. insert: shift-open a slot at the earliest boundary reaching pos
       (lands BEFORE tombstone runs, matching MergeTree.resolve) and
       stamp it;
    3. remove: mask-stamp covered slots (overlap keeps earliest stamp,
       this client records as additional remover);
    4. annotate: LWW per-key write into the covered slots' prop tables.

    A single structure (vs. a lax.switch of four bodies) matters under
    vmap: batched switch lowers to executing every branch and selecting,
    so shared work would otherwise be paid four times.
    """
    S = state.max_slots
    typ = op[F_TYPE]
    is_ins = typ == OP_INSERT
    is_rem = typ == OP_REMOVE
    is_ann = typ == OP_ANNOTATE
    active = is_ins | is_rem | is_ann
    pos, end = op[F_POS], op[F_END]
    seq, ref_seq, client = op[F_SEQ], op[F_REFSEQ], op[F_CLIENT]
    p2 = jnp.where(is_ins, pos, end)

    vis0, vlen0, cum0 = _visibility(state, ref_seq, client)
    total = jnp.sum(vlen0)
    bad_shape = jnp.where(is_ins, pos > total, (end > total) | (end <= pos))
    # exact slot demand: a split only happens when the position falls
    # STRICTLY inside a visible segment (adding the start boundary cannot
    # move the end strictly inside/outside a segment, so the pre-split
    # test is exact for both)
    inc0 = cum0 + vlen0

    def strictly_inside(p):
        return jnp.any(vis0 & (cum0 < p) & (p < inc0)).astype(jnp.int32)

    needed = jnp.where(
        is_ins,
        1 + strictly_inside(pos),
        strictly_inside(pos) + strictly_inside(end),
    )
    bad = active & (bad_shape | (state.count + needed > S))
    # a bad/inactive op must not split: clamp positions to 0 (never
    # strictly inside a segment) so both splits no-op
    p1s = jnp.where(active & ~bad, pos, 0)
    p2s = jnp.where(active & ~bad, p2, 0)

    st = _split_at(state, p1s, ref_seq, client)
    st = _split_at(st, p2s, ref_seq, client)

    vis, vlen, cum = _visibility(st, ref_seq, client)
    i = jnp.arange(S, dtype=jnp.int32)

    # ---- insert: open a slot at idx and stamp it
    do_ins = is_ins & ~bad
    idx = jnp.argmax(cum >= pos)  # earliest boundary (post-split)
    tlen, tstart = op[F_TLEN], op[F_TSTART]
    shift = do_ins & (i > idx)
    new = do_ins & (i == idx)

    new_vals = {
        "length": jnp.where(tlen > 0, tlen, 1),
        "text_start": tstart,
        "flags": op[F_FLAGS],
        "ins_seq": seq,
        "ins_client": client,
        "rem_seq": NO_SEQ,
        "rem_client_a": NO_CLIENT,
        "rem_client_b": NO_CLIENT,
    }

    def insert_shift(name, a):
        if a.ndim == 2:  # prop tables: new slot starts empty
            fill = NO_KEY if name == "prop_key" else 0
            out = jnp.where(shift[:, None], _shift1(a), a)
            return jnp.where(new[:, None], fill, out)
        out = jnp.where(shift, _shift1(a), a)
        return jnp.where(new, new_vals[name], out)

    st = _fieldwise(
        st,
        insert_shift,
        count=st.count + do_ins.astype(jnp.int32),
        overflow=st.overflow,
    )

    # ---- remove/annotate target mask. The post-split (pre-insert)
    # prefix is correct here: the insert shift only runs when do_ins,
    # in which case this mask is dead — no recompute needed
    covered = vis & (cum >= pos) & (cum + vlen <= end)
    rm = is_rem & ~bad & covered
    fresh = rm & (st.rem_seq == NO_SEQ)
    # overlap: ops apply in seq order so the existing stamp is the
    # earliest; just record this client as an additional remover
    over = rm & (st.rem_seq != NO_SEQ)
    add_b = over & (st.rem_client_a != client) & (st.rem_client_b == NO_CLIENT)
    third = over & (st.rem_client_a != client) & (st.rem_client_b != client) & (
        st.rem_client_b != NO_CLIENT
    )

    # ---- annotate: per-key LWW write (val == NO_VAL deletes the key)
    key, val = op[F_KEY], op[F_VAL]
    P = state.max_props
    an = is_ann & ~bad & covered
    match = st.prop_key == key  # [S, P]
    has_key = jnp.any(match, axis=-1)
    empty = st.prop_key == NO_KEY
    has_empty = jnp.any(empty, axis=-1)
    tgt = jnp.where(has_key, jnp.argmax(match, axis=-1), jnp.argmax(empty, axis=-1))
    is_delete = val == NO_VAL
    do_write = an & (has_key | (~is_delete & has_empty))
    onehot = (jnp.arange(P, dtype=jnp.int32)[None, :] == tgt[:, None]) & do_write[
        :, None
    ]
    # a slot that needs a (P+1)th distinct key cannot hold it → escalate
    table_full = jnp.any(an & ~has_key & ~has_empty & ~is_delete)

    return DocState(
        length=st.length,
        text_start=st.text_start,
        flags=st.flags,
        ins_seq=st.ins_seq,
        ins_client=st.ins_client,
        rem_seq=jnp.where(fresh, seq, st.rem_seq),
        rem_client_a=jnp.where(fresh, client, st.rem_client_a),
        rem_client_b=jnp.where(add_b, client, st.rem_client_b),
        prop_key=jnp.where(onehot, jnp.where(is_delete, NO_KEY, key), st.prop_key),
        prop_val=jnp.where(onehot, jnp.where(is_delete, 0, val), st.prop_val),
        count=st.count,
        overflow=st.overflow | jnp.any(third) | table_full | bad,
    )


def apply_op(state: DocState, op) -> DocState:
    """Apply one sequenced op vector (int32[OP_FIELDS]) to one doc."""
    return _apply_unified(state, op)


# [D docs] × one op each
apply_op_batch = jax.vmap(apply_op)


def apply_ops_scan(state: DocState, ops) -> DocState:
    """Apply K sequenced ops (int32[K, OP_FIELDS]) to one doc, in order."""

    def step(s, op):
        return apply_op(s, op), None

    out, _ = lax.scan(step, state, ops)
    return out


# [D docs] × [K ops each]: the batched hot loop
apply_ops_batch = jax.vmap(apply_ops_scan)


def wave_min_seq(ops) -> jax.Array:
    """Per-doc zamboni floor for a [D, K, OP_FIELDS] wave: the msn of the
    LAST real op applied to each doc. msn is monotone per doc and NOOP
    padding carries msn 0, so this is simply the max over the wave. Using
    the wave's own msn (not a later one) is what keeps compaction safe
    while later-sequenced ops are still staged on the host: deli
    guarantees every future op's refSeq ≥ the msn it stamped HERE, not
    the msn it stamped afterwards."""
    return jnp.max(ops[..., F_MSN], axis=-1)


def compact(state: DocState, min_seq) -> DocState:
    """Zamboni, device-side: drop slots whose remove seq ≤ minSeq (no future
    perspective can see them; ref mergeTree.ts:1455) and re-pack in order."""
    S = state.max_slots
    i = jnp.arange(S, dtype=jnp.int32)
    in_use = i < state.count
    drop = in_use & (state.rem_seq != NO_SEQ) & (state.rem_seq <= min_seq)
    keep = in_use & ~drop
    order = jnp.argsort(jnp.where(keep, i, S + i))  # kept first, stable
    new_count = jnp.sum(keep.astype(jnp.int32))
    live = jnp.arange(S, dtype=jnp.int32) < new_count

    def g(a, fill):
        gathered = a[order]
        mask = live if a.ndim == 1 else live[:, None]
        return jnp.where(mask, gathered, fill)

    return DocState(
        length=g(state.length, 0),
        text_start=g(state.text_start, 0),
        flags=g(state.flags, 0),
        ins_seq=g(state.ins_seq, 0),
        ins_client=g(state.ins_client, NO_CLIENT),
        rem_seq=g(state.rem_seq, NO_SEQ),
        rem_client_a=g(state.rem_client_a, NO_CLIENT),
        rem_client_b=g(state.rem_client_b, NO_CLIENT),
        prop_key=g(state.prop_key, NO_KEY),
        prop_val=g(state.prop_val, 0),
        count=new_count,
        overflow=state.overflow,
    )


compact_batch = jax.vmap(compact)
