"""SharedMap: LWW key-value store with optimistic local ops.

Ref: packages/dds/map/src/map.ts over mapKernel.ts:141 — the kernel logic
lives in map_kernel.MapKernel, shared with SharedDirectory exactly as the
reference shares mapKernel.ts.

Wire ops: {"op": "set", "key", "value"} | {"op": "delete", "key"}
| {"op": "clear"}.
"""

from __future__ import annotations

from typing import Any, Iterator

from ..protocol.messages import SequencedDocumentMessage
from .map_kernel import MapKernel
from .registry import register_channel_type
from .shared_object import SharedObject


@register_channel_type
class SharedMap(SharedObject):
    channel_type = "shared-map"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self._kernel = MapKernel()
        self._pending_ops: list[dict] = []  # FIFO, for ack + resubmit

    # ----------------------------------------------------------- mutators

    def set(self, key: str, value: Any) -> None:
        prev = (self._kernel.get(key), self._kernel.has(key))
        self._kernel.local_set(key, value)
        self._submit_map_op({"op": "set", "key": key, "value": value})
        self._emit("valueChanged", {"key": key, "local": True,
                                    "previousValue": prev[0],
                                    "previousExisted": prev[1]})

    def delete(self, key: str) -> bool:
        prev = (self._kernel.get(key), self._kernel.has(key))
        existed = self._kernel.local_delete(key)
        self._submit_map_op({"op": "delete", "key": key})
        self._emit("valueChanged", {"key": key, "local": True,
                                    "previousValue": prev[0],
                                    "previousExisted": prev[1]})
        return existed

    def clear(self) -> None:
        self._kernel.local_clear()
        self._submit_map_op({"op": "clear"})
        self._emit("clear", {"local": True})

    def _submit_map_op(self, op: dict) -> None:
        self._pending_ops.append(op)
        self.submit_local_message(op)

    # ------------------------------------------------------------ readers

    def get(self, key: str, default: Any = None) -> Any:
        return self._kernel.get(key, default)

    def has(self, key: str) -> bool:
        return self._kernel.has(key)

    def keys(self) -> Iterator[str]:
        return self._kernel.keys()

    def items(self):
        return self._kernel.data.items()

    def __len__(self) -> int:
        return len(self._kernel.data)

    # ----------------------------------------------------------- contract

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._kernel.ack(self._pending_ops.pop(0))
            return
        op = msg.contents
        if self._kernel.apply_remote(op):
            if op["op"] == "clear":
                self._emit("clear", {"local": False})
            else:
                self._emit("valueChanged", {"key": op["key"], "local": False})

    def resubmit_pending(self) -> None:
        # LWW values carry no positions: resubmit verbatim, same order
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return {"data": dict(self._kernel.data)}

    def load_core(self, snap: dict) -> None:
        self._kernel.data = dict(snap.get("data", {}))
