"""SharedMap: LWW key-value store with optimistic local ops.

Ref: packages/dds/map/src/mapKernel.ts:141 — local set/delete/clear apply
immediately; remote ops for a key are IGNORED while a local op on that key
is in flight (the local op is later in the total order, so it wins
everywhere once sequenced: tryProcessMessage :515). Clear has its own
pending count; acks decrement (trySubmitMessage :498). Values must be
JSON-serializable; DDS handles are a framework-layer concern.

Wire ops: {"op": "set", "key", "value"} | {"op": "delete", "key"}
| {"op": "clear"}.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from ..protocol.messages import SequencedDocumentMessage
from .registry import register_channel_type
from .shared_object import SharedObject


@register_channel_type
class SharedMap(SharedObject):
    channel_type = "shared-map"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self._data: dict[str, Any] = {}
        self._pending_keys: dict[str, int] = {}  # key → in-flight local ops
        self._pending_clear_count = 0
        self._pending_ops: list[dict] = []  # FIFO, for ack + resubmit

    # ----------------------------------------------------------- mutators

    def set(self, key: str, value: Any) -> None:
        self._data[key] = value
        self._submit_map_op({"op": "set", "key": key, "value": value})
        self._emit("valueChanged", {"key": key, "local": True})

    def delete(self, key: str) -> bool:
        existed = key in self._data
        self._data.pop(key, None)
        self._submit_map_op({"op": "delete", "key": key})
        self._emit("valueChanged", {"key": key, "local": True})
        return existed

    def clear(self) -> None:
        self._data.clear()
        self._pending_clear_count += 1
        self._pending_ops.append({"op": "clear"})
        self.submit_local_message({"op": "clear"})
        self._emit("clear", {"local": True})

    def _submit_map_op(self, op: dict) -> None:
        self._pending_keys[op["key"]] = self._pending_keys.get(op["key"], 0) + 1
        self._pending_ops.append(op)
        self.submit_local_message(op)

    # ------------------------------------------------------------ readers

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def has(self, key: str) -> bool:
        return key in self._data

    def keys(self) -> Iterator[str]:
        return iter(self._data.keys())

    def items(self):
        return self._data.items()

    def __len__(self) -> int:
        return len(self._data)

    # ----------------------------------------------------------- contract

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        op = msg.contents
        if local:
            # our own op came back: clear its pending mark; state applied
            # optimistically already
            head = self._pending_ops.pop(0)
            if head["op"] == "clear":
                self._pending_clear_count -= 1
            else:
                key = head["key"]
                self._pending_keys[key] -= 1
                if self._pending_keys[key] == 0:
                    del self._pending_keys[key]
            return

        if op["op"] == "clear":
            # a remote clear wipes acked state but keeps our optimistic
            # pending values (they resequence after the clear)
            if self._pending_keys:
                survivors = {k: v for k, v in self._data.items()
                             if k in self._pending_keys}
                self._data = survivors
            else:
                self._data.clear()
            self._emit("clear", {"local": False})
            return

        key = op["key"]
        if self._pending_clear_count > 0 or key in self._pending_keys:
            return  # our in-flight op is later in the total order: it wins
        if op["op"] == "set":
            self._data[key] = op["value"]
        else:
            self._data.pop(key, None)
        self._emit("valueChanged", {"key": key, "local": False})

    def resubmit_pending(self) -> None:
        # LWW values carry no positions: resubmit verbatim, same order
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return {"data": dict(self._data)}

    def load_core(self, snap: dict) -> None:
        self._data = dict(snap.get("data", {}))
