"""Value sequences: ordered lists of items over the merge-tree CRDT.

Ref: packages/dds/sequence — SharedNumberSequence / SharedObjectSequence
(sequence.ts SharedSegmentSequence over SubSequence segments). Here each
item rides a merge-tree MARKER segment (length 1, dict payload), so
insert/remove get the full concurrent-position semantics of the text
path for free; items must be JSON-serializable.
"""

from __future__ import annotations

from typing import Any, Sequence

from ..mergetree.ops import op_to_wire
from ..protocol.messages import MessageType, SequencedDocumentMessage
from .registry import register_channel_type
from .string import SharedString

ITEM_KEY = "seqItem"


class SharedSequence(SharedString):
    """Sequence of opaque items; reuses the SharedString channel machinery
    (merge-tree client, interval collections, reconnect regeneration)."""

    def insert_range(self, pos: int, items: Sequence[Any]) -> None:
        # one marker per item: concurrent inserts interleave at item
        # granularity exactly like characters
        for i, item in enumerate(items):
            op = self.client.insert_marker_local(pos + i, {ITEM_KEY: item})
            self.submit_local_message(op_to_wire(op))
        self._emit("sequenceDelta", {"op": "insert", "pos": pos,
                                     "count": len(items), "local": True})

    def remove_range(self, start: int, end: int) -> None:
        op = self.client.remove_range_local(start, end)
        self.submit_local_message(op_to_wire(op))
        self._emit("sequenceDelta", {"op": "remove", "start": start,
                                     "end": end, "local": True})

    def get_items(self, start: int = 0, end: int | None = None) -> list[Any]:
        view = self.client.local_view()
        items = [
            seg.marker[ITEM_KEY]
            for seg in self.client.tree.segments
            if seg.is_marker and seg.visible_in(view) and ITEM_KEY in seg.marker
        ]
        return items[start:end]

    def get_item(self, pos: int) -> Any:
        seg, _ = self.client.tree.visible_segment_at(
            pos, self.client.local_view())
        if seg is None:
            raise IndexError(pos)
        return seg.marker[ITEM_KEY]

    def item_count(self) -> int:
        return self.client.get_length()


@register_channel_type
class SharedNumberSequence(SharedSequence):
    channel_type = "shared-number-sequence"


@register_channel_type
class SharedObjectSequence(SharedSequence):
    channel_type = "shared-object-sequence"
