"""SharedDirectory: hierarchical SharedMaps with subdirectory ops.

Ref: packages/dds/map/src/directory.ts:371 — a tree of named
subdirectories, each holding its own LWW key store. The kernel logic is
shared with SharedMap (map_kernel.MapKernel, as the reference shares
mapKernel.ts). Subdirectory create/delete follow the SAME pending-masking
rule as keys — an in-flight local create/delete of a name masks remote
ops on that name — and ops addressed to a path that does not exist are
DROPPED, never resurrected: a sequenced deleteSubdir deterministically
kills the whole subtree (and any interior ops) on every replica.

Wire ops carry an absolute ``path`` (["a","b"] = /a/b):
{"op": "set"/"delete"/"clear", "path", ...} |
{"op": "createSubdir"/"deleteSubdir", "path", "name"}.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from ..protocol.messages import SequencedDocumentMessage
from .map_kernel import MapKernel
from .registry import register_channel_type
from .shared_object import SharedObject


class SubDirectory:
    def __init__(self, root: "SharedDirectory", path: tuple[str, ...]):
        self._root = root
        self._path = path
        self._kernel = MapKernel()
        self._subdirs: dict[str, "SubDirectory"] = {}
        self._pending_subdirs: dict[str, int] = {}  # name → in-flight ops

    # ------------------------------------------------------------- values

    def set(self, key: str, value: Any) -> None:
        self._kernel.local_set(key, value)
        self._root._submit_dir_op(
            {"op": "set", "path": list(self._path), "key": key, "value": value})

    def delete(self, key: str) -> bool:
        existed = self._kernel.local_delete(key)
        self._root._submit_dir_op(
            {"op": "delete", "path": list(self._path), "key": key})
        return existed

    def clear(self) -> None:
        self._kernel.local_clear()
        self._root._submit_dir_op({"op": "clear", "path": list(self._path)})

    def get(self, key: str, default: Any = None) -> Any:
        return self._kernel.get(key, default)

    def has(self, key: str) -> bool:
        return self._kernel.has(key)

    def keys(self) -> Iterator[str]:
        return self._kernel.keys()

    # -------------------------------------------------------- subdirectories

    def create_subdirectory(self, name: str) -> "SubDirectory":
        if name not in self._subdirs:
            self._subdirs[name] = SubDirectory(self._root, self._path + (name,))
            self._pending_subdirs[name] = self._pending_subdirs.get(name, 0) + 1
            self._root._submit_dir_op(
                {"op": "createSubdir", "path": list(self._path), "name": name})
        return self._subdirs[name]

    def delete_subdirectory(self, name: str) -> bool:
        existed = name in self._subdirs
        self._subdirs.pop(name, None)
        self._pending_subdirs[name] = self._pending_subdirs.get(name, 0) + 1
        self._root._submit_dir_op(
            {"op": "deleteSubdir", "path": list(self._path), "name": name})
        return existed

    def get_subdirectory(self, name: str) -> Optional["SubDirectory"]:
        return self._subdirs.get(name)

    def subdirectories(self):
        return self._subdirs.items()

    # ------------------------------------------------------------ internal

    def _snapshot(self) -> dict:
        return {
            "data": dict(self._kernel.data),
            "subdirs": {n: d._snapshot() for n, d in self._subdirs.items()},
        }

    def _load(self, snap: dict) -> None:
        self._kernel.data = dict(snap.get("data", {}))
        for name, sub in snap.get("subdirs", {}).items():
            d = SubDirectory(self._root, self._path + (name,))
            d._load(sub)
            self._subdirs[name] = d


@register_channel_type
class SharedDirectory(SharedObject):
    channel_type = "shared-directory"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self.root = SubDirectory(self, ())
        self._pending_ops: list[dict] = []

    # root-level conveniences (the directory IS a map at its root)
    def set(self, key: str, value: Any) -> None:
        self.root.set(key, value)

    def get(self, key: str, default: Any = None) -> Any:
        return self.root.get(key, default)

    def delete(self, key: str) -> bool:
        return self.root.delete(key)

    def has(self, key: str) -> bool:
        return self.root.has(key)

    def create_subdirectory(self, name: str) -> SubDirectory:
        return self.root.create_subdirectory(name)

    def delete_subdirectory(self, name: str) -> bool:
        return self.root.delete_subdirectory(name)

    def get_subdirectory(self, name: str) -> Optional[SubDirectory]:
        return self.root.get_subdirectory(name)

    def get_working_directory(self, path: str) -> Optional[SubDirectory]:
        """Resolve an absolute path like "/a/b" (ref: directory.ts)."""
        node: Optional[SubDirectory] = self.root
        for part in [p for p in path.split("/") if p]:
            if node is None:
                return None
            node = node.get_subdirectory(part)
        return node

    # ------------------------------------------------------------ internal

    def _submit_dir_op(self, op: dict) -> None:
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def _resolve(self, path: list[str]) -> Optional[SubDirectory]:
        node = self.root
        for part in path:
            node = node._subdirs.get(part)
            if node is None:
                return None  # never resurrect a deleted subtree
        return node

    def _resolve_remote(self, path: list[str]) -> Optional[SubDirectory]:
        """Resolution for REMOTE ops: a pending local create/delete on any
        path component masks the whole subtree — our sequenced-later op
        will decide that subtree's fate on every replica, so interior
        remote ops must not land only here."""
        node = self.root
        for part in path:
            if part in node._pending_subdirs:
                return None
            node = node._subdirs.get(part)
            if node is None:
                return None
        return node

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        op = msg.contents
        if local:
            # release the mask and RE-APPLY at the sequenced position
            # (map_kernel.ack semantics): if a remote delete+recreate of
            # the node swallowed our optimistic application, the sequenced
            # op still lands on the replacement, as on every other replica
            head = self._pending_ops.pop(0)
            d = self._resolve(head["path"])
            if d is not None:
                if head["op"] in ("set", "delete", "clear"):
                    d._kernel.ack(head)
                else:
                    name = head["name"]
                    if name in d._pending_subdirs:
                        d._pending_subdirs[name] -= 1
                        if d._pending_subdirs[name] == 0:
                            del d._pending_subdirs[name]
                    if name not in d._pending_subdirs:
                        if head["op"] == "createSubdir":
                            if name not in d._subdirs:
                                d._subdirs[name] = SubDirectory(
                                    self, d._path + (name,))
                        else:
                            d._subdirs.pop(name, None)
            return

        d = self._resolve_remote(op["path"])
        if d is None:
            return  # path deleted, never created here, or locally masked
        kind = op["op"]
        if kind in ("createSubdir", "deleteSubdir"):
            name = op["name"]
            if name in d._pending_subdirs:
                return  # our in-flight create/delete is later: it wins
            if kind == "createSubdir":
                if name not in d._subdirs:
                    d._subdirs[name] = SubDirectory(self, d._path + (name,))
                self._emit("subDirectoryCreated",
                           {"path": op["path"], "name": name})
            else:
                d._subdirs.pop(name, None)
                self._emit("subDirectoryDeleted",
                           {"path": op["path"], "name": name})
            return
        if d._kernel.apply_remote(op):
            if kind == "clear":
                self._emit("clear", {"path": op["path"], "local": False})
            else:
                self._emit("valueChanged",
                           {"path": op["path"], "key": op["key"], "local": False})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return self.root._snapshot()

    def load_core(self, snap: dict) -> None:
        self.root._load(snap)
