"""SharedObject: the contract every DDS implements.

Ref: packages/dds/shared-object-base/src/sharedObject.ts — snapshot()
:191, loadCore() :206, processCore() :237, reSubmit() :398, plus dirty/ack
bookkeeping. Channels submit through a bound connection adapter
(datastore ChannelDeltaConnection analog) and receive every sequenced op
for their address, with ``local`` telling them it is their own ack.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Any, Callable, Optional

from ..protocol.messages import SequencedDocumentMessage
from ..protocol.summary import SummaryBlob, SummaryHandle, SummaryObject


class SharedObject:
    channel_type: str = "shared-object"

    def __init__(self, channel_id: str):
        self.id = channel_id
        self._submit_fn: Optional[Callable[[Any], None]] = None
        self._is_connected_fn: Callable[[], bool] = lambda: False
        self._listeners: dict[str, list[Callable]] = defaultdict(list)
        self.client_id: Optional[str] = None
        # seq of the last sequenced op that touched this channel — the
        # incremental-summary producer compares it against the parent
        # summary's capture seq to decide handle reuse (ref: summarizer
        # tracking in summarizerNode / channel contexts)
        self.last_changed_seq = 0

    # ------------------------------------------------------------- wiring

    def _bind(self, submit: Callable[[Any], None], is_connected: Callable[[], bool]) -> None:
        self._submit_fn = submit
        self._is_connected_fn = is_connected

    @property
    def is_attached(self) -> bool:
        return self._submit_fn is not None

    def submit_local_message(self, contents: Any) -> None:
        """Send a local op (the runtime records it as pending even while
        disconnected, replaying on reconnect)."""
        if self._submit_fn is None:
            raise RuntimeError(f"channel {self.id} is not attached")
        self._submit_fn(contents)

    # ------------------------------------------------------------- events

    def on(self, event: str, cb: Callable) -> Callable:
        self._listeners[event].append(cb)
        return cb

    def off(self, event: str, cb: Callable) -> None:
        if cb in self._listeners[event]:
            self._listeners[event].remove(cb)

    def _emit(self, event: str, *args) -> None:
        for cb in list(self._listeners[event]):
            cb(*args)

    # ----------------------------------------------------------- contract

    def process(self, msg: SequencedDocumentMessage, local: bool) -> None:
        self.last_changed_seq = msg.sequence_number
        self.process_core(msg, local)

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        raise NotImplementedError

    def resubmit_pending(self) -> None:
        """Regenerate + resubmit all unacked local ops after reconnect
        (ref: reSubmit sharedObject.ts:398)."""
        raise NotImplementedError

    def set_connection_state(self, connected: bool, client_id: Optional[str]) -> None:
        if connected:
            self.client_id = client_id
            self.on_connect(client_id)
        else:
            self.on_disconnect()

    def on_connect(self, client_id: str) -> None:
        pass

    def on_disconnect(self) -> None:
        pass

    def snapshot(self) -> dict:
        raise NotImplementedError

    def load_core(self, snap: dict) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------ summary

    def summarize(self, path: str,
                  parent_capture_seq: Optional[int] = None) -> SummaryObject:
        """Incremental summary entry: a channel untouched since the parent
        summary's capture seq is sent as a HANDLE to the parent's subtree
        at ``path`` — nothing re-uploads (ref: protocol-definitions
        summary.ts ISummaryHandle; channel contexts deciding reuse).

        ``last_changed_seq > 0`` guards new channels: one that never saw a
        sequenced op (attach included) cannot be in the parent tree."""
        from ..protocol.summary import SummaryTree

        if (
            parent_capture_seq is not None
            and 0 < self.last_changed_seq <= parent_capture_seq
        ):
            return SummaryHandle(handle=path)
        return SummaryTree(tree={
            "type": SummaryBlob(json.dumps(self.channel_type).encode()),
            "snapshot": self.summarize_core(),
        })

    def summarize_core(self) -> SummaryObject:
        """Full (non-handle) summary content. Default: one blob holding
        the snapshot; DDSes with big state override with a chunked tree
        (merge-tree, ref snapshotV1.ts:87)."""
        return SummaryBlob(
            json.dumps(self.snapshot(), separators=(",", ":")).encode())
