"""SharedMatrix: a 2-D grid over two merge-tree permutation vectors.

Ref: packages/dds/matrix (SURVEY §2.2) — rows and cols are each a
merge-tree sequence (permutationvector.ts:124) mapping logical index →
stable LOCAL handle; cells live in a sparse store keyed by (row_handle,
col_handle) (sparsearray2d.ts:60). Row/col insert/remove are merge-tree
ops; setCell is LWW with pending-local masking (matrix.ts:197-273).

Handles never cross the wire: insert ops carry only (pos, count) and each
replica allocates its own contiguous handles on apply; setCell ops carry
(row, col) POSITIONS resolved at the author's (refSeq, clientId)
perspective — exactly the merge-tree concurrent-position rule, reused
twice.

Wire: {"op": "insertRows"/"insertCols"/"removeRows"/"removeCols",
       "pos", "count"}
    | {"op": "setCell", "row", "col", "value"}.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Optional

from ..mergetree.client import MergeTreeClient
from ..mergetree.ops import InsertOp, RemoveOp, op_to_wire
from ..mergetree.perspective import Perspective
from ..protocol.messages import SequencedDocumentMessage
from .registry import register_channel_type
from .shared_object import SharedObject

HANDLE_BASE = 0x100  # handle h ↔ chr(HANDLE_BASE + h) in segment text
DETACHED_ID = "detached"


class PermutationVector:
    """Logical index → stable local handle, under concurrent edits.

    The merge-tree does all the work: segment text chars ARE the handles
    (split arithmetic keeps them contiguous per fragment), and position
    resolution at any (refSeq, client) perspective is the standard
    merge-tree query (ref: permutationvector.ts PermutationSegment:36,
    handletable.ts:19).
    """

    def __init__(self):
        self.mc = MergeTreeClient(DETACHED_ID)
        self._next_handle = 0

    def alloc(self, count: int) -> str:
        start = self._next_handle
        self._next_handle += count
        return "".join(chr(HANDLE_BASE + start + i) for i in range(count))

    @property
    def length(self) -> int:
        return self.mc.get_length()

    def handle_at(self, pos: int, perspective: Optional[Perspective] = None) -> int:
        """The stable handle of the item at ``pos`` in the given view."""
        persp = perspective or self.mc.local_view()
        seg, off = self.mc.tree.visible_segment_at(pos, persp)
        if seg is None:
            raise IndexError(f"position {pos} out of range")
        return ord(seg.text[off]) - HANDLE_BASE

    def position_of_handle(self, handle: int) -> Optional[int]:
        """CURRENT local position of a handle (None if its item is gone)."""
        ch = chr(HANDLE_BASE + handle)
        persp = self.mc.local_view()
        pos = 0
        for seg in self.mc.tree.segments:
            vl = seg.visible_length(persp)
            idx = seg.text.find(ch) if seg.text else -1
            if idx >= 0:
                return pos + idx if vl > 0 else None
            pos += vl
        return None

    def snapshot(self) -> dict:
        return {"mc": self.mc.snapshot(), "nextHandle": self._next_handle}

    def load(self, snap: dict) -> None:
        self.mc = MergeTreeClient.load(DETACHED_ID, snap["mc"])
        self._next_handle = snap["nextHandle"]


@register_channel_type
class SharedMatrix(SharedObject):
    channel_type = "shared-matrix"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self.rows = PermutationVector()
        self.cols = PermutationVector()
        self._cells: dict[tuple[int, int], Any] = {}  # (row_h, col_h) → value
        # FIFO of pending local ops:
        # {"kind": "vector", "wire": ..., } | {"kind": "cell", "rh","ch","wire"}
        self._pending: list[dict] = []
        self._pending_cells: dict[tuple[int, int], int] = {}

    # ------------------------------------------------------------ shape api

    @property
    def row_count(self) -> int:
        return self.rows.length

    @property
    def col_count(self) -> int:
        return self.cols.length

    def insert_rows(self, pos: int, count: int) -> None:
        self._insert_vector(self.rows, "insertRows", pos, count)

    def insert_cols(self, pos: int, count: int) -> None:
        self._insert_vector(self.cols, "insertCols", pos, count)

    def remove_rows(self, pos: int, count: int) -> None:
        self._remove_vector(self.rows, "removeRows", pos, count)

    def remove_cols(self, pos: int, count: int) -> None:
        self._remove_vector(self.cols, "removeCols", pos, count)

    def _insert_vector(self, vec: PermutationVector, kind: str, pos: int, count: int) -> None:
        vec.mc.insert_text_local(pos, vec.alloc(count))
        wire = {"op": kind, "pos": pos, "count": count}
        self._pending.append({"kind": "vector", "wire": wire})
        self.submit_local_message(wire)
        self._emit("shapeChanged", {
            "op": kind, "pos": pos, "count": count, "local": True,
            # stable handles of the inserted span: undo anchors on these,
            # not on positions that concurrent remote edits can shift
            "handles": [vec.handle_at(p) for p in range(pos, pos + count)],
        })

    def _remove_vector(self, vec: PermutationVector, kind: str, pos: int, count: int) -> None:
        handles = [vec.handle_at(p) for p in range(pos, pos + count)]
        vec.mc.remove_range_local(pos, pos + count)
        wire = {"op": kind, "pos": pos, "count": count}
        self._pending.append({"kind": "vector", "wire": wire})
        self.submit_local_message(wire)
        self._purge_cells(kind.endswith("Rows"), handles)
        self._emit("shapeChanged", {"op": kind, "local": True})

    def _purge_cells(self, is_rows: bool, handles: list[int]) -> None:
        """Drop cell values of removed rows/cols so the sparse store and
        snapshots do not grow without bound (ref: matrix handle recycling
        via handletable.ts — we reclaim storage, not handles)."""
        dead = set(handles)
        axis = 0 if is_rows else 1
        for key in [k for k in self._cells if k[axis] in dead]:
            del self._cells[key]

    # ------------------------------------------------------------- cell api

    def set_cell(self, row: int, col: int, value: Any) -> None:
        rh = self.rows.handle_at(row)
        ch = self.cols.handle_at(col)
        prev = self._cells.get((rh, ch))
        self._cells[(rh, ch)] = value
        self._pending_cells[(rh, ch)] = self._pending_cells.get((rh, ch), 0) + 1
        wire = {"op": "setCell", "row": row, "col": col, "value": value}
        self._pending.append({"kind": "cell", "rh": rh, "ch": ch, "wire": wire})
        self.submit_local_message(wire)
        self._emit("cellChanged", {"row": row, "col": col, "local": True,
                                   "rowHandle": rh, "colHandle": ch,
                                   "previousValue": prev})

    def position_of_handles(self, row_handle: int, col_handle: int):
        """Current (row, col) of a stable handle pair, or None when
        either axis was removed — the undo anchor resolution."""
        row = self.rows.position_of_handle(row_handle)
        col = self.cols.position_of_handle(col_handle)
        if row is None or col is None:
            return None
        return row, col

    def get_cell(self, row: int, col: int) -> Any:
        rh = self.rows.handle_at(row)
        ch = self.cols.handle_at(col)
        return self._cells.get((rh, ch))

    def to_lists(self) -> list[list[Any]]:
        return [
            [self.get_cell(r, c) for c in range(self.col_count)]
            for r in range(self.row_count)
        ]

    # ------------------------------------------------------------- contract

    _VECTOR_OPS = {
        "insertRows": ("rows", True), "insertCols": ("cols", True),
        "removeRows": ("rows", False), "removeCols": ("cols", False),
    }

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        op = msg.contents
        kind = op["op"]
        if local:
            head = self._pending.pop(0)
            if head["kind"] == "cell":
                key = (head["rh"], head["ch"])
                self._pending_cells[key] -= 1
                if self._pending_cells[key] == 0:
                    del self._pending_cells[key]
                self._observe_all(msg)
            else:
                # ack the vector op on its owning merge tree; the other
                # vector just observes the (seq, msn) advance
                axis, is_insert = self._VECTOR_OPS[head["wire"]["op"]]
                mt_wire = self._to_merge_wire(head["wire"], text="?" * head["wire"]["count"])
                getattr(self, axis).mc.apply_msg(replace(msg, contents=mt_wire), True)
                self._observe_other(axis, msg)
            return

        if kind == "setCell":
            rows_persp = Perspective(
                msg.reference_sequence_number, self.rows.mc.intern(msg.client_id))
            cols_persp = Perspective(
                msg.reference_sequence_number, self.cols.mc.intern(msg.client_id))
            rh = self.rows.handle_at(op["row"], rows_persp)
            ch = self.cols.handle_at(op["col"], cols_persp)
            self._observe_all(msg)
            if (rh, ch) in self._pending_cells:
                return  # our in-flight write is later in the order: it wins
            if (self.rows.position_of_handle(rh) is None
                    or self.cols.position_of_handle(ch) is None):
                return  # target row/col already removed: don't resurrect
            self._cells[(rh, ch)] = op["value"]
            self._emit("cellChanged", {"rowHandle": rh, "colHandle": ch,
                                       "local": False})
            return

        axis, is_insert = self._VECTOR_OPS[kind]
        vec: PermutationVector = getattr(self, axis)
        text = vec.alloc(op["count"]) if is_insert else ""
        if not is_insert:
            # capture the doomed handles at the author's view before apply
            persp = Perspective(msg.reference_sequence_number,
                                vec.mc.intern(msg.client_id))
            dead = [vec.handle_at(p, persp)
                    for p in range(op["pos"], op["pos"] + op["count"])]
        vec.mc.apply_msg(replace(msg, contents=self._to_merge_wire(op, text)), False)
        if not is_insert:
            self._purge_cells(axis == "rows", dead)
        self._observe_other(axis, msg)
        self._emit("shapeChanged", {"op": kind, "local": False})

    @staticmethod
    def _to_merge_wire(op: dict, text: str) -> dict:
        if op["op"].startswith("insert"):
            return op_to_wire(InsertOp(pos=op["pos"], text=text))
        return op_to_wire(RemoveOp(start=op["pos"], end=op["pos"] + op["count"]))

    def _observe_all(self, msg: SequencedDocumentMessage) -> None:
        for vec in (self.rows, self.cols):
            self._observe(vec, msg)

    def _observe_other(self, applied_axis: str, msg: SequencedDocumentMessage) -> None:
        self._observe(self.cols if applied_axis == "rows" else self.rows, msg)

    @staticmethod
    def _observe(vec: PermutationVector, msg: SequencedDocumentMessage) -> None:
        """Advance (seq, msn) on a vector that got no op of its own, so
        zamboni windows stay in sync with the document order."""
        tree = vec.mc.tree
        tree.current_seq = max(tree.current_seq, msg.sequence_number)
        tree.update_min_seq(msg.minimum_sequence_number)

    # ------------------------------------------------------------ reconnect

    def resubmit_pending(self) -> None:
        """Rebase-and-resubmit: vector ops regenerate through their merge
        trees; cell ops re-resolve their handles to CURRENT positions
        (dropping writes to rows/cols that no longer exist)."""
        pending, self._pending = self._pending, []
        for axis in ("rows", "cols"):
            vec: PermutationVector = getattr(self, axis)
            for mop in vec.mc.regenerate_pending_ops():
                if isinstance(mop, InsertOp):
                    wire = {"op": f"insert{axis.capitalize()}", "pos": mop.pos,
                            "count": len(mop.text)}
                else:
                    wire = {"op": f"remove{axis.capitalize()}", "pos": mop.start,
                            "count": mop.end - mop.start}
                self._pending.append({"kind": "vector", "wire": wire})
                self.submit_local_message(wire)
        for entry in pending:
            if entry["kind"] != "cell":
                continue
            row = self.rows.position_of_handle(entry["rh"])
            col = self.cols.position_of_handle(entry["ch"])
            key = (entry["rh"], entry["ch"])
            if row is None or col is None:
                # target vanished: drop the write and its pending mask
                self._pending_cells[key] -= 1
                if self._pending_cells[key] == 0:
                    del self._pending_cells[key]
                continue
            wire = dict(entry["wire"], row=row, col=col)
            self._pending.append({"kind": "cell", "rh": entry["rh"],
                                  "ch": entry["ch"], "wire": wire})
            self.submit_local_message(wire)

    def on_connect(self, client_id: str) -> None:
        for vec in (self.rows, self.cols):
            if client_id != vec.mc.client_id:
                vec.mc.update_client_id(client_id)

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        return {
            "rows": self.rows.snapshot(),
            "cols": self.cols.snapshot(),
            "cells": [[rh, ch, v] for (rh, ch), v in self._cells.items()],
        }

    def load_core(self, snap: dict) -> None:
        self.rows.load(snap["rows"])
        self.cols.load(snap["cols"])
        self._cells = {(rh, ch): v for rh, ch, v in snap["cells"]}
