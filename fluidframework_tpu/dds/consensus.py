"""Consensus collections: linearizable primitives over the total order.

Ref: packages/dds/register-collection (consensusRegisterCollection.ts) and
packages/dds/ordered-collection (consensusOrderedCollection.ts,
consensusQueue.ts). Unlike the optimistic DDSes these expose only ACKED
state — a write is visible when its op comes back sequenced, and
linearizability falls out of the total order + collaboration window.
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Optional

from ..protocol.messages import SequencedDocumentMessage
from .registry import register_channel_type
from .shared_object import SharedObject


@register_channel_type
class ConsensusRegisterCollection(SharedObject):
    """Named linearizable registers with concurrency-window versioning.

    Ref: consensusRegisterCollection.ts — each write lands with its
    (seq, refSeq); versions the writer had SEEN (seq ≤ writer's refSeq)
    are superseded and dropped; concurrent versions coexist until later
    writes observe them. Read policies: "atomic" = the earliest surviving
    version (the consensus winner), "lww" = the latest.

    Wire: {"op": "write", "key", "value"}.
    """

    channel_type = "consensus-register-collection"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        # key → list of {"value", "seq"} ordered by seq
        self._versions: dict[str, list[dict]] = {}
        self._pending_ops: list[dict] = []

    def write(self, key: str, value: Any) -> None:
        op = {"op": "write", "key": key, "value": value}
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def read(self, key: str, policy: str = "atomic") -> Optional[Any]:
        versions = self._versions.get(key)
        if not versions:
            return None
        return versions[0 if policy == "atomic" else -1]["value"]

    def read_versions(self, key: str) -> list[Any]:
        return [v["value"] for v in self._versions.get(key, [])]

    def keys(self):
        return list(self._versions.keys())

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._pending_ops.pop(0)
        op = msg.contents
        key = op["key"]
        versions = self._versions.setdefault(key, [])
        # versions the writer had seen are superseded (ref:
        # consensusRegisterCollection processInboundWrite)
        ref = msg.reference_sequence_number
        versions[:] = [v for v in versions if v["seq"] > ref]
        versions.append({"value": op["value"], "seq": msg.sequence_number})
        won = versions[0]["seq"] == msg.sequence_number
        self._emit("atomicChanged" if won else "versionChanged",
                   {"key": key, "local": local})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return {"versions": {k: list(v) for k, v in self._versions.items()}}

    def load_core(self, snap: dict) -> None:
        self._versions = {k: list(v) for k, v in snap.get("versions", {}).items()}


@register_channel_type
class ConsensusQueue(SharedObject):
    """Exactly-once distributed work queue.

    Ref: consensusOrderedCollection.ts/consensusQueue.ts — ``add`` appends;
    ``acquire`` hands the head to exactly one client (decided by the total
    order); the holder must ``complete`` (remove durably) or ``release``
    (requeue). A holder's leave releases its items deterministically
    (every replica sees the same sequenced leave).

    Wire: {"op": "add", "value", "id"} | {"op": "acquire", "id"}
    | {"op": "complete"/"release", "id"}.
    """

    channel_type = "consensus-queue"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self._items: list[dict] = []  # {"id", "value"} FIFO
        self._in_flight: dict[str, dict] = {}  # item id → {"value", "client"}
        self._pending_ops: list[dict] = []
        self._uid = itertools.count()
        # ids minted before attach must still be globally unique — a
        # literal 'detached' prefix would collide across replicas
        self._detached_token = f"detached-{uuid.uuid4().hex[:12]}"

    # ---------------------------------------------------------------- api

    def _mint_id(self) -> str:
        return f"{self.client_id or self._detached_token}:{next(self._uid)}"

    def add(self, value: Any) -> None:
        op = {"op": "add", "value": value, "id": self._mint_id()}
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def acquire(self) -> str:
        """Request the queue head. Returns a ticket; listen for
        "acquired" events or poll :meth:`holding` for the outcome."""
        ticket = self._mint_id()
        op = {"op": "acquire", "id": ticket}
        self._pending_ops.append(op)
        self.submit_local_message(op)
        return ticket

    def complete(self, item_id: str) -> None:
        op = {"op": "complete", "id": item_id}
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def release(self, item_id: str) -> None:
        op = {"op": "release", "id": item_id}
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def holding(self, client_id: Optional[str] = None) -> list[tuple[str, Any]]:
        """Items currently held by ``client_id`` (default: me)."""
        me = client_id or self.client_id
        return [(iid, e["value"]) for iid, e in self._in_flight.items()
                if e["client"] == me]

    def __len__(self) -> int:
        return len(self._items)

    def peek_values(self) -> list[Any]:
        return [i["value"] for i in self._items]

    # ----------------------------------------------------------- contract

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._pending_ops.pop(0)
        op = msg.contents
        kind = op["op"]
        if kind == "add":
            self._items.append({"id": op["id"], "value": op["value"]})
            self._emit("add", {"value": op["value"], "local": local})
        elif kind == "acquire":
            if self._items:
                item = self._items.pop(0)
                self._in_flight[item["id"]] = {
                    "value": item["value"], "client": msg.client_id}
                self._emit("acquired", {
                    "ticket": op["id"], "itemId": item["id"],
                    "value": item["value"], "client": msg.client_id,
                    "local": local})
            elif local:
                self._emit("acquireFailed", {"ticket": op["id"]})
        elif kind == "complete":
            entry = self._in_flight.pop(op["id"], None)
            if entry is not None:
                self._emit("complete", {"itemId": op["id"], "value": entry["value"]})
        elif kind == "release":
            entry = self._in_flight.pop(op["id"], None)
            if entry is not None:
                # released items re-add at the BACK (ref:
                # consensusOrderedCollection removeClient/release), which
                # also keeps multi-item releases in FIFO order
                self._items.append({"id": op["id"], "value": entry["value"]})
                self._emit("localRelease", {"itemId": op["id"]})

    def on_member_removed(self, client_id: str) -> None:
        """A holder left: requeue its items (deterministic — driven by the
        sequenced leave every replica processes)."""
        for iid in [i for i, e in self._in_flight.items() if e["client"] == client_id]:
            entry = self._in_flight.pop(iid)
            self._items.append({"id": iid, "value": entry["value"]})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return {"items": list(self._items),
                "inFlight": {k: dict(v) for k, v in self._in_flight.items()}}

    def load_core(self, snap: dict) -> None:
        self._items = list(snap.get("items", []))
        self._in_flight = {k: dict(v) for k, v in snap.get("inFlight", {}).items()}
