"""MapKernel: the LWW key-store state machine shared by map + directory.

Ref: packages/dds/map/src/mapKernel.ts:141 — one implementation of
optimistic local apply with pending-local masking, used by both SharedMap
and every SharedDirectory node (the reference shares mapKernel.ts between
them for the same reason).

Rules: local set/delete/clear apply immediately and mask the key (or the
whole store for clear) against remote ops until acked — the local op is
later in the total order, so it wins everywhere once sequenced.
"""

from __future__ import annotations

from typing import Any, Iterator


class MapKernel:
    def __init__(self):
        self.data: dict[str, Any] = {}
        self.pending_keys: dict[str, int] = {}
        self.pending_clear_count = 0

    # ---------------------------------------------------------- local ops

    def local_set(self, key: str, value: Any) -> None:
        self.data[key] = value
        self.pending_keys[key] = self.pending_keys.get(key, 0) + 1

    def local_delete(self, key: str) -> bool:
        existed = key in self.data
        self.data.pop(key, None)
        self.pending_keys[key] = self.pending_keys.get(key, 0) + 1
        return existed

    def local_clear(self) -> None:
        self.data.clear()
        self.pending_clear_count += 1

    # --------------------------------------------------------- ack / remote

    def ack(self, op: dict) -> None:
        """Our own op came back sequenced: drop its pending mask and
        RE-APPLY the op at its sequenced position (unless one of our later
        ops on the same key is still in flight and masks it).

        The re-apply is what keeps acked state a pure function of the
        sequenced stream even when the optimistic application was lost —
        e.g. a directory node remotely deleted and recreated while our op
        was in flight took our optimistic value with it, but every OTHER
        replica applies our sequenced op to the replacement node.
        Normally it just idempotently rewrites the value already there.
        """
        if op["op"] == "clear":
            if self.pending_clear_count > 0:
                self.pending_clear_count -= 1
            if self.pending_clear_count == 0:
                # keep optimistic values of still-pending keys (they
                # resequence after this clear), as in apply_remote
                self.data = {k: v for k, v in self.data.items()
                             if k in self.pending_keys}
            return
        key = op["key"]
        if key in self.pending_keys:
            self.pending_keys[key] -= 1
            if self.pending_keys[key] == 0:
                del self.pending_keys[key]
        if key not in self.pending_keys and self.pending_clear_count == 0:
            if op["op"] == "set":
                self.data[key] = op["value"]
            else:
                self.data.pop(key, None)

    def apply_remote(self, op: dict) -> bool:
        """Apply a remote op under the masking rules; True if state changed."""
        if op["op"] == "clear":
            if self.pending_keys:
                # keep optimistic values of in-flight keys: they resequence
                # after this clear
                self.data = {k: v for k, v in self.data.items()
                             if k in self.pending_keys}
            else:
                self.data.clear()
            return True
        key = op["key"]
        if self.pending_clear_count > 0 or key in self.pending_keys:
            return False  # our in-flight op is later in the order: it wins
        if op["op"] == "set":
            self.data[key] = op["value"]
        else:
            self.data.pop(key, None)
        return True

    # ------------------------------------------------------------- readers

    def get(self, key: str, default: Any = None) -> Any:
        return self.data.get(key, default)

    def has(self, key: str) -> bool:
        return key in self.data

    def keys(self) -> Iterator[str]:
        return iter(self.data.keys())
