"""DDS layer: the distributed data structures (all merge logic lives here).

Ref: packages/dds (SURVEY §2.2) — every DDS is a deterministic state
machine over (snapshot, sequenced op stream) implementing the SharedObject
contract (shared-object-base/src/sharedObject.ts): optimistic local apply,
remote apply, own-op ack, reconnect resubmit, snapshot/load.
"""

from .shared_object import SharedObject
from .registry import create_channel, load_channel, register_channel_type
from .string import SharedString
from .map import SharedMap
from .cell import SharedCell, SharedCounter
from .directory import SharedDirectory
from .consensus import ConsensusQueue, ConsensusRegisterCollection
from .ink import Ink, SharedSummaryBlock
from .matrix import SharedMatrix
from .sequence import SharedNumberSequence, SharedObjectSequence
from .intervals import IntervalCollection, SequenceInterval

__all__ = [
    "SharedObject",
    "SharedString",
    "SharedMap",
    "SharedCell",
    "SharedCounter",
    "SharedDirectory",
    "ConsensusQueue",
    "ConsensusRegisterCollection",
    "Ink",
    "SharedSummaryBlock",
    "SharedMatrix",
    "SharedNumberSequence",
    "SharedObjectSequence",
    "IntervalCollection",
    "SequenceInterval",
    "create_channel",
    "load_channel",
    "register_channel_type",
]
