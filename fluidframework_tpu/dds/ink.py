"""Ink: append-only freehand stroke streams.

Ref: packages/dds/ink/src/ink.ts — createStroke starts a stroke with pen
settings; appendPointToStroke adds points.

Convergence design: ACKED state (stroke order, per-stroke point lists) is
built strictly in sequenced order, identically on every replica; local
pending strokes/points are kept in a separate optimistic overlay that
readers see appended at the end and that drains into acked state as acks
arrive. Snapshots persist the acked state only, so a client booting from
a summary and then replaying the pending ops' sequenced forms cannot
double-apply or lose interleaved remote points.

Wire: {"op": "createStroke", "id", "pen"}
| {"op": "stylus", "id", "point": {"x","y","time","pressure"?}}.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

from ..protocol.messages import SequencedDocumentMessage
from .registry import register_channel_type
from .shared_object import SharedObject


@register_channel_type
class Ink(SharedObject):
    channel_type = "ink"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        # acked, sequenced-order state (identical on every replica)
        self._strokes: dict[str, dict] = {}  # id → {"id","pen","points"}
        self._order: list[str] = []
        # optimistic overlay: our in-flight ops
        self._pending_ops: list[dict] = []
        self._uid = itertools.count()

    # ---------------------------------------------------------------- api

    def create_stroke(self, pen: Optional[dict] = None) -> str:
        stroke_id = f"{self.client_id or 'detached'}:{next(self._uid)}"
        op = {"op": "createStroke", "id": stroke_id, "pen": pen or {}}
        self._pending_ops.append(op)
        self.submit_local_message(op)
        return stroke_id

    def append_point(self, stroke_id: str, x: float, y: float, **extra) -> None:
        op = {"op": "stylus", "id": stroke_id, "point": {"x": x, "y": y, **extra}}
        self._pending_ops.append(op)
        self.submit_local_message(op)

    def get_stroke(self, stroke_id: str) -> Optional[dict]:
        """Acked stroke merged with our optimistic pending points."""
        base = self._strokes.get(stroke_id)
        pen = base["pen"] if base else None
        points = list(base["points"]) if base else []
        found = base is not None
        for op in self._pending_ops:
            if op["id"] != stroke_id:
                continue
            if op["op"] == "createStroke":
                found, pen = True, op["pen"]
            else:
                points.append(op["point"])
        if not found:
            return None
        return {"id": stroke_id, "pen": pen, "points": points}

    def get_strokes(self) -> list[dict]:
        ids = list(self._order)
        for op in self._pending_ops:
            if op["op"] == "createStroke" and op["id"] not in self._strokes:
                ids.append(op["id"])
        return [self.get_stroke(i) for i in ids]

    # ----------------------------------------------------------- contract

    def _apply_sequenced(self, op: dict) -> None:
        """Advance the acked state — same code for remote ops and our own
        acks, so every replica builds the identical sequenced history."""
        if op["op"] == "createStroke":
            if op["id"] not in self._strokes:
                self._strokes[op["id"]] = {"id": op["id"], "pen": op["pen"],
                                           "points": []}
                self._order.append(op["id"])
        else:
            stroke = self._strokes.get(op["id"])
            if stroke is not None:
                stroke["points"].append(op["point"])

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._apply_sequenced(self._pending_ops.pop(0))
            return
        self._apply_sequenced(msg.contents)
        self._emit("stylus" if msg.contents["op"] == "stylus" else "createStroke",
                   {"local": False})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        # acked state only: pending ops re-apply via their sequenced forms
        return {"strokes": {k: {"id": v["id"], "pen": v["pen"],
                                "points": list(v["points"])}
                            for k, v in self._strokes.items()},
                "order": list(self._order)}

    def load_core(self, snap: dict) -> None:
        self._strokes = {k: {"id": v["id"], "pen": v["pen"],
                             "points": list(v["points"])}
                         for k, v in snap.get("strokes", {}).items()}
        self._order = list(snap.get("order", []))


@register_channel_type
class SharedSummaryBlock(SharedObject):
    """Summary-only data: no ops, state travels exclusively via snapshots.

    Ref: packages/dds/shared-summary-block — written by the summarizer
    client between summaries; readers see it on load.
    """

    channel_type = "shared-summary-block"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self._data: dict[str, Any] = {}
        self._dirty_at = 0

    def set(self, key: str, value: Any) -> None:
        self._data[key] = value
        # local-only writes never sequence, so the base class's
        # last_changed_seq cannot see them: mark changed past the current
        # STREAM head to disqualify summary handle reuse until a summary
        # whose capture seq passes this point has uploaded the write
        head_fn = getattr(self, "_head_fn", None)
        head = head_fn() if head_fn is not None else self.last_changed_seq
        self._dirty_at = max(self._dirty_at, head + 1)

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def summarize(self, path, parent_capture_seq=None):
        """A write marked at head+1 is covered by any ACKED summary whose
        capture seq reached that point (its upload read current _data);
        until then, force a fresh subtree upload."""
        if parent_capture_seq is not None \
                and self._dirty_at > parent_capture_seq:
            parent_capture_seq = None
        return super().summarize(path, parent_capture_seq)

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        raise RuntimeError("SharedSummaryBlock never sends or receives ops")

    def resubmit_pending(self) -> None:
        pass

    def snapshot(self) -> dict:
        return {"data": dict(self._data)}

    def load_core(self, snap: dict) -> None:
        self._data = dict(snap.get("data", {}))
