"""Interval collections: stable named ranges over a SharedString.

Ref: packages/dds/sequence/src/intervalCollection.ts:669 — named
collections of intervals whose endpoints are merge-tree local references
(they SLIDE when their anchor text is removed, localReference.ts), with
add/delete/change ops flowing through the string's channel. Concurrency:
per-interval LWW with pending-local masking (same rule as the map
kernel); remote endpoint positions anchor at the AUTHOR's perspective —
the merge-tree concurrent-position rule again.

Wire (inside the SharedString channel, tagged to coexist with merge-tree
ops): {"type": "interval", "label", "op": "add"/"delete"/"change",
"id", "start"?, "end"?, "props"?}.
"""

from __future__ import annotations

import itertools
from typing import Optional

from ..mergetree.client import MergeTreeClient
from ..mergetree.perspective import Perspective
from ..mergetree.references import LocalReference, ReferenceType


class SequenceInterval:
    __slots__ = ("id", "start_ref", "end_ref", "properties")

    def __init__(self, interval_id: str, start_ref: LocalReference,
                 end_ref: LocalReference, properties: Optional[dict] = None):
        self.id = interval_id
        self.start_ref = start_ref
        self.end_ref = end_ref
        self.properties = dict(properties or {})


class IntervalCollection:
    """One labeled collection; obtained via
    SharedString.get_interval_collection(label)."""

    def __init__(self, label: str, shared_string):
        self.label = label
        self._string = shared_string
        self._intervals: dict[str, SequenceInterval] = {}
        self._pending_ids: dict[str, int] = {}  # interval id → in-flight ops
        self._uid = itertools.count()
        self._listeners: list = []

    # ---------------------------------------------------------------- api

    def add(self, start: int, end: int, props: Optional[dict] = None) -> SequenceInterval:
        mc: MergeTreeClient = self._string.client
        iid = f"{mc.client_id}:{self.label}:{next(self._uid)}"
        interval = SequenceInterval(
            iid, mc.create_reference(start), mc.create_reference(end), props)
        self._intervals[iid] = interval
        self._mask(iid)
        self._string._submit_interval_op(
            {"type": "interval", "label": self.label, "op": "add", "id": iid,
             "start": start, "end": end, "props": props or {}})
        return interval

    def delete(self, interval_id: str) -> bool:
        existed = interval_id in self._intervals
        self._detach(self._intervals.pop(interval_id, None))
        self._mask(interval_id)
        self._string._submit_interval_op(
            {"type": "interval", "label": self.label, "op": "delete",
             "id": interval_id})
        return existed

    def change(self, interval_id: str, start: Optional[int] = None,
               end: Optional[int] = None, props: Optional[dict] = None) -> None:
        interval = self._intervals.get(interval_id)
        if interval is None:
            raise KeyError(interval_id)
        mc: MergeTreeClient = self._string.client
        if start is not None:
            self._detach_ref(interval.start_ref)
            interval.start_ref = mc.create_reference(start)
        if end is not None:
            self._detach_ref(interval.end_ref)
            interval.end_ref = mc.create_reference(end)
        if props:
            interval.properties.update(props)
        self._mask(interval_id)
        self._string._submit_interval_op(
            {"type": "interval", "label": self.label, "op": "change",
             "id": interval_id, "start": start, "end": end,
             "props": props or {}})

    def get(self, interval_id: str) -> Optional[SequenceInterval]:
        return self._intervals.get(interval_id)

    def position(self, interval: SequenceInterval) -> tuple[int, int]:
        """CURRENT (start, end) positions — endpoints slide with edits."""
        mc: MergeTreeClient = self._string.client
        return (mc.reference_position(interval.start_ref),
                mc.reference_position(interval.end_ref))

    def find_overlapping(self, start: int, end: int) -> list[SequenceInterval]:
        out = []
        for interval in self._intervals.values():
            s, e = self.position(interval)
            if s <= end and start <= e:
                out.append(interval)
        return out

    def __iter__(self):
        return iter(list(self._intervals.values()))

    def __len__(self) -> int:
        return len(self._intervals)

    def on_changed(self, cb) -> None:
        self._listeners.append(cb)

    # ----------------------------------------------------------- op flow

    def _mask(self, interval_id: str) -> None:
        self._pending_ids[interval_id] = self._pending_ids.get(interval_id, 0) + 1

    def _unmask(self, interval_id: str) -> None:
        if interval_id in self._pending_ids:
            self._pending_ids[interval_id] -= 1
            if self._pending_ids[interval_id] == 0:
                del self._pending_ids[interval_id]

    def process(self, op: dict, msg, local: bool) -> None:
        iid = op["id"]
        if local:
            self._unmask(iid)
            self._notify(op, local=True)
            return
        if iid in self._pending_ids:
            return  # our in-flight op on this interval wins (LWW)
        mc: MergeTreeClient = self._string.client
        persp = Perspective(msg.reference_sequence_number, mc.intern(msg.client_id))
        kind = op["op"]
        if kind == "add":
            if iid not in self._intervals:
                self._intervals[iid] = SequenceInterval(
                    iid,
                    mc.create_reference_at(op["start"], persp),
                    mc.create_reference_at(op["end"], persp),
                    op.get("props"),
                )
        elif kind == "delete":
            self._detach(self._intervals.pop(iid, None))
        elif kind == "change":
            interval = self._intervals.get(iid)
            if interval is None:
                return
            if op.get("start") is not None:
                self._detach_ref(interval.start_ref)
                interval.start_ref = mc.create_reference_at(op["start"], persp)
            if op.get("end") is not None:
                self._detach_ref(interval.end_ref)
                interval.end_ref = mc.create_reference_at(op["end"], persp)
            if op.get("props"):
                interval.properties.update(op["props"])
        self._notify(op, local=False)

    def _notify(self, op: dict, local: bool) -> None:
        for cb in self._listeners:
            cb({"op": op["op"], "id": op["id"], "local": local})

    @staticmethod
    def _detach_ref(ref: Optional[LocalReference]) -> None:
        if ref is not None and ref.segment is not None:
            if ref in ref.segment.local_refs:
                ref.segment.local_refs.remove(ref)
            ref.segment = None

    def _detach(self, interval: Optional[SequenceInterval]) -> None:
        if interval is not None:
            self._detach_ref(interval.start_ref)
            self._detach_ref(interval.end_ref)

    # ----------------------------------------------------------- pending

    def pending_ops_rebased(self) -> list[dict]:
        """Regenerate in-flight ops against CURRENT positions for
        reconnect resubmission (endpoints already slid with local state)."""
        # the string tracks which wire ops are pending; this collection
        # only needs to refresh positions for add/change by id
        return []

    # ----------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        out = []
        for interval in self._intervals.values():
            s, e = self.position(interval)
            out.append({"id": interval.id, "start": s, "end": e,
                        "props": interval.properties})
        return {"intervals": out}

    def load(self, snap: dict) -> None:
        mc: MergeTreeClient = self._string.client
        for entry in snap.get("intervals", []):
            self._intervals[entry["id"]] = SequenceInterval(
                entry["id"],
                mc.create_reference(entry["start"]),
                mc.create_reference(entry["end"]),
                entry.get("props"),
            )
