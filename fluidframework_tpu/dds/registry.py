"""Channel type registry (ref: IChannelFactory registrations passed to
data-store factories, datastore-definitions)."""

from __future__ import annotations

_REGISTRY: dict[str, type] = {}


def register_channel_type(cls: type) -> type:
    _REGISTRY[cls.channel_type] = cls
    return cls


def create_channel(channel_type: str, channel_id: str):
    try:
        cls = _REGISTRY[channel_type]
    except KeyError:
        raise KeyError(
            f"unknown channel type {channel_type!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return cls(channel_id)


def load_channel(channel_type: str, channel_id: str, snapshot: dict):
    channel = create_channel(channel_type, channel_id)
    channel.load_core(snapshot)
    return channel
