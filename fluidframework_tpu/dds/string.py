"""SharedString: collaborative text over the merge-tree CRDT.

Ref: packages/dds/sequence/src/sharedString.ts (insertText :152) +
sequence.ts SharedSegmentSequence, which bridges the merge-tree Client to
the channel contract. The heavy lifting — optimistic apply, remote
perspective resolution, ack, reconnect rebase — is MergeTreeClient
(mergetree/client.py, the scalar oracle; the batched TPU path applies the
same sequenced stream server-side via ops/apply.py).
"""

from __future__ import annotations

from typing import Optional

from ..config import DEFAULT as _CFG
from ..mergetree.client import MergeTreeClient
from ..mergetree.ops import op_to_wire
from ..mergetree.references import LocalReference, ReferenceType
from ..protocol.messages import MessageType, SequencedDocumentMessage
from .intervals import IntervalCollection
from .registry import register_channel_type
from .shared_object import SharedObject

DETACHED_ID = "detached"
_SUMMARY_CHUNK_SEGMENTS = _CFG.summary_chunk_segments


@register_channel_type
class SharedString(SharedObject):
    channel_type = "shared-string"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self.client = MergeTreeClient(DETACHED_ID)
        self._interval_collections: dict[str, IntervalCollection] = {}
        self._pending_interval_ops: list[dict] = []

    # ------------------------------------------------------------- editing

    def insert_text(self, pos: int, text: str, props: Optional[dict] = None) -> None:
        op = self.client.insert_text_local(pos, text, props)
        self.submit_local_message(op_to_wire(op))
        self._emit("sequenceDelta", {"op": "insert", "pos": pos, "text": text,
                                     "local": True})

    def insert_marker(self, pos: int, marker: dict, props: Optional[dict] = None) -> None:
        op = self.client.insert_marker_local(pos, marker, props)
        self.submit_local_message(op_to_wire(op))

    def remove_text(self, start: int, end: int) -> None:
        removed = self.get_text()[start:end]
        op = self.client.remove_range_local(start, end)
        self.submit_local_message(op_to_wire(op))
        self._emit("sequenceDelta", {"op": "remove", "start": start, "end": end,
                                     "removedText": removed, "local": True})

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        op = self.client.annotate_range_local(start, end, props)
        self.submit_local_message(op_to_wire(op))

    # ------------------------------------------------------------- queries

    def get_text(self) -> str:
        return self.client.get_text()

    def __len__(self) -> int:
        return self.client.get_length()

    def create_reference(
        self, pos: int, ref_type: int = ReferenceType.SLIDE_ON_REMOVE
    ) -> LocalReference:
        return self.client.create_reference(pos, ref_type)

    def reference_position(self, ref: LocalReference) -> int:
        return self.client.reference_position(ref)

    # ----------------------------------------------------------- intervals

    def get_interval_collection(self, label: str) -> IntervalCollection:
        """Named collection of sliding ranges over this string (ref:
        SharedSegmentSequence.getIntervalCollection, sequence.ts)."""
        if label not in self._interval_collections:
            self._interval_collections[label] = IntervalCollection(label, self)
        return self._interval_collections[label]

    def _submit_interval_op(self, wire: dict) -> None:
        self._pending_interval_ops.append(wire)
        self.submit_local_message(wire)

    # ------------------------------------------------------------ contract

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        contents = msg.contents
        if isinstance(contents, dict) and contents.get("type") == "interval":
            coll = self.get_interval_collection(contents["label"])
            if local:
                self._pending_interval_ops.pop(0)
            coll.process(contents, msg, local)
            # interval msgs still advance the collab window for zamboni
            self.client.tree.current_seq = max(
                self.client.tree.current_seq, msg.sequence_number)
            self.client.tree.update_min_seq(msg.minimum_sequence_number)
            return
        self.client.apply_msg(msg, local)
        if not local and msg.type == MessageType.OPERATION:
            self._emit("sequenceDelta", {"wire": msg.contents, "local": False})

    def resubmit_pending(self) -> None:
        for op in self.client.regenerate_pending_ops():
            self.submit_local_message(op_to_wire(op))
        pending, self._pending_interval_ops = self._pending_interval_ops, []
        for wire in pending:
            # endpoints already slid with local edits: refresh positions
            wire = dict(wire)
            if wire["op"] in ("add", "change"):
                coll = self.get_interval_collection(wire["label"])
                interval = coll.get(wire["id"])
                if interval is None and wire["op"] == "change":
                    continue  # deleted meanwhile: drop the change
                if interval is not None:
                    s, e = coll.position(interval)
                    if wire.get("start") is not None:
                        wire["start"] = s
                    if wire.get("end") is not None:
                        wire["end"] = e
            self._submit_interval_op(wire)

    def on_connect(self, client_id: str) -> None:
        if client_id != self.client.client_id:
            self.client.update_client_id(client_id)

    def snapshot(self) -> dict:
        return {
            "mergetree": self.client.snapshot(),
            "intervals": {
                label: coll.snapshot()
                for label, coll in self._interval_collections.items()
            },
        }

    # segments per summary chunk blob (ref: SnapshotV1 chunked emit,
    # snapshotV1.ts:87 — bounded blob sizes keep incremental uploads and
    # partial loads cheap for giant documents); default from the unified
    # config registry, overridable per instance
    SUMMARY_CHUNK_SEGMENTS = _SUMMARY_CHUNK_SEGMENTS

    def summarize_core(self):
        import json

        from ..protocol.summary import SummaryBlob, SummaryTree

        snap = self.snapshot()
        segments = snap["mergetree"]["segments"]
        n = self.SUMMARY_CHUNK_SEGMENTS
        if len(segments) <= n:
            return SummaryBlob(
                json.dumps(snap, separators=(",", ":")).encode())
        header = {
            "mergetree_header": {
                k: v for k, v in snap["mergetree"].items() if k != "segments"
            },
            "intervals": snap["intervals"],
            "chunks": (len(segments) + n - 1) // n,
        }
        tree = {"header": SummaryBlob(
            json.dumps(header, separators=(",", ":")).encode())}
        for i in range(header["chunks"]):
            tree[f"chunk_{i}"] = SummaryBlob(json.dumps(
                segments[i * n:(i + 1) * n], separators=(",", ":")).encode())
        return SummaryTree(tree=tree)

    def load_core(self, snap: dict) -> None:
        if "header" in snap and "mergetree" not in snap:
            # chunked summary form (materialized tree): reassemble
            header = snap["header"]
            segments = []
            for i in range(header["chunks"]):
                segments.extend(snap[f"chunk_{i}"])
            snap = {
                "mergetree": dict(header["mergetree_header"],
                                  segments=segments),
                "intervals": header["intervals"],
            }
        if "mergetree" not in snap:  # pre-intervals snapshot layout
            self.client = MergeTreeClient.load(DETACHED_ID, snap)
            return
        self.client = MergeTreeClient.load(DETACHED_ID, snap["mergetree"])
        for label, coll_snap in snap.get("intervals", {}).items():
            self.get_interval_collection(label).load(coll_snap)
