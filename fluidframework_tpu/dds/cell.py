"""SharedCell: a single LWW value.

Ref: packages/dds/cell/src/cell.ts — set/delete with pending-local
masking (same optimistic rule as the map kernel, for one slot).
Wire ops: {"op": "set", "value"} | {"op": "delete"}.
"""

from __future__ import annotations

from typing import Any

from ..protocol.messages import SequencedDocumentMessage
from .registry import register_channel_type
from .shared_object import SharedObject

_EMPTY = object()


@register_channel_type
class SharedCell(SharedObject):
    channel_type = "shared-cell"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self._value: Any = _EMPTY
        self._pending_ops: list[dict] = []

    def set(self, value: Any) -> None:
        self._value = value
        op = {"op": "set", "value": value}
        self._pending_ops.append(op)
        self.submit_local_message(op)
        self._emit("valueChanged", {"local": True})

    def delete(self) -> None:
        self._value = _EMPTY
        op = {"op": "delete"}
        self._pending_ops.append(op)
        self.submit_local_message(op)
        self._emit("delete", {"local": True})

    def get(self, default: Any = None) -> Any:
        return default if self._value is _EMPTY else self._value

    @property
    def empty(self) -> bool:
        return self._value is _EMPTY

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._pending_ops.pop(0)
            return
        if self._pending_ops:
            return  # our in-flight write is later in the order: it wins
        op = msg.contents
        if op["op"] == "set":
            self._value = op["value"]
            self._emit("valueChanged", {"local": False})
        else:
            self._value = _EMPTY
            self._emit("delete", {"local": False})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        return {"empty": self._value is _EMPTY,
                "value": None if self._value is _EMPTY else self._value}

    def load_core(self, snap: dict) -> None:
        self._value = _EMPTY if snap.get("empty", True) else snap["value"]


@register_channel_type
class SharedCounter(SharedObject):
    """Commutative increment counter (ref: packages/dds/counter/src/counter.ts).

    Increments commute, so remote ops always apply and local ops apply
    optimistically; no masking needed. Wire: {"op": "increment", "delta"}.
    """

    channel_type = "shared-counter"

    def __init__(self, channel_id: str):
        super().__init__(channel_id)
        self.value: int = 0
        self._pending_ops: list[dict] = []

    def increment(self, delta: int = 1) -> None:
        if not isinstance(delta, int):
            raise TypeError("counter delta must be an integer")
        self.value += delta
        op = {"op": "increment", "delta": delta}
        self._pending_ops.append(op)
        self.submit_local_message(op)
        self._emit("incremented", {"delta": delta, "value": self.value, "local": True})

    def process_core(self, msg: SequencedDocumentMessage, local: bool) -> None:
        if local:
            self._pending_ops.pop(0)  # already applied optimistically
            return
        delta = msg.contents["delta"]
        self.value += delta
        self._emit("incremented", {"delta": delta, "value": self.value, "local": False})

    def resubmit_pending(self) -> None:
        for op in self._pending_ops:
            self.submit_local_message(op)

    def snapshot(self) -> dict:
        # acked value only: pending increments replay on top after load
        acked = self.value - sum(op["delta"] for op in self._pending_ops)
        return {"value": acked}

    def load_core(self, snap: dict) -> None:
        self.value = snap.get("value", 0)
