"""Last-edited tracker: who touched the document last, convergent.

Ref: packages/framework/last-edited-experimental — watches the sequenced
op stream and records (clientId, user detail, timestamp, seq) of the
last CONTENT edit into shared state every replica agrees on (system
messages and noops don't count as edits).
"""

from __future__ import annotations

from typing import Optional

from ..protocol.messages import MessageType, SequencedDocumentMessage

LAST_EDITED_KEY = "lastEdited"


class LastEditedTracker:
    def __init__(self, container, ds_id: str = "default",
                 channel_id: str = "last-edited"):
        self.container = container
        ds = container.runtime.get_data_store(ds_id)
        if channel_id in ds.channels:
            self._map = ds.get_channel(channel_id)
        else:
            self._map = ds.create_channel(channel_id, "shared-map")
        container.add_message_observer(self._observe)

    @property
    def last_edited(self) -> Optional[dict]:
        return self._map.get(LAST_EDITED_KEY)

    def _observe(self, msg: SequencedDocumentMessage) -> None:
        if msg.type is not MessageType.OPERATION or msg.client_id is None:
            return
        env = msg.contents
        if not isinstance(env, dict) or env.get("kind") != "chanop":
            return  # only content edits count
        # every replica observes the same stream, but only ONE should
        # write the record (or the tracker's own writes would cascade);
        # the oldest member writes — deterministic on every replica
        members = self.container.quorum.members
        if not members:
            return
        writer = min(members.items(), key=lambda kv: kv[1].sequence_number)[0]
        if writer != self.container.client_id:
            return
        if env["contents"].get("address") == self._map.id:
            return  # our own record write: not an edit
        member = members.get(msg.client_id)
        self._map.set(LAST_EDITED_KEY, {
            "clientId": msg.client_id,
            "user": member.client.user_id if member is not None else None,
            "sequenceNumber": msg.sequence_number,
            "timestamp": msg.timestamp,
        })
