"""Undo/redo: revertible capture over DDS events.

Ref: packages/framework/undo-redo — UndoRedoStackManager
(undoRedoStackManager.ts:80) groups local DDS changes into operations and
replays inverses; handlers exist for SharedMap value changes
(mapHandler) and sequence deltas (sequenceHandler.ts:23). Undo positions
for text use sliding local references, so intervening remote edits move
the undo target instead of corrupting it.

Known simplification vs the reference: text revertibles anchor RANGES via
sliding references, where the reference tracks the affected SEGMENTS
(merge-tree TrackingGroups maintained through splits). Consequence: undo
chains whose ranges overlap earlier undone/redone ranges are positional
approximations — convergent across replicas (they emit ordinary ops) but
possibly differing from segment-exact undo. Segment tracking groups are
the planned upgrade path.
"""

from __future__ import annotations

from typing import Optional

from ..dds.map import SharedMap
from ..dds.string import SharedString


class _MapRevertible:
    def __init__(self, m: SharedMap, key: str, prev_value, prev_existed: bool):
        self.map = m
        self.key = key
        self.prev_value = prev_value
        self.prev_existed = prev_existed

    def revert(self) -> None:
        if self.prev_existed:
            self.map.set(self.key, self.prev_value)
        else:
            self.map.delete(self.key)


class _InsertRevertible:
    """Undo an insert: remove the (possibly slid) inserted range.

    Anchors on the FIRST and LAST inserted characters (a reference past
    doc end would detach); remote text inserted strictly inside the range
    is removed with it — the reference's segment-tracking handlers are
    finer-grained, this matches its simple sequence handler.
    """

    def __init__(self, s: SharedString, pos: int, length: int):
        self.string = s
        self.start_ref = s.create_reference(pos)
        self.last_ref = s.create_reference(pos + length - 1)

    def revert(self) -> None:
        n = len(self.string)
        start = min(self.string.reference_position(self.start_ref), n)
        last = min(self.string.reference_position(self.last_ref), n - 1)
        if last >= start:
            self.string.remove_text(start, last + 1)


class _RemoveRevertible:
    """Undo a remove: reinsert the text at the (possibly slid) position.

    Anchors on the character BEFORE the removed range — a forward anchor
    would detach whenever the removal reached the end of the document
    (the revertible is built after the removal applied).
    """

    def __init__(self, s: SharedString, pos: int, text: str):
        self.string = s
        self.before_ref = s.create_reference(pos - 1) if pos > 0 else None
        self.text = text

    def revert(self) -> None:
        pos = (0 if self.before_ref is None
               else self.string.reference_position(self.before_ref) + 1)
        # the anchor may sit on a tombstone whose base position is past
        # the live end (e.g. everything after it was undone too)
        self.string.insert_text(min(pos, len(self.string)), self.text)


class UndoRedoStackManager:
    """Attach DDSes; local changes group into undoable operations.

    ``close_current_operation()`` ends a group (one undo step). Reverting
    re-enters the DDSes, and those captures land on the opposite stack; a
    fresh user edit clears the redo stack (standard undo semantics).
    """

    def __init__(self):
        self._undo: list[list] = []
        self._redo: list[list] = []
        self._open: Optional[list] = None
        self._capture_into: Optional[list] = None  # revert-in-progress sink

    # ------------------------------------------------------------ attach

    def attach_map(self, m: SharedMap) -> None:
        m.on("valueChanged", lambda e: self._on_map_event(m, e))

    def attach_string(self, s: SharedString) -> None:
        s.on("sequenceDelta", lambda e: self._on_string_event(s, e))

    def attach_matrix(self, m) -> None:
        """Cell sets and row/col INSERTS are undoable; removals are not
        (purged cells cannot be revived — see _VectorInsertRevertible)."""
        m.on("cellChanged", lambda e: self._on_matrix_cell(m, e))
        m.on("shapeChanged", lambda e: self._on_matrix_shape(m, e))

    def _on_matrix_cell(self, m, event: dict) -> None:
        if event.get("local") and "rowHandle" in event:
            self._capture(_CellRevertible(
                m, event["rowHandle"], event["colHandle"],
                event.get("previousValue")))

    def _on_matrix_shape(self, m, event: dict) -> None:
        if not event.get("local"):
            return
        op = event.get("op", "")
        if op in ("insertRows", "insertCols") and event.get("handles"):
            self._capture(_VectorInsertRevertible(
                m, op == "insertRows", event["handles"]))

    def _on_map_event(self, m: SharedMap, event: dict) -> None:
        if event.get("local"):
            self._capture(_MapRevertible(
                m, event["key"], event.get("previousValue"),
                event.get("previousExisted", False)))

    def _on_string_event(self, s: SharedString, event: dict) -> None:
        if not event.get("local"):
            return
        if event["op"] == "insert":
            self._capture(_InsertRevertible(s, event["pos"], len(event["text"])))
        elif event["op"] == "remove":
            self._capture(_RemoveRevertible(
                s, event["start"], event.get("removedText", "")))

    # ------------------------------------------------------------- stacks

    def _capture(self, revertible) -> None:
        if self._capture_into is not None:
            self._capture_into.append(revertible)
            return
        self._redo.clear()  # a fresh edit invalidates the redo future
        if self._open is None:
            self._open = []
            self._undo.append(self._open)
        self._open.append(revertible)

    def close_current_operation(self) -> None:
        self._open = None

    @property
    def can_undo(self) -> bool:
        return bool(self._undo)

    @property
    def can_redo(self) -> bool:
        return bool(self._redo)

    def _revert_group(self, group: list, into: list) -> None:
        self._capture_into = into
        try:
            for revertible in reversed(group):
                revertible.revert()
        finally:
            self._capture_into = None

    def undo(self) -> bool:
        if not self._undo:
            return False
        self.close_current_operation()
        group = self._undo.pop()
        inverse: list = []
        self._revert_group(group, inverse)
        self._redo.append(inverse)
        return True

    def redo(self) -> bool:
        if not self._redo:
            return False
        group = self._redo.pop()
        inverse: list = []
        self._revert_group(group, inverse)
        self._undo.append(inverse)
        return True


class _CellRevertible:
    """Undo a setCell by rewriting the previous LWW value, anchored on
    the cell's STABLE handles — concurrent remote row/col inserts shift
    positions, so a position-addressed revert would clobber the wrong
    cell (ref: matrix undoprovider.ts tracks handles for the same
    reason)."""

    def __init__(self, m, row_handle: int, col_handle: int, prev_value):
        self.m, self.rh, self.ch, self.prev = m, row_handle, col_handle, \
            prev_value

    def revert(self) -> None:
        at = self.m.position_of_handles(self.rh, self.ch)
        if at is None:
            return  # the cell's row/col was removed meanwhile: no-op
        self.m.set_cell(at[0], at[1], self.prev)


class _VectorInsertRevertible:
    """Undo an insertRows/insertCols by removing the inserted span,
    resolved through the inserted HANDLES at revert time (the span may
    have moved or been interleaved by remote inserts). Row/col REMOVALS
    are not undoable here: the cells of removed axes are purged with
    their handles, so there is no content to revive — attach_matrix
    documents the scope."""

    def __init__(self, m, is_rows: bool, handles: list):
        self.m, self.is_rows, self.handles = m, is_rows, list(handles)

    def revert(self) -> None:
        vec = self.m.rows if self.is_rows else self.m.cols
        positions = sorted(
            (p for p in (vec.position_of_handle(h) for h in self.handles)
             if p is not None),
            reverse=True)  # highest first: removals don't shift the rest
        for p in positions:
            if self.is_rows:
                self.m.remove_rows(p, 1)
            else:
                self.m.remove_cols(p, 1)
