"""Request routing: URL-path access into a container's object graph.

Ref: packages/framework/request-handler + RequestParser
(runtime-utils) — containers expose their data stores/channels through
composable path handlers ("/default/text" → that channel), the same
surface hosts use to wire views. Handlers compose first-match-wins.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

Handler = Callable[[list[str], Any], Optional[Any]]


def parse_request(url: str) -> list[str]:
    return [p for p in url.split("/") if p]


class RequestRouter:
    """First-match-wins handler chain (ref: buildRuntimeRequestHandler)."""

    def __init__(self, container):
        self.container = container
        self._handlers: list[Handler] = [self._data_store_handler]

    def add_handler(self, handler: Handler) -> "RequestRouter":
        # custom handlers run BEFORE the default object-graph walk
        self._handlers.insert(0, handler)
        return self

    def request(self, url: str) -> Any:
        parts = parse_request(url)
        for handler in self._handlers:
            result = handler(parts, self.container)
            if result is not None:
                return result
        raise KeyError(f"no handler resolved {url!r}")

    @staticmethod
    def _data_store_handler(parts: list[str], container) -> Optional[Any]:
        """/<dataStore>[/<channel>] → runtime objects."""
        if not parts:
            return container.runtime
        ds = container.runtime.data_stores.get(parts[0])
        if ds is None:
            return None
        if len(parts) == 1:
            return ds
        if len(parts) == 2 and parts[1] in ds.channels:
            return ds.get_channel(parts[1])
        return None
