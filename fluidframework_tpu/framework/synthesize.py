"""Dependency synthesis: typed provider registry for data objects.

Ref: packages/framework/synthesize — a DI container mapping provider
symbols to instances/factories, with optional vs required synthesis
(dependencyContainer.ts). Data objects declare what they consume
(logger, config, services) and hosts register providers once; parent
scopes chain, so a host-level container can back many containers.
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class DependencyContainer:
    def __init__(self, parent: Optional["DependencyContainer"] = None):
        self._parent = parent
        self._providers: dict[str, Any] = {}
        self._factories: dict[str, Callable[[], Any]] = {}

    def register(self, symbol: str, provider: Any) -> "DependencyContainer":
        self._providers[symbol] = provider
        return self

    def register_factory(self, symbol: str,
                         factory: Callable[[], Any]) -> "DependencyContainer":
        """Lazily constructed, then cached (singleton per container)."""
        self._factories[symbol] = factory
        return self

    def has(self, symbol: str) -> bool:
        return (symbol in self._providers or symbol in self._factories
                or (self._parent is not None and self._parent.has(symbol)))

    def resolve(self, symbol: str) -> Any:
        if symbol in self._providers:
            return self._providers[symbol]
        if symbol in self._factories:
            value = self._factories.pop(symbol)()
            self._providers[symbol] = value
            return value
        if self._parent is not None:
            return self._parent.resolve(symbol)
        raise KeyError(f"no provider for {symbol!r}")

    def synthesize(self, required: tuple = (), optional: tuple = ()) -> dict:
        """Build the dependency dict a data object consumes: required
        symbols must resolve (KeyError otherwise), optional ones fill
        with None (ref: synthesize required/optional split)."""
        out = {symbol: self.resolve(symbol) for symbol in required}
        for symbol in optional:
            out[symbol] = self.resolve(symbol) if self.has(symbol) else None
        return out
