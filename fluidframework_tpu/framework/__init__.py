"""Framework/API layer: developer-facing sugar over runtime + DDS.

Ref: packages/framework (SURVEY §2.6) — aqueduct DataObject/-Factory,
undo-redo stack managers, DDS interceptions, request routing.
"""

from .data_object import DataObject, DataObjectFactory, default_data_object
from .undo_redo import UndoRedoStackManager
from .interceptions import intercepted_map, intercepted_string

__all__ = [
    "DataObject",
    "DataObjectFactory",
    "default_data_object",
    "UndoRedoStackManager",
    "intercepted_map",
    "intercepted_string",
]
