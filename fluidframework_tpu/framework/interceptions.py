"""DDS interceptions: wrap a DDS so every local op passes a callback.

Ref: packages/framework/dds-interceptions — factory wrappers that
intercept DDS write APIs (e.g. to stamp attribution properties on every
string edit or map set) before the op is submitted
(createSharedStringWithInterception etc.).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..dds.map import SharedMap
from ..dds.string import SharedString


class intercepted_string:
    """Proxy over a SharedString whose writes pass through an interceptor
    that may amend properties (attribution stamping)."""

    def __init__(
        self,
        string: SharedString,
        props_interceptor: Callable[[Optional[dict]], dict],
    ):
        self._s = string
        self._intercept = props_interceptor

    def insert_text(self, pos: int, text: str, props: Optional[dict] = None) -> None:
        self._s.insert_text(pos, text, self._intercept(props))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self._s.annotate_range(start, end, self._intercept(props))

    def __getattr__(self, name: str) -> Any:
        return getattr(self._s, name)


class intercepted_map:
    """Proxy over a SharedMap whose set() passes through a value
    interceptor."""

    def __init__(
        self,
        m: SharedMap,
        set_interceptor: Callable[[str, Any], Any],
    ):
        self._m = m
        self._intercept = set_interceptor

    def set(self, key: str, value: Any) -> None:
        self._m.set(key, self._intercept(key, value))

    def __getattr__(self, name: str) -> Any:
        return getattr(self._m, name)
