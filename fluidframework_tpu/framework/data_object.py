"""DataObject: the aqueduct-style developer entry point.

Ref: packages/framework/aqueduct — PureDataObject/DataObject own a root
SharedDirectory and an initialization lifecycle
(data-objects/dataObject.ts:32: initializingFirstTime /
initializingFromExisting / hasInitialized), created through a
DataObjectFactory and a container-runtime factory with a default store
(containerRuntimeFactoryWithDefaultDataStore.ts:24).
"""

from __future__ import annotations

from typing import Optional, Type

from ..runtime.datastore import DataStoreRuntime

ROOT_CHANNEL_ID = "root"


class DataObject:
    """Subclass and override the lifecycle hooks; access state via
    ``self.root`` (a SharedDirectory) or ``create_channel`` helpers."""

    def __init__(self, runtime: DataStoreRuntime):
        self.runtime = runtime

    # ------------------------------------------------------------ lifecycle

    def initializing_first_time(self) -> None:
        """Called exactly once, on the replica that creates the object."""

    def initializing_from_existing(self) -> None:
        """Called when loading an already-created object."""

    def has_initialized(self) -> None:
        """Called on every replica after either initialization path."""

    # -------------------------------------------------------------- state

    @property
    def root(self):
        return self.runtime.get_channel(ROOT_CHANNEL_ID)

    def create_channel(self, channel_id: str, channel_type: str):
        return self.runtime.create_channel(channel_id, channel_type)

    def get_channel(self, channel_id: str):
        return self.runtime.get_channel(channel_id)


class DataObjectFactory:
    """Creates/loads a DataObject type against a container runtime
    (ref: aqueduct DataObjectFactory)."""

    def __init__(self, pkg: str, cls: Type[DataObject]):
        self.pkg = pkg
        self.cls = cls

    def create(self, container_runtime, ds_id: str) -> DataObject:
        ds = container_runtime.create_data_store(ds_id, pkg=self.pkg)
        ds.create_channel(ROOT_CHANNEL_ID, "shared-directory")
        obj = self.cls(ds)
        obj.initializing_first_time()
        obj.has_initialized()
        return obj

    def load(self, container_runtime, ds_id: str) -> DataObject:
        obj = self.cls(container_runtime.get_data_store(ds_id))
        obj.initializing_from_existing()
        obj.has_initialized()
        return obj

    def create_or_load(self, container, ds_id: str = "default") -> DataObject:
        """The ContainerRuntimeFactoryWithDefaultDataStore pattern: the
        container's creator makes the default object, everyone else loads
        it (ref: containerRuntimeFactoryWithDefaultDataStore.ts:24)."""
        runtime = container.runtime
        if ds_id in runtime.data_stores:
            return self.load(runtime, ds_id)
        if container.existing:
            raise KeyError(
                f"document exists but has no data store {ds_id!r}")
        return self.create(runtime, ds_id)


def default_data_object(container, factory: Optional[DataObjectFactory] = None):
    """Resolve a container's default data object with the stock DataObject
    class unless a factory is supplied."""
    factory = factory or DataObjectFactory("default", DataObject)
    return factory.create_or_load(container)
