"""Network driver: client stack ⇄ NetworkFrontEnd over TCP.

Ref: packages/drivers/routerlicious-driver (documentService.ts:22 wires
stream + delta storage + snapshot storage to the service endpoints) and
driver-base/src/documentDeltaConnection.ts:53 (the socket client emitting
connect_document/submitOp and listening op/nack/signal). Same wire format
as service/front_end.py: 4-byte length-prefixed JSON frames.

Concurrency: a daemon reader thread dispatches pushed events (op, nack,
signal) into the client callbacks under ``self.lock``; submits take the
same lock, so the client replica never interleaves a local submit with a
remote dispatch. Request/response calls (deltas, storage) ride the same
connection, matched by request id.

Ingress coalescing: binary submits pass through an adaptive window —
ops submitted within the window (or while a send is in flight) merge
into ONE binwire boxcar frame, so a hot client pays one sendall + one
server-side parse per wave instead of per op. The window self-tunes
from observed ack latency (EWMA over own-op round trips): an idle or
fast client sees window 0 and keeps the old inline sub-millisecond
submit; only a client whose acks already take milliseconds trades a
fraction of that latency for frame amortization. Set
``conn.coalesce_window`` to force a fixed window (tests, soak).
"""

from __future__ import annotations

import itertools
import json
import random
import socket
import threading
import time
from typing import Any, Callable, Optional

from ..obs import tier_counters
from ..utils.affinity import blocking
from ..protocol import binwire
from ..protocol.messages import MessageType, TraceHop
from ..protocol.serialization import message_from_dict, message_to_dict
from ..utils.telemetry import HOP_SHED, HOP_SUBMIT, Counters
from .definitions import (
    DocumentDeltaConnection,
    DocumentDeltaStorage,
    DocumentService,
    DocumentServiceFactory,
    DocumentStorage,
)

#: chaos seam (fluidframework_tpu/chaos): when set, transports constructed
#: while installed route outbound frames through the hook for drop /
#: duplicate / delay / reorder / mid-frame-truncate faults. Captured per
#: transport at construction so arming cannot race live connections.
FRAME_FAULT_HOOK = None

#: binwire boxcars carry a u16 op count; chunk well below it so the
#: string pool of a pathological wave cannot overflow either
_MAX_BOXCAR_OPS = 60000

#: adaptive-window tuning: below this observed ack latency the client
#: counts as fast/idle and submits inline (window 0); above it the
#: window is ack_ewma/8 capped here — always a small fraction of the
#: latency the client is already paying
_COALESCE_MIN_ACK_S = 0.005
_COALESCE_MAX_WINDOW_S = 0.004


class LogTruncatedError(RuntimeError):
    """The requested backfill range reaches below the server's retention
    base: the prefix is summary-covered and gone from the op log — catch
    up from the latest summary instead of retrying a range that can
    never fill. (The driver's own class, mirroring the service-side
    exception: drivers never import service modules.)"""

    def __init__(self, base: int, snapshot_seq=None):
        super().__init__(
            f"op log truncated below seq {base}: reload from the latest "
            "acked summary")
        self.base = base
        # the snapshot-backed base the server advertised: an acked
        # summary at this seq boots past the hole (always ≥ base)
        self.snapshot_seq = snapshot_seq


class BootPendingError(RuntimeError):
    """The doc's first route landed during a cold-start boot storm and
    the core's rehydration executor parked it: retry after the hinted
    backoff (the connect-side twin of the admission shed lane). The
    driver's connect loop absorbs this transparently."""

    def __init__(self, retry_after: int):
        super().__init__(
            f"doc boot parked by cold-start admission; retry in "
            f"{retry_after}ms")
        self.retry_after_ms = retry_after


#: Give a parked connect this long to win a boot slot before erroring
#: out — covers a 10k-doc storm draining through a bounded executor.
_BOOT_RETRY_MAX_S = 60.0


class _Transport:
    """One framed TCP connection + reader thread + rid-matched requests."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        # op frames are small and latency-bound: Nagle coalescing adds
        # tens of ms per hop under load
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.timeout = timeout
        self.lock = threading.RLock()  # serializes dispatch vs. submit
        self._wlock = threading.Lock()
        self._rid = itertools.count(1)
        self._pending: dict[int, dict] = {}  # rid → reply frame
        # rid → decoded backfill messages from FT_COLS_DELTAS pushes; the
        # pushes and the terminal JSON reply ride the same wire and the
        # same reader thread, so by the time the reply is matched every
        # block for that rid has landed here
        self._blocks: dict[int, list] = {}
        # chunk_hash → raw snapcols chunk bytes from FT_COLS_SNAP pushes
        # (content-addressed, so the hash — not a rid — is the key; same
        # same-thread ordering guarantee as _blocks)
        self._snap_chunks: dict[str, bytes] = {}
        # rid → decoded history commits from FT_HISTORY pushes (the
        # history_log listing; same same-thread ordering as _blocks)
        self._history: dict[int, list] = {}
        self._pending_cv = threading.Condition()
        self._push_handlers: dict[str, Callable[[dict], None]] = {}
        # binary ops batches bypass the dict layer entirely
        self.on_binary_ops: Optional[Callable[[list], None]] = None
        # coalesced FT_PRESENCE batches (the ephemeral signal lane)
        self.on_presence: Optional[Callable[[list], None]] = None
        self.on_disconnect: Optional[Callable[[str], None]] = None
        self._closed = False
        self._fault = FRAME_FAULT_HOOK
        self._held: list[bytes] = []  # delayed frames awaiting overtake
        self._idle_windows = 0  # consecutive recv-timeout windows
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="fluid-net-reader")
        self._reader.start()

    # ------------------------------------------------------------- sending

    def send(self, frame: dict) -> None:
        self.send_body(json.dumps(frame, separators=(",", ":")).encode(),
                       kind=frame.get("t"))

    def send_body(self, body: bytes, kind: Optional[str] = None) -> None:
        """Send a length-prefix-framed body (JSON or binwire)."""
        if self._fault is not None:
            self._send_with_faults(body, kind)
            return
        with self._wlock:
            self.sock.sendall(len(body).to_bytes(4, "big") + body)

    def _send_with_faults(self, body: bytes, kind: Optional[str]) -> None:
        """Chaos-armed send path: consult the fault plane per frame.

        - ``drop``: the frame vanishes (a lost datagram-equivalent; TCP
          never does this, but a dying proxy/LB absolutely does).
        - ``dup``: the frame arrives twice (an at-least-once relay).
        - ``delay``/``reorder``: the frame is held and flushed AFTER the
          next frame — a later frame overtakes it on the wire.
        - ``truncate``: half the body is sent under a full-length header,
          then the connection dies mid-frame — the peer's framed read
          blocks on bytes that never come and sees the close.
        """
        directive = self._fault("net.send", kind=kind, size=len(body))
        if directive in ("delay", "reorder"):
            self._held.append(body)
            return
        if directive == "truncate":
            with self._wlock:
                try:
                    self.sock.sendall(
                        len(body).to_bytes(4, "big")
                        + body[:len(body) // 2])
                except OSError:
                    pass
            self.close()
            return
        if directive == "drop":
            frames = []
        elif directive == "dup":
            frames = [body, body]
        else:
            frames = [body]
        with self._wlock:
            # held (delayed) frames flush AFTER this one: the overtake
            # IS the reorder
            frames += self._held
            self._held = []
            for b in frames:
                self.sock.sendall(len(b).to_bytes(4, "big") + b)

    def request(self, frame: dict) -> dict:
        """Send a frame with a request id; block for the matching reply."""
        return self.request_rid(frame)[1]

    @blocking("parks the calling thread on a condition variable until the reply frame or timeout")
    def request_rid(self, frame: dict) -> tuple[int, dict]:
        """Like :meth:`request` but also returns the rid, so callers can
        collect rid-tagged binary pushes (:meth:`take_blocks`)."""
        rid = next(self._rid)
        self.send(dict(frame, rid=rid))
        with self._pending_cv:
            ok = self._pending_cv.wait_for(
                lambda: rid in self._pending or self._closed,
                timeout=self.timeout)
            if not ok or rid not in self._pending:
                self._blocks.pop(rid, None)
                self._history.pop(rid, None)
                raise ConnectionError(
                    f"no reply for {frame.get('t')} (connection "
                    f"{'closed' if self._closed else 'timed out'})")
            reply = self._pending.pop(rid)
        if reply.get("t") == "error":
            self._blocks.pop(rid, None)
            self._history.pop(rid, None)
            if reply.get("code") == "log_truncated":
                raise LogTruncatedError(int(reply.get("base", 0)),
                                        snapshot_seq=reply.get("snapshotSeq"))
            if reply.get("code") == "boot_pending":
                raise BootPendingError(int(reply.get("retryAfterMs", 50)))
            raise RuntimeError(f"server error: {reply.get('message')}")
        return rid, reply

    def take_blocks(self, rid: int) -> list:
        """Claim the decoded backfill messages pushed for ``rid``."""
        return self._blocks.pop(rid, [])

    def take_snap_chunks(self) -> dict:
        """Claim the snapshot chunks pushed ahead of the last
        get_snapshot_cols terminal reply."""
        chunks, self._snap_chunks = self._snap_chunks, {}
        return chunks

    def take_history(self, rid: int) -> list:
        """Claim the decoded history commits pushed for ``rid``."""
        return self._history.pop(rid, [])

    # ------------------------------------------------------------ receiving

    def _recv_exactly(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except TimeoutError:
                # the connect timeout stays on the socket, but a PUSH
                # connection is legitimately silent for long stretches
                # (an idle doc; a paused/backlogged pipeline). Treating
                # the timeout as EOF killed the reader thread after 30 s
                # of server silence — the client then ignored every
                # later push (acks, ops) while looking connected: the
                # round-4 full-composition failure. Idle is not death —
                # but a VANISHED peer (powered off, partitioned,
                # SIGSTOPped core) sends no FIN either, so idle windows
                # escalate: probe with a ping (every terminator — core,
                # python gateway, native gateway — answers pong/error,
                # and ANY bytes prove liveness); two unanswered probe
                # windows in a row mean the peer is gone and the
                # disconnect path (auto-reconnect/failover) must run.
                if self._closed:
                    return None
                self._idle_windows += 1
                if self._idle_windows > 2:
                    return None
                try:
                    self.send({"t": "ping"})
                except OSError:
                    return None
                continue
            except (OSError, ValueError):
                return None
            if not chunk:
                return None
            self._idle_windows = 0
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        reason = "connection closed by server"
        try:
            while not self._closed:
                header = self._recv_exactly(4)
                if header is None:
                    break
                body = self._recv_exactly(int.from_bytes(header, "big"))
                if body is None:
                    break
                if binwire.is_binary(body):
                    if body[1] == binwire.FT_COLS_SNAP:
                        # snapshot chunk push: stage raw bytes by content
                        # hash for the booting requester (decode happens
                        # on the boot thread, not the reader)
                        _, h, chunk = binwire.read_snap_chunk(body)
                        self._snap_chunks[h] = chunk
                        continue
                    if body[1] == binwire.FT_COLS_DELTAS:
                        # rid-tagged backfill block: decode the column
                        # section client-side and stage it for the
                        # requester (the terminal JSON reply arrives
                        # after, on this same thread)
                        brid, msgs = binwire.read_cols_deltas(body)
                        self._blocks.setdefault(brid, []).extend(msgs)
                        continue
                    if body[1] == binwire.FT_HISTORY:
                        # rid-tagged history commit (the history_log
                        # listing): decode through the refgraph codec
                        # and stage for the requester
                        hrid, commit = binwire.decode_history_commit(body)
                        self._history.setdefault(hrid, []).append(commit)
                        continue
                    if body[1] == binwire.FT_PRESENCE:
                        # coalesced presence batch: one frame, N signals
                        # (the ephemeral lane — never sequenced)
                        cb = self.on_presence
                        if cb is not None:
                            sigs = binwire.decode_presence(body)
                            with self.lock:
                                cb(sigs)
                        continue
                    cb = self.on_binary_ops
                    if cb is not None:
                        _, msgs = binwire.decode_ops(body)
                        with self.lock:
                            cb(msgs)
                    continue
                frame = json.loads(body.decode())
                rid = frame.get("rid")
                if rid is not None:
                    with self._pending_cv:
                        self._pending[rid] = frame
                        self._pending_cv.notify_all()
                else:
                    handler = self._push_handlers.get(frame.get("t"))
                    if handler is not None:
                        with self.lock:
                            handler(frame)
        except Exception as e:  # a raising push handler must not leave
            reason = f"reader failed: {e}"  # requesters hanging silently
        finally:
            # wake any blocked requester, then notify disconnect
            with self._pending_cv:
                self._closed = True
                self._pending_cv.notify_all()
            if self.on_disconnect is not None:
                self.on_disconnect(reason)

    def on_push(self, t: str, handler: Callable[[dict], None]) -> None:
        self._push_handlers[t] = handler

    def close(self) -> None:
        # under the cv: a requester blocked in wait_for must observe the
        # flag and wake now, not when the reader thread happens to die
        with self._pending_cv:
            self._closed = True
            self._pending_cv.notify_all()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class NetworkDeltaConnection(DocumentDeltaConnection):
    """The live stream over the shared transport. Events arriving before a
    callback is attached are buffered and flushed on attach (same contract
    as the in-proc ServerConnection)."""

    def __init__(self, transport: _Transport, tenant_id: str,
                 document_id: str, details: Any = None,
                 token: Optional[str] = None, binary: bool = True,
                 cache=None, counters: Optional[Counters] = None,
                 readonly: bool = False):
        self._t = transport
        self.lock = transport.lock
        self._binary = binary
        self.readonly = readonly
        self._tenant = tenant_id
        self._doc = document_id
        self._cache = cache
        self.counters = (counters if counters is not None
                         else tier_counters("driver"))
        #: 1-in-N submit tracing (0 = disarmed): every Nth boxcar gets a
        #: client/submit hop — columnar frames via the 9-byte hoptail
        #: append, rec frames via a TraceHop on the last op — so arming
        #: costs one counter increment per flush, not per op
        self.trace_sample_n = 0
        self._trace_seq = 0
        self._handlers: dict[str, Optional[Callable]] = {
            "op": None, "nack": None, "signal": None}
        self._buffers: dict[str, list] = {"op": [], "nack": [], "signal": []}
        self.on_disconnect = None
        self._disc_fired = False
        #: None = adaptive (tuned from ack latency); a float forces a
        #: fixed coalescing window in seconds (0.0 = always inline)
        self.coalesce_window: Optional[float] = None
        self._coal_cv = threading.Condition(threading.Lock())
        self._pending_ops: list = []
        self._send_inflight = False
        self._flush_deadline: Optional[float] = None
        self._flusher: Optional[threading.Thread] = None
        self._coal_closed = False
        self._inflight_ts: dict[int, float] = {}  # own cseq → submit time
        self._ack_ewma: Optional[float] = None
        # admission-shed retry state (under _coal_cv): ops the server
        # nacked with retry_after_ms, held in arrival (= clientSeq)
        # order; nothing newer may flush past them or the clientSeq
        # stream would gap at deli
        self._shed_ops: list = []
        self._shed_deadline: Optional[float] = None
        # wall clock of the EARLIEST park since the last shed flush:
        # when the held ops finally flush, the frame carries a HOP_SHED
        # stamp at this time so shed_to_submit measures park duration
        self._shed_park_wall: Optional[float] = None
        self._pending_shed_wall: Optional[float] = None

        def on_ops(f):
            for d in f["msgs"]:
                self._deliver("op", message_from_dict(d))

        def on_binary_ops(msgs):
            for m in msgs:
                self._deliver("op", m)

        transport.on_push("ops", on_ops)
        transport.on_binary_ops = on_binary_ops
        transport.on_push("op", lambda f: self._deliver(
            "op", message_from_dict(f["msg"])))
        transport.on_push("nack", self._on_nack_frame)
        transport.on_push("signal", lambda f: self._deliver(
            "signal", message_from_dict(f["signal"])))

        def on_presence(sigs):
            for s in sigs:
                self._deliver("signal", s)

        transport.on_presence = on_presence
        transport.on_disconnect = self._fire_disconnect
        connect_frame = {
            "t": "connect", "tenant": tenant_id, "doc": document_id,
            "details": details, "token": token,
            "bin": 1 if binary else 0}
        if readonly:
            # fast reader session: no join op is ordered, the clientId
            # never enters the quorum — the session is free on the core's
            # op path (boots from snapshot cache + bounded backfill)
            connect_frame["readonly"] = 1
        # cold-start storm lane: a parked first-route (boot_pending)
        # retries with the server's jittered backoff instead of failing
        # the session — the connect-side twin of the shed-retry lane
        deadline = time.monotonic() + _BOOT_RETRY_MAX_S
        while True:
            try:
                reply = transport.request(connect_frame)
                break
            except BootPendingError as e:
                delay = (e.retry_after_ms / 1000.0) \
                    * (1.0 + 0.5 * random.random())
                if time.monotonic() + delay >= deadline:
                    raise
                self.counters.inc("boot.parked.retries")
                time.sleep(delay)
        self.client_id = reply["clientId"]
        self.initial_sequence_number = reply["seq"]
        self.mode = reply.get("mode", "write")
        self.max_message_size = reply.get("maxMessageSize")
        # server advertises the columnar backfill door only on direct
        # core connections (a gateway cannot relay the binary pushes)
        self.cols_backfill = bool(reply.get("colsBackfill"))

    def _on_nack_frame(self, f: dict) -> None:
        """Reader-thread nack dispatch: an admission shed (THROTTLING +
        retry_after_ms + the op echoed back) is a transparent retry,
        not an app-visible refusal — hold the op and flush it after the
        server's backoff. Every other nack delivers to ``on_nack``."""
        nack = message_from_dict(f["nack"])
        if (self._binary and nack.retry_after_ms
                and nack.operation is not None):
            self._queue_shed_retry(nack.operation, nack.retry_after_ms)
            return
        self._deliver("nack", nack)

    def _queue_shed_retry(self, op, retry_ms: int) -> None:
        # shed nacks arrive in submit (= clientSeq) order, so appending
        # preserves the resubmit order the server's resume watermark
        # expects; jitter keeps a shed fleet from re-flooding in
        # lockstep
        delay = (retry_ms / 1000.0) * (1.0 + 0.5 * random.random())
        with self._coal_cv:
            if self._coal_closed:
                return
            self._shed_ops.append(op)
            if self._shed_park_wall is None:
                self._shed_park_wall = time.time()
            self._shed_deadline = max(self._shed_deadline or 0.0,
                                      time.monotonic() + delay)
            self._ensure_flusher()
            self._coal_cv.notify_all()
        self.counters.inc("driver.submit.shed_retries")

    def _deliver(self, kind: str, event) -> None:
        if kind == "op" \
                and getattr(event, "client_id", None) == getattr(
                    self, "client_id", None):
            # own op came back sequenced: close the ack-latency loop the
            # adaptive coalescing window tunes from
            t0 = self._inflight_ts.pop(event.client_sequence_number, None)
            if t0 is not None:
                dt = time.monotonic() - t0
                e = self._ack_ewma
                self._ack_ewma = dt if e is None else e + 0.25 * (dt - e)
        if kind == "op" and self._cache is not None \
                and event.type == MessageType.SUMMARY_ACK:
            # a newer summary committed: the cached boot snapshot is
            # stale — drop it so the NEXT boot fetches the new head
            self._cache.invalidate(self._tenant, self._doc)
        cb = self._handlers[kind]
        if cb is None:
            self._buffers[kind].append(event)
        else:
            cb(event)

    def _set_handler(self, kind: str, cb) -> None:
        with self._t.lock:
            self._handlers[kind] = cb
            if cb is not None:
                pending, self._buffers[kind] = self._buffers[kind], []
                for event in pending:
                    cb(event)

    on_op = property(lambda self: self._handlers["op"],
                     lambda self, cb: self._set_handler("op", cb))
    on_nack = property(lambda self: self._handlers["nack"],
                       lambda self, cb: self._set_handler("nack", cb))
    on_signal = property(lambda self: self._handlers["signal"],
                         lambda self, cb: self._set_handler("signal", cb))

    def submit(self, messages) -> None:
        if self.readonly:
            # fail client-side: a readonly session has no quorum seat
            # and the server would only scope-nack the op anyway
            raise RuntimeError("cannot submit on a readonly connection")
        messages = list(messages)
        if not messages:
            return
        if not self._binary:
            with self._t.lock:
                self._t.send({"t": "submit",
                              "ops": [message_to_dict(m) for m in messages]})
            return
        cseq = getattr(messages[-1], "client_sequence_number", None)
        if cseq is not None:
            if len(self._inflight_ts) > 256:
                # evict only the OLDEST entry (dicts are insertion-
                # ordered): wiping the whole map here discarded every
                # in-flight ack-latency sample under a deep burst and
                # froze the coalescing EWMA at its pre-burst value
                del self._inflight_ts[next(iter(self._inflight_ts))]
                self.counters.inc("driver.inflight.evicted")
            self._inflight_ts[cseq] = time.monotonic()
        with self._coal_cv:
            if self._coal_closed:
                raise OSError("delta connection closed")
            if self._pending_ops:
                self.counters.inc("driver.submit.coalesced", len(messages))
            self._pending_ops.extend(messages)
            if self._shed_ops:
                # a shed backoff is running: the held ops must reach the
                # wire before anything newer, so this submit parks until
                # the flusher releases the whole queue at the deadline
                self._ensure_flusher()
                self._coal_cv.notify_all()
                return
            if self._send_inflight:
                # the in-flight flush drains the buffer before it parks:
                # these ops ride the next boxcar without a new wakeup
                return
            window = self._window()
            if window > 0.0:
                if self._flush_deadline is None:
                    self._flush_deadline = time.monotonic() + window
                self._ensure_flusher()
                self._coal_cv.notify_all()
                return
            self._send_inflight = True
        self._drain_and_send()

    def _window(self) -> float:
        w = self.coalesce_window
        if w is not None:
            return w
        e = self._ack_ewma
        if e is None or e < _COALESCE_MIN_ACK_S:
            return 0.0
        return min(_COALESCE_MAX_WINDOW_S, e * 0.125)

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flusher_loop, daemon=True,
                name="fluid-net-coalesce")
            self._flusher.start()

    def _flusher_loop(self) -> None:
        while True:
            with self._coal_cv:
                if self._coal_closed:
                    return
                d = (self._shed_deadline if self._shed_ops
                     else self._flush_deadline)
                if d is None or self._send_inflight \
                        or not (self._pending_ops or self._shed_ops):
                    self._coal_cv.wait(0.1)
                    continue
                now = time.monotonic()
                if now < d:
                    self._coal_cv.wait(d - now)
                    continue
                self._send_inflight = True
            try:
                self._drain_and_send()
            except OSError:
                # peer gone mid-flush: _send_ops already requeued the
                # unsent tail; a genuinely dead socket is the reader
                # thread's to notice and turn into a disconnect
                pass

    def _drain_and_send(self) -> None:
        """Flush the coalescing buffer, then keep draining anything that
        arrived while the send was on the wire. Runs with
        ``_send_inflight`` held; always releases it."""
        try:
            while True:
                with self._coal_cv:
                    if self._shed_ops:
                        now = time.monotonic()
                        if (self._shed_deadline is not None
                                and now < self._shed_deadline):
                            # backoff still running: nothing may pass
                            # the held ops (clientSeq order); the
                            # flusher re-enters at the deadline
                            self._flush_deadline = None
                            return
                        ops = self._shed_ops + self._pending_ops
                        self._shed_ops = []
                        self._shed_deadline = None
                        self._pending_shed_wall = self._shed_park_wall
                        self._shed_park_wall = None
                    else:
                        ops = self._pending_ops
                    self._flush_deadline = None
                    if not ops:
                        return
                    self._pending_ops = []
                self._send_ops(ops)
        finally:
            with self._coal_cv:
                self._send_inflight = False
                self._coal_cv.notify_all()

    def _send_ops(self, ops: list) -> None:
        for i in range(0, len(ops), _MAX_BOXCAR_OPS):
            try:
                self._send_chunk(ops[i:i + _MAX_BOXCAR_OPS])
            except OSError:
                # the peer stopped reading long enough for the send to
                # fail (buffer-full timeout under a nack storm): the
                # drained batch must NOT vanish — requeue everything
                # unsent at the head of the shed lane and back off. If
                # the failure left a partial frame on the wire the
                # server's framed read breaks and drops the connection,
                # which runs the visible disconnect path; either way an
                # op is never lost silently.
                with self._coal_cv:
                    if not self._coal_closed:
                        self._shed_ops[:0] = ops[i:]
                        if self._shed_park_wall is None:
                            self._shed_park_wall = time.time()
                        self._shed_deadline = max(
                            self._shed_deadline or 0.0,
                            time.monotonic() + 0.5)
                        self._ensure_flusher()
                        self._coal_cv.notify_all()
                raise

    def _send_chunk(self, chunk: list) -> None:
        shed_wall, self._pending_shed_wall = self._pending_shed_wall, None
        sample = False
        if self.trace_sample_n:
            self._trace_seq += 1
            # shed flushes are force-sampled: park time is exactly the
            # tail latency the hop breakdown exists to attribute
            sample = (self._trace_seq % self.trace_sample_n == 0
                      or shed_wall is not None)
        # columnar first: a canonical chanop boxcar rides the
        # fixed-stride column frame the server admits without
        # materializing per-op objects (kind stays "submit" so the
        # chaos net.send rules fault both frame families alike)
        columnar = False
        body = binwire.encode_submit_columns(chunk)
        if body is not None:
            columnar = True
            if sample:
                # hoptail append keeps the op columns untouched —
                # stamping traces on the op itself would kick the
                # boxcar off the columnar path entirely
                if shed_wall is not None:
                    body = binwire.append_hop(
                        body, HOP_SHED, shed_wall)
                body = binwire.append_hop(
                    body, HOP_SUBMIT, time.time())
                self.counters.inc("driver.trace.sampled")
        else:
            if sample:
                if shed_wall is not None:
                    chunk[-1].traces.append(TraceHop(
                        service="frontend", action="shed",
                        timestamp=shed_wall))
                chunk[-1].traces.append(TraceHop(
                    service="client", action="submit",
                    timestamp=time.time()))
                self.counters.inc("driver.trace.sampled")
            try:
                body = binwire.encode_submit(chunk)
            except Exception:
                # a boxcar binwire cannot pack (>u16 ops, int outside
                # the fixed-field range) still goes through: the
                # server accepts both frame kinds on any connection
                body = None
        with self._t.lock:
            if body is not None:
                self._t.send_body(body, kind="submit")
            else:
                self._t.send(
                    {"t": "submit",
                     "ops": [message_to_dict(m) for m in chunk]})
        self.counters.inc("driver.submit.frames")
        self.counters.inc("driver.submit.ops", len(chunk))
        if columnar:
            self.counters.inc("driver.submit.columnar")

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        self._t.send({"t": "signal", "content": content, "type": type})

    def _fire_disconnect(self, reason: str) -> None:
        """Exactly-once disconnect notification: close() and the reader
        thread's exit path both land here, and callers should not need to
        de-register handlers to avoid a double callback."""
        with self._t._pending_cv:
            if self._disc_fired:
                return
            self._disc_fired = True
        if self.on_disconnect:
            self.on_disconnect(reason)

    def close(self) -> None:
        # drain the coalescing window first: close must not drop submits
        # the caller already handed over
        with self._coal_cv:
            self._coal_closed = True
            deadline = time.monotonic() + 0.5
            while self._send_inflight and time.monotonic() < deadline:
                self._coal_cv.wait(0.05)
            # held shed ops flush too (ahead of the buffer — clientSeq
            # order holds even on the close path)
            pending = self._shed_ops + self._pending_ops
            self._shed_ops, self._pending_ops = [], []
            self._coal_cv.notify_all()
        if pending:
            try:
                self._send_ops(pending)
            except OSError:
                pass
        try:
            self._t.send({"t": "disconnect"})
        except OSError:
            pass
        self._t.close()
        self._fire_disconnect("client closed connection")


class NetworkDeltaStorage(DocumentDeltaStorage):
    """``cols`` is a late-bound flag (callable or bool): whether the
    server advertised the columnar backfill door on the delta-stream
    connect (it may connect after this object is built)."""

    def __init__(self, transport: _Transport, tenant_id: str,
                 document_id: str, token_provider=None, cols=False):
        self._t = transport
        self._tenant = tenant_id
        self._doc = document_id
        self._token_provider = token_provider
        self._cols = cols

    def get_deltas(self, from_seq: int, to_seq: int):
        token = (self._token_provider(self._tenant, self._doc)
                 if self._token_provider else None)
        cols = self._cols() if callable(self._cols) else self._cols
        if cols:
            # columnar door: blocks arrive as rid-tagged binary pushes
            # (already decoded into take_blocks by the reader thread); a
            # boundary block may overhang the range, so trim by seq here
            rid, reply = self._t.request_rid({
                "t": "get_deltas_cols", "tenant": self._tenant,
                "doc": self._doc, "from": from_seq, "to": to_seq,
                "token": token})
            msgs = [message_from_dict(d) for d in reply.get("msgs", [])]
            blocks = self._t.take_blocks(rid)
            if blocks:
                msgs.extend(m for m in blocks
                            if from_seq < m.sequence_number < to_seq)
                msgs.sort(key=lambda m: m.sequence_number)
            return msgs
        reply = self._t.request({
            "t": "get_deltas", "tenant": self._tenant, "doc": self._doc,
            "from": from_seq, "to": to_seq, "token": token})
        return [message_from_dict(d) for d in reply["msgs"]]


class NetworkStorage(DocumentStorage):
    """Snapshot storage RPCs, with an optional driver-side cache.

    With a :class:`~.snapshot_cache.SnapshotCache` attached (the
    odsp-driver lesson, odspCache.ts), a re-boot of an unchanged doc
    serves version+tree from the cache and issues ZERO storage round
    trips; the delta connection invalidates the entry when a newer
    summary commits (summaryAck on the live stream)."""

    def __init__(self, transport: _Transport, tenant_id: str,
                 document_id: str, token_provider=None, cache=None,
                 counters: Optional[Counters] = None):
        self._t = transport
        self._tenant = tenant_id
        self._doc = document_id
        self._token_provider = token_provider
        self._cache = cache
        self.counters = (counters if counters is not None
                         else tier_counters("driver"))
        self.rpcs = 0  # storage round trips issued (cache hits don't count)

    def _req(self, t: str, **kw) -> dict:
        self.rpcs += 1
        token = (self._token_provider(self._tenant, self._doc)
                 if self._token_provider else None)
        return self._t.request(
            {"t": t, "tenant": self._tenant, "doc": self._doc,
             "token": token, **kw})

    def get_versions(self, count: int = 1) -> list[dict]:
        if self._cache is not None and count == 1:
            entry = self._cache.get(self._tenant, self._doc)
            if entry is not None:
                return [dict(entry["version"])]
        return self._req("get_versions", count=count)["versions"]

    def get_snapshot_tree(self, version: Optional[dict] = None):
        if self._cache is not None:
            entry = self._cache.get(self._tenant, self._doc)
            if entry is not None and (
                    version is None
                    or version.get("id") == entry["version"].get("id")):
                return entry["tree"]
        if version is not None:
            # explicit (possibly historical) version: serve it through
            # the tree shim but never cache it — it must not demote a
            # newer cached head
            return self._req("get_tree", version=version)["tree"]
        epoch = (self._cache.epoch(self._tenant, self._doc)
                 if self._cache is not None else None)
        # snapshot fast path first: columnar chunks, content-addressed
        # client dedupe, zero server-side re-serialization
        head = tree = None
        try:
            head, tree = self._snapcols_boot()
        except (RuntimeError, ValueError, KeyError):
            # torn/missing chunk, decode failure, or a server predating
            # the RPC: fall back to the legacy whole-tree path, which
            # materializes from the same durable store
            self.counters.inc("boot.snapshot.fallback")
            head = tree = None
        if head is None and tree is None:
            versions = self._req("get_versions", count=1)["versions"]
            if not versions:
                return None
            head = versions[0]
            tree = self._req("get_tree", version=head)["tree"]
        if tree is not None and self._cache is not None:
            # epoch-guarded: if a summary ack invalidated mid-fetch,
            # this put is dropped rather than resurrecting stale state
            self._cache.put(self._tenant, self._doc, dict(head), tree,
                            epoch=epoch)
        return tree

    def _snapcols_boot(self):
        """Boot through the columnar door: one get_snapshot_cols RPC
        (advertising cached chunk hashes), FT_COLS_SNAP pushes for only
        the missing chunks, client-side np.frombuffer decode. Returns
        ``(version, tree)`` — ``(None, None)`` when the doc has no
        summary yet; raises when the head predates snapcols or a chunk
        arrives torn/missing (callers fall back to the tree shim)."""
        import hashlib

        from ..protocol import snapcols

        self.rpcs += 1
        token = (self._token_provider(self._tenant, self._doc)
                 if self._token_provider else None)
        have = (self._cache.chunk_hashes()
                if self._cache is not None else [])
        _, reply = self._t.request_rid({
            "t": "get_snapshot_cols", "tenant": self._tenant,
            "doc": self._doc, "token": token, "have": have})
        pushed = self._t.take_snap_chunks()
        if reply.get("version") is None:
            return None, None
        if reply.get("legacy"):
            raise ValueError("head summary predates snapcols")
        chunks = []
        fetched = cached = 0
        for h in reply["chunks"]:
            data = pushed.get(h)
            if data is not None:
                if hashlib.sha256(data).hexdigest() != h:
                    raise ValueError(f"torn snapshot chunk {h[:12]}")
                if self._cache is not None:
                    self._cache.put_chunk(h, data)
                fetched += 1
            else:
                data = (self._cache.get_chunk(h)
                        if self._cache is not None else None)
                if data is None:
                    raise ValueError(f"missing snapshot chunk {h[:12]}")
                cached += 1
            chunks.append(data)
        self.counters.inc("boot.chunks.fetched", fetched)
        self.counters.inc("boot.chunks.cached", cached)
        mergetree = snapcols.decode_snapshot_chunks(
            chunks, reply["min_seq"], reply["tree_seq"])
        tree = {
            "protocol": reply["protocol"],
            "runtime": {"dataStores": {reply["ds"]: {
                "pkg": reply["pkg"],
                "snapshot": {"channels": {reply["channel"]: {
                    "type": "shared-string",
                    "snapshot": {"mergetree": mergetree,
                                 "intervals": {}},
                }}},
            }}},
            "sequence_number": reply["seq"],
        }
        self.counters.inc("boot.snapshot.used")
        return {"id": reply["version"]}, tree

    def read_blob(self, blob_id: str) -> bytes:
        return bytes.fromhex(self._req("read_blob", id=blob_id)["hex"])

    def write_blob(self, content: bytes) -> str:
        return self._req("write_blob", hex=content.hex())["id"]

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        from ..protocol.summary import (
            SummaryAttachment,
            SummaryBlob,
            SummaryHandle,
            SummaryTree,
            summary_to_wire,
        )

        if isinstance(summary, (SummaryTree, SummaryBlob, SummaryHandle,
                                SummaryAttachment)):
            summary = summary_to_wire(summary)
        return self._req("upload_summary", summary=summary, parent=parent)["id"]


class NetworkDocumentService(DocumentService):
    """One document's bindings over the network. The delta stream gets its
    own TCP connection (it carries the push traffic); delta/snapshot
    storage share a second, request-only connection — mirroring the
    reference's socket + REST split."""

    def __init__(self, host: str, port: int, tenant_id: str, document_id: str,
                 timeout: float = 30.0, token_provider=None,
                 binary: bool = True, cache=None,
                 counters: Optional[Counters] = None,
                 readonly: bool = False):
        self._host, self._port, self._timeout = host, port, timeout
        self._tenant = tenant_id
        self._doc = document_id
        self._token_provider = token_provider
        self._binary = binary
        self._cache = cache
        self._readonly = readonly
        self.counters = (counters if counters is not None
                         else tier_counters("driver"))
        self._rpc: Optional[_Transport] = None
        self._cols_backfill = False  # learned from the stream connect

    def _rpc_transport(self) -> _Transport:
        if self._rpc is None or self._rpc._closed:
            self._rpc = _Transport(self._host, self._port, self._timeout)
        return self._rpc

    def connect_to_delta_stream(self, details: Any = None) -> NetworkDeltaConnection:
        t = _Transport(self._host, self._port, self._timeout)
        token = (self._token_provider(self._tenant, self._doc)
                 if self._token_provider else None)
        conn = NetworkDeltaConnection(t, self._tenant, self._doc, details,
                                      token=token, binary=self._binary,
                                      cache=self._cache,
                                      counters=self.counters,
                                      readonly=self._readonly)
        self._cols_backfill = conn.cols_backfill
        return conn

    def connect_to_delta_storage(self) -> NetworkDeltaStorage:
        return NetworkDeltaStorage(self._rpc_transport(), self._tenant,
                                   self._doc, self._token_provider,
                                   cols=lambda: self._cols_backfill)

    def connect_to_storage(self) -> NetworkStorage:
        return NetworkStorage(self._rpc_transport(), self._tenant,
                              self._doc, self._token_provider,
                              cache=self._cache, counters=self.counters)

    def history(self):
        from .history import NetworkHistoryClient

        return NetworkHistoryClient(self)


class NetworkDocumentServiceFactory(DocumentServiceFactory):
    """``token_provider(tenant, doc) -> str`` supplies the signed JWT the
    front door validates when tenancy is enforced (ref:
    routerlicious-driver tokens.ts TokenProvider)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 token_provider=None, binary: bool = True,
                 snapshot_cache: bool = True,
                 counters: Optional[Counters] = None,
                 readonly: bool = False):
        from .snapshot_cache import SnapshotCache

        self._host, self._port, self._timeout = host, port, timeout
        self._token_provider = token_provider
        self._binary = binary
        self._readonly = readonly
        # one cache shared by every document of this factory (the
        # odspCache shape); reachable as factory.snapshot_cache for
        # stats/assertions
        self.snapshot_cache = SnapshotCache() if snapshot_cache else None
        # one Counters shared by every connection of this factory, so
        # bench/soak/tests can assert submit coalescing engaged; the
        # registry-vended instance also surfaces in the metrics scrape
        # under tier="driver"
        self.counters = (counters if counters is not None
                         else tier_counters("driver"))

    def create_document_service(
        self, tenant_id: str, document_id: str
    ) -> NetworkDocumentService:
        return NetworkDocumentService(
            self._host, self._port, tenant_id, document_id, self._timeout,
            token_provider=self._token_provider, binary=self._binary,
            cache=self.snapshot_cache, counters=self.counters,
            readonly=self._readonly)
