"""File driver: a document persisted as plain files, for replay/offline.

Ref: packages/drivers/file-driver (fileDocumentService.ts — reads a
document's ops + snapshots from local files and feeds the replay-tool)
and replay-driver (replayController.ts — a read-only document service
that pumps recorded ops through the real loader/runtime).

On-disk layout, one directory per document:

    <root>/<tenant>/<doc>/messages.json   [wire-encoded sequenced msgs]
    <root>/<tenant>/<doc>/snapshot.json   optional boot summary dict

A document opened through this driver is READ-ONLY: there is no ordering
service behind it, so the delta stream cannot accept submissions. Load
containers with ``connect=False`` and pump with
``delta_manager.advance_to(seq)``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

from ..protocol.serialization import message_from_dict, message_to_dict
from .definitions import (
    DocumentDeltaStorage,
    DocumentService,
    DocumentServiceFactory,
    DocumentStorage,
)


def write_doc_dir(doc_dir: str, msgs: list, snap: Optional[dict]) -> str:
    """THE on-disk writer for the file-driver layout — record_document
    (in-proc) and replay/fetch.py (network) both serialize through here
    so the format can never fork between them."""
    os.makedirs(doc_dir, exist_ok=True)
    with open(os.path.join(doc_dir, "messages.json"), "w") as f:
        json.dump([message_to_dict(m) for m in msgs], f)
    if snap is not None:
        with open(os.path.join(doc_dir, "snapshot.json"), "w") as f:
            json.dump(snap, f)
    return doc_dir


def record_document(server, tenant_id: str, document_id: str,
                    root_dir: str) -> str:
    """Dump a live server's document to the file-driver layout (the
    fetch-tool role): full sequenced log + latest acked summary."""
    msgs = server.get_deltas(tenant_id, document_id, 0, 10**9)
    snap = server.storage(tenant_id, document_id).get_snapshot_tree()
    return write_doc_dir(os.path.join(root_dir, tenant_id, document_id),
                         msgs, snap)


class FileDeltaStorage(DocumentDeltaStorage):
    def __init__(self, messages: list):
        self._messages = messages
        # a fetched doc may hold only the TAIL of a retention-truncated
        # log: index by the first message's actual seq, never assume
        # messages[i] is seq i+1
        self._first = (messages[0].sequence_number if messages else 1)

    def get_deltas(self, from_seq: int, to_seq: int):
        lo = max(from_seq - (self._first - 1), 0)
        hi = min(to_seq - self._first, len(self._messages))
        return self._messages[lo:hi] if hi > lo else []

    @property
    def last_seq(self) -> int:
        return self._messages[-1].sequence_number if self._messages else 0


class FileStorage(DocumentStorage):
    def __init__(self, snapshot: Optional[dict]):
        self._snapshot = snapshot

    def get_versions(self, count: int = 1) -> list[dict]:
        return [{"id": "file", "tree_id": "file"}] if self._snapshot else []

    def get_snapshot_tree(self, version: Optional[dict] = None):
        return self._snapshot

    def read_blob(self, blob_id: str) -> bytes:
        raise NotImplementedError("file driver stores one materialized tree")

    def write_blob(self, content: bytes) -> str:
        raise ReadOnlyDocumentError("file documents are read-only")

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        raise ReadOnlyDocumentError("file documents are read-only")


class ReadOnlyDocumentError(RuntimeError):
    pass


class FileDocumentService(DocumentService):
    def __init__(self, messages: list, snapshot: Optional[dict]):
        self._delta_storage = FileDeltaStorage(messages)
        self._storage = FileStorage(snapshot)

    @classmethod
    def from_dir(cls, doc_dir: str) -> "FileDocumentService":
        with open(os.path.join(doc_dir, "messages.json")) as f:
            messages = [message_from_dict(d) for d in json.load(f)]
        snap_path = os.path.join(doc_dir, "snapshot.json")
        snapshot = None
        if os.path.exists(snap_path):
            with open(snap_path) as f:
                snapshot = json.load(f)
        return cls(messages, snapshot)

    def connect_to_delta_stream(self, details: Any = None):
        raise ReadOnlyDocumentError(
            "file documents have no ordering service: load with "
            "connect=False and pump with delta_manager.advance_to()")

    def connect_to_delta_storage(self) -> FileDeltaStorage:
        return self._delta_storage

    def connect_to_storage(self) -> FileStorage:
        return self._storage

    @property
    def last_seq(self) -> int:
        return self._delta_storage.last_seq


class FileDocumentServiceFactory(DocumentServiceFactory):
    def __init__(self, root_dir: str):
        self._root = root_dir

    def create_document_service(
        self, tenant_id: str, document_id: str
    ) -> FileDocumentService:
        return FileDocumentService.from_dir(
            os.path.join(self._root, tenant_id, document_id))
