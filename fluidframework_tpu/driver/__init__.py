"""Driver layer: the pluggable boundary between client stack and service.

Ref: packages/loader/driver-definitions + packages/drivers (SURVEY §2.5).
A document service exposes three sub-services (driver-definitions):

- delta connection  — the live op stream (socket analog)
- delta storage     — sequenced-op backfill (REST /deltas analog)
- storage           — snapshots/blobs (historian/git analog)

``local`` binds them straight to an in-proc LocalServer (the local-driver
test backbone, packages/drivers/local-driver). Production drivers (gRPC
front end over DCN) implement the same surface.
"""

from .definitions import (
    DocumentDeltaConnection,
    DocumentDeltaStorage,
    DocumentService,
    DocumentServiceFactory,
    DocumentStorage,
)
from .history import HistoryClient, LocalHistoryClient, NetworkHistoryClient
from .local import LocalDocumentServiceFactory
from .network import NetworkDocumentServiceFactory

__all__ = [
    "DocumentDeltaConnection",
    "DocumentDeltaStorage",
    "DocumentService",
    "DocumentServiceFactory",
    "DocumentStorage",
    "HistoryClient",
    "LocalHistoryClient",
    "NetworkHistoryClient",
    "LocalDocumentServiceFactory",
    "NetworkDocumentServiceFactory",
]
