"""Driver contracts (ref: packages/loader/driver-definitions/src).

``IDocumentServiceFactory`` → ``IDocumentService`` → the three
sub-services: ``IDocumentDeltaConnection`` (live stream),
``IDocumentDeltaStorageService`` (backfill), ``IDocumentStorageService``
(snapshots/blobs).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Optional

from ..protocol.messages import (
    DocumentMessage,
    Nack,
    SequencedDocumentMessage,
    Signal,
)


class DocumentDeltaConnection(ABC):
    """Live bidirectional op stream for one client connection.

    Ref: driver-definitions IDocumentDeltaConnection; socket wrapper in
    driver-base/src/documentDeltaConnection.ts:53.
    """

    client_id: str
    initial_sequence_number: int
    # event callbacks (buffered until assigned, matching socket semantics)
    on_op: Optional[Callable[[SequencedDocumentMessage], None]]
    on_nack: Optional[Callable[[Nack], None]]
    on_signal: Optional[Callable[[Signal], None]]
    on_disconnect: Optional[Callable[[str], None]]

    @abstractmethod
    def submit(self, messages: list[DocumentMessage]) -> None: ...

    @abstractmethod
    def submit_signal(self, content: Any, type: str = "signal") -> None: ...

    @abstractmethod
    def close(self) -> None: ...


class DocumentDeltaStorage(ABC):
    """Sequenced-op backfill (ref: IDocumentDeltaStorageService; alfred
    /deltas REST → routerlicious-driver deltaStorageService.ts:17)."""

    @abstractmethod
    def get_deltas(self, from_seq: int, to_seq: int) -> list[SequencedDocumentMessage]:
        """Ops with from_seq < seq < to_seq (exclusive bounds)."""


class DocumentStorage(ABC):
    """Snapshot/blob storage (ref: IDocumentStorageService; historian REST
    via services-client GitManager)."""

    @abstractmethod
    def get_versions(self, count: int = 1) -> list[dict]:
        """Latest summary versions, newest first ({'id', 'tree_id'})."""

    @abstractmethod
    def get_snapshot_tree(self, version: Optional[dict] = None) -> Optional[dict]:
        """The summary tree for a version (None ⇒ no summary yet)."""

    @abstractmethod
    def read_blob(self, blob_id: str) -> bytes: ...

    @abstractmethod
    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        """Write a summary tree; returns its handle (commit id)."""


class DocumentService(ABC):
    """One document's service bindings (ref: IDocumentService)."""

    @abstractmethod
    def connect_to_delta_stream(self, details: Any = None) -> DocumentDeltaConnection: ...

    @abstractmethod
    def connect_to_delta_storage(self) -> DocumentDeltaStorage: ...

    @abstractmethod
    def connect_to_storage(self) -> DocumentStorage: ...

    def history(self):
        """History-plane client for this document (commit log, fork,
        point-in-time replay, integrate) — see driver/history.py. Not
        abstract: drivers without a history surface (file, replay) keep
        working and refuse here."""
        raise NotImplementedError(
            f"{type(self).__name__} has no history surface")


class DocumentServiceFactory(ABC):
    """Resolves a document URL/id to a DocumentService
    (ref: IDocumentServiceFactory.createDocumentService)."""

    @abstractmethod
    def create_document_service(
        self, tenant_id: str, document_id: str
    ) -> DocumentService: ...
