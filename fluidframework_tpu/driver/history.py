"""History driver: the client surface onto the doc history plane.

One :class:`HistoryClient` per (tenant, document) — obtained from
``DocumentService.history()`` — exposes the commit/ref graph (``log`` /
``refs``), near-free fork (``fork``), point-in-time replay
(``open_at``), and CRDT-mediated integrate (``integrate``) in both
deployments: :class:`LocalHistoryClient` calls the in-proc plane
directly, :class:`NetworkHistoryClient` rides the ``history_*`` doors
over the RPC transport (commits arrive as binary FT_HISTORY frames —
the same refgraph codec the durable ref files use, so the wire
exercises the torn-tail framing end to end).

``replay_service`` is the replay driver half of point-in-time reads
(ref: packages/drivers/replay-driver ReplayController): it resolves the
nearest committed version at or below the requested seq and binds a
:class:`DocumentService` that pins it — storage serves THAT version
through the ordinary storage doors (an explicit-version ``get_tree``,
deliberately bypassing the latest-head snapshot cache), delta storage
serves the bounded tail ``(base, seq]`` through the history delta
fetch (which tolerates retention-trimmed ranges the live backfill door
refuses), and the delta stream refuses to connect: a historical read
has no seat in the quorum. The container-boot half lives one layer up
in ``loader.history_boot.open_at`` (drivers may not import the
loader), which ``Loader.resolve_at`` wraps for the common case.
"""

from __future__ import annotations

from typing import Any, Optional

from ..protocol.serialization import message_from_dict
from .definitions import (
    DocumentDeltaStorage,
    DocumentService,
    DocumentStorage,
)


class _PinnedStorage(DocumentStorage):
    """Pin ``version`` as the one and only head: the container boots the
    commit's snapshot even when newer summaries exist, and can never
    write (a historical session has nothing to summarize)."""

    def __init__(self, inner: DocumentStorage, version: dict):
        self._inner = inner
        self._version = dict(version)

    def get_versions(self, count: int = 1) -> list[dict]:
        return [dict(self._version)]

    def get_snapshot_tree(self, version: Optional[dict] = None):
        return self._inner.get_snapshot_tree(dict(self._version))

    def read_blob(self, blob_id: str) -> bytes:
        return self._inner.read_blob(blob_id)

    def write_blob(self, content: bytes) -> str:
        raise RuntimeError("historical session is read-only")

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        raise RuntimeError("historical session is read-only")


class _HistoryDeltaStorage(DocumentDeltaStorage):
    """Bounded tail backfill through the history delta fetch: clamps to
    the replay target so ``advance_to`` can never run past it, and the
    fetch survives retention trims (the plane falls back to a durable
    log scan where the live door would refuse with log_truncated)."""

    def __init__(self, fetch, max_seq: int):
        self._fetch = fetch
        self._max = max_seq

    def get_deltas(self, from_seq: int, to_seq: int):
        to_seq = min(to_seq, self._max + 1)
        if to_seq <= from_seq + 1:
            return []
        return self._fetch(from_seq, to_seq)


class _ReplayService(DocumentService):
    """The service a historical container binds: pinned storage, clamped
    history-backed delta storage, and NO delta stream."""

    def __init__(self, storage: DocumentStorage, deltas: DocumentDeltaStorage):
        self._storage = storage
        self._deltas = deltas

    def connect_to_delta_stream(self, details: Any = None):
        raise RuntimeError(
            "historical sessions are offline: open the live doc for a "
            "connected container")

    def connect_to_delta_storage(self):
        return self._deltas

    def connect_to_storage(self):
        return self._storage


class HistoryClient:
    """Per-(tenant, doc) history surface; subclasses supply the five
    primitive calls, ``open_at`` composes them into the replay boot."""

    tenant_id: str
    document_id: str

    # ------------------------------------------------------- primitives

    def log(self, count: Optional[int] = None) -> list[dict]:
        """Commits newest-first (JSON-safe dicts)."""
        raise NotImplementedError

    def refs(self) -> dict:
        """Named refs → commit id."""
        raise NotImplementedError

    def at(self, seq: int) -> dict:
        """Resolve a time-travel read: ``{"commit", "version",
        "base_seq"}`` for the nearest commit at or below ``seq``."""
        raise NotImplementedError

    def deltas(self, from_seq: int, to_seq: int) -> list:
        """Historical ops ``from_seq < seq < to_seq`` (retention-trim
        tolerant, unlike the live backfill door)."""
        raise NotImplementedError

    def fork(self, at_seq: Optional[int] = None,
             new_doc: Optional[str] = None) -> dict:
        """Fork this doc at ``at_seq`` (default: head) into ``new_doc``."""
        raise NotImplementedError

    def integrate(self, batch: int = 64) -> dict:
        """Replay THIS doc's post-base tail into its fork parent through
        the ordinary total order (the CRDT does the merging)."""
        raise NotImplementedError

    # ------------------------------------------------------------ replay

    def _storage(self) -> DocumentStorage:
        raise NotImplementedError

    def replay_service(self, seq: int) -> DocumentService:
        """A :class:`DocumentService` pinned to this doc as of ``seq``:
        snapshot-nearest-below storage plus bounded history-backed tail
        backfill, no live stream. ``loader.history_boot.open_at`` boots
        a read-only container from it."""
        at = self.at(seq)
        storage = _PinnedStorage(self._storage(), at["version"])
        deltas = _HistoryDeltaStorage(self.deltas, seq)
        return _ReplayService(storage, deltas)


class LocalHistoryClient(HistoryClient):
    """In-proc: straight onto ``server.history`` (the plane itself)."""

    def __init__(self, server, tenant_id: str, document_id: str):
        self._server = server
        self.tenant_id = tenant_id
        self.document_id = document_id

    @property
    def _plane(self):
        return self._server.history

    def log(self, count: Optional[int] = None) -> list[dict]:
        from ..protocol.refgraph import commit_to_json

        return [commit_to_json(c)
                for c in self._plane.log(self.tenant_id, self.document_id,
                                         count)]

    def refs(self) -> dict:
        return self._plane.refs(self.tenant_id, self.document_id)

    def at(self, seq: int) -> dict:
        return self._plane.replay_read(self.tenant_id, self.document_id,
                                       seq)

    def deltas(self, from_seq: int, to_seq: int) -> list:
        return self._plane.read_deltas(self.tenant_id, self.document_id,
                                       from_seq, to_seq)

    def fork(self, at_seq: Optional[int] = None,
             new_doc: Optional[str] = None) -> dict:
        return self._plane.fork(self.tenant_id, self.document_id,
                                at_seq=at_seq, new_doc=new_doc)

    def integrate(self, batch: int = 64) -> dict:
        return self._plane.integrate(self.tenant_id, self.document_id,
                                     batch=batch)

    def _storage(self) -> DocumentStorage:
        return self._server.storage(self.tenant_id, self.document_id)


class NetworkHistoryClient(HistoryClient):
    """Over the wire: the front end's ``history_*`` doors on the shared
    request transport. ``log`` collects the rid-tagged FT_HISTORY binary
    pushes the terminal JSON reply confirms (same wire, same reader
    thread: by reply time every commit frame has landed)."""

    def __init__(self, service):
        self._svc = service
        self.tenant_id = service._tenant
        self.document_id = service._doc

    def _frame(self, t: str, **kw) -> dict:
        svc = self._svc
        token = (svc._token_provider(self.tenant_id, self.document_id)
                 if svc._token_provider else None)
        return {"t": t, "tenant": self.tenant_id, "doc": self.document_id,
                "token": token, **kw}

    def _req(self, t: str, **kw) -> dict:
        return self._svc._rpc_transport().request(self._frame(t, **kw))

    def log(self, count: Optional[int] = None) -> list[dict]:
        transport = self._svc._rpc_transport()
        rid, reply = transport.request_rid(self._frame(
            "history_log", count=count))
        commits = transport.take_history(rid)
        if len(commits) != reply.get("commits", 0):
            raise RuntimeError(
                f"history log frame loss: {len(commits)} of "
                f"{reply.get('commits')} commits arrived")
        return commits

    def refs(self) -> dict:
        return self._req("history_log", count=0)["refs"]

    def at(self, seq: int) -> dict:
        return self._req("history_at", seq=seq)["at"]

    def deltas(self, from_seq: int, to_seq: int) -> list:
        reply = self._req("history_deltas",
                          **{"from": from_seq, "to": to_seq})
        return [message_from_dict(d) for d in reply["msgs"]]

    def fork(self, at_seq: Optional[int] = None,
             new_doc: Optional[str] = None) -> dict:
        return self._req("history_fork", seq=at_seq,
                         new_doc=new_doc)["fork"]

    def integrate(self, batch: int = 64) -> dict:
        return self._req("history_integrate", batch=batch)["integrate"]

    def _storage(self) -> DocumentStorage:
        return self._svc.connect_to_storage()
