"""Local driver: client stack ⇄ in-proc LocalServer, no network.

Ref: packages/drivers/local-driver (localDocumentService.ts,
localDocumentDeltaConnection.ts) — the test backbone binding the REAL
client stack to the REAL service lambdas in one process (SURVEY §4).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

from ..service.core import summary_versions_collection
from ..service.local_server import LocalServer, ServerConnection
from .definitions import (
    DocumentDeltaConnection,
    DocumentDeltaStorage,
    DocumentService,
    DocumentServiceFactory,
    DocumentStorage,
)


class LocalDeltaConnection(DocumentDeltaConnection):
    def __init__(self, conn: ServerConnection):
        self._conn = conn
        self.client_id = conn.client_id
        self.initial_sequence_number = conn.initial_sequence_number
        self.mode = getattr(conn, "mode", "write")
        self.on_disconnect = None

    # event callbacks proxy straight to the server connection's buffered
    # handler slots
    on_op = property(
        lambda self: self._conn.on_op,
        lambda self, cb: setattr(self._conn, "on_op", cb))
    on_nack = property(
        lambda self: self._conn.on_nack,
        lambda self, cb: setattr(self._conn, "on_nack", cb))
    on_signal = property(
        lambda self: self._conn.on_signal,
        lambda self, cb: setattr(self._conn, "on_signal", cb))

    def submit(self, messages) -> None:
        self._conn.submit(messages)

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        self._conn.submit_signal(content, type)

    def close(self) -> None:
        self._conn.disconnect()
        if self.on_disconnect:
            self.on_disconnect("client closed connection")


class LocalDeltaStorage(DocumentDeltaStorage):
    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        self._server = server
        self._tenant = tenant_id
        self._doc = document_id

    def get_deltas(self, from_seq: int, to_seq: int):
        return self._server.get_deltas(self._tenant, self._doc, from_seq, to_seq)


class LocalStorage(DocumentStorage):
    """Versioned summary storage over the server's content-addressed blob
    store (the gitrest/historian analog — the C++ chunk store when the
    server has a storage dir; trees/blobs keyed by sha, versions = the
    ref chain, scribe ack = the ref update).

    Summary trees upload recursively (ref: summaryWriter.ts:69-192
    writeClientSummary → createGitTree): each blob is content-addressed;
    each tree node is a JSON blob of named child refs; a
    ``SummaryHandle`` resolves to the PARENT version's subtree ref at
    that path and re-uploads nothing (protocol-definitions summary.ts
    incremental contract).

    Stored tree-node format: {"t": "tree", "e": {name: {"k", "id"}}}.
    """

    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        from ..service.local_orderer import restore_version_records

        # durable-log deployments: acked version records may only exist
        # on the versions topic after a process restart (boot reads
        # storage BEFORE any orderer exists to restore them). Once per
        # (tenant, doc) per process: LocalStorage is constructed per
        # storage RPC, and an unmemoized scan would tax every request
        # with O(#summaries) log reads.
        restored = getattr(server, "_versions_restored", None)
        if restored is None:
            restored = server._versions_restored = set()
        if (tenant_id, document_id) not in restored:
            restore_version_records(server.log, server.db, tenant_id,
                                    document_id)
            restored.add((tenant_id, document_id))
        self._server = server
        self._tenant = tenant_id
        self._doc = document_id
        self._db = server.db
        self._blobs = server.blob_store
        self._stats = server.storage_stats
        self._versions_col = summary_versions_collection(tenant_id, document_id)

    # ------------------------------------------------------------ versions

    def get_versions(self, count: int = 1) -> list[dict]:
        """Only scribe-ACKED versions are boot sources (the git-ref analog:
        scribe committing a summary is what makes it a version); uploads
        that were never validated, or were nacked, are invisible here."""
        versions = sorted(
            (v for v in self._db.collection(self._versions_col).values()
             if v.get("acked")),
            key=lambda v: v["n"],
            reverse=True,
        )
        return [{"id": v["_id"], "tree_id": v["tree_id"]} for v in versions[:count]]

    # -------------------------------------------------------------- reads

    def get_snapshot_tree(self, version: Optional[dict] = None) -> Optional[dict]:
        """Materialize a version into the plain nested summary dict the
        container boots from (reads back through the chunk store)."""
        if version is None:
            versions = self.get_versions(1)
            if not versions:
                return None
            version = versions[0]
        ref = json.loads(self.read_blob(version["tree_id"]).decode())
        if ref.get("t") == "snapcols":
            from ..service.summary_trees import materialize_snapcols

            return materialize_snapcols(self.read_blob, ref)
        if ref.get("t") != "tree":
            return ref  # legacy single-blob summary
        from ..service.summary_trees import materialize_tree

        return materialize_tree(self.read_blob,
                                {"k": "tree", "id": version["tree_id"]})

    def read_blob(self, blob_id: str) -> bytes:
        return self._blobs.get(blob_id)

    def write_blob(self, content: bytes) -> str:
        return self._blobs.put(content)

    # ------------------------------------------------------------- uploads

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        from ..protocol.summary import (
            SummaryObject,
            SummaryTree,
            is_summary_wire,
            summary_from_wire,
        )

        if is_summary_wire(summary):
            summary = summary_from_wire(summary)
        if isinstance(summary, SummaryTree):
            parent_root = self._version_root_ref(parent)
            root_ref = self._upload_obj(summary, parent_root)
            tree_id = root_ref["id"]
        else:
            # legacy monolithic dict
            tree_id = self.write_blob(json.dumps(summary).encode())
        n = len(self._db.collection(self._versions_col))
        version_id = f"v{n}"
        record = {"n": n, "tree_id": tree_id, "parent": parent}
        self._db.upsert(self._versions_col, version_id, record)
        hook = getattr(self._server, "on_version_uploaded", None)
        if hook is not None:
            # split-service composition: the external scribe process
            # learns of uploads through this announcement (it has no
            # view of this process's db)
            hook(self._tenant, self._doc, version_id, record)
        return version_id

    def _version_root_ref(self, version_id: Optional[str]) -> Optional[dict]:
        if version_id is None:
            return None
        v = self._db.find_one(self._versions_col, version_id)
        if v is None:
            return None
        return {"k": "tree", "id": v["tree_id"]}

    def _upload_obj(self, obj, parent_root: Optional[dict]) -> dict:
        from ..service.summary_trees import upload_summary_obj

        return upload_summary_obj(self._blobs, obj, parent_root, self._stats)


class LocalDocumentService(DocumentService):
    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        self._server = server
        self._tenant = tenant_id
        self._doc = document_id

    def connect_to_delta_stream(self, details: Any = None) -> LocalDeltaConnection:
        return LocalDeltaConnection(self._server.connect(self._tenant, self._doc, details))

    def connect_to_delta_storage(self) -> LocalDeltaStorage:
        return LocalDeltaStorage(self._server, self._tenant, self._doc)

    def connect_to_storage(self):
        return self._server.storage(self._tenant, self._doc)

    def history(self):
        from .history import LocalHistoryClient

        return LocalHistoryClient(self._server, self._tenant, self._doc)


class LocalDocumentServiceFactory(DocumentServiceFactory):
    def __init__(self, server: LocalServer):
        self._server = server

    def create_document_service(
        self, tenant_id: str, document_id: str
    ) -> LocalDocumentService:
        return LocalDocumentService(self._server, tenant_id, document_id)
