"""Local driver: client stack ⇄ in-proc LocalServer, no network.

Ref: packages/drivers/local-driver (localDocumentService.ts,
localDocumentDeltaConnection.ts) — the test backbone binding the REAL
client stack to the REAL service lambdas in one process (SURVEY §4).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

from ..service.core import summary_versions_collection
from ..service.local_server import LocalServer, ServerConnection
from .definitions import (
    DocumentDeltaConnection,
    DocumentDeltaStorage,
    DocumentService,
    DocumentServiceFactory,
    DocumentStorage,
)


class LocalDeltaConnection(DocumentDeltaConnection):
    def __init__(self, conn: ServerConnection):
        self._conn = conn
        self.client_id = conn.client_id
        self.initial_sequence_number = conn.initial_sequence_number
        self.on_disconnect = None

    # event callbacks proxy straight to the server connection's buffered
    # handler slots
    on_op = property(
        lambda self: self._conn.on_op,
        lambda self, cb: setattr(self._conn, "on_op", cb))
    on_nack = property(
        lambda self: self._conn.on_nack,
        lambda self, cb: setattr(self._conn, "on_nack", cb))
    on_signal = property(
        lambda self: self._conn.on_signal,
        lambda self, cb: setattr(self._conn, "on_signal", cb))

    def submit(self, messages) -> None:
        self._conn.submit(messages)

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        self._conn.submit_signal(content, type)

    def close(self) -> None:
        self._conn.disconnect()
        if self.on_disconnect:
            self.on_disconnect("client closed connection")


class LocalDeltaStorage(DocumentDeltaStorage):
    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        self._server = server
        self._tenant = tenant_id
        self._doc = document_id

    def get_deltas(self, from_seq: int, to_seq: int):
        return self._server.get_deltas(self._tenant, self._doc, from_seq, to_seq)


class LocalStorage(DocumentStorage):
    """Content-addressed blob + versioned summary-tree store on the server
    db (the gitrest/historian analog; trees/blobs keyed by sha, versions =
    the ref chain)."""

    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        self._db = server.db
        self._versions_col = summary_versions_collection(tenant_id, document_id)
        self._blobs_col = "blobs"

    def get_versions(self, count: int = 1) -> list[dict]:
        """Only scribe-ACKED versions are boot sources (the git-ref analog:
        scribe committing a summary is what makes it a version); uploads
        that were never validated, or were nacked, are invisible here."""
        versions = sorted(
            (v for v in self._db.collection(self._versions_col).values()
             if v.get("acked")),
            key=lambda v: v["n"],
            reverse=True,
        )
        return [{"id": v["_id"], "tree_id": v["tree_id"]} for v in versions[:count]]

    def get_snapshot_tree(self, version: Optional[dict] = None) -> Optional[dict]:
        if version is None:
            versions = self.get_versions(1)
            if not versions:
                return None
            version = versions[0]
        blob = self.read_blob(version["tree_id"])
        return json.loads(blob.decode())

    def read_blob(self, blob_id: str) -> bytes:
        doc = self._db.find_one(self._blobs_col, blob_id)
        if doc is None:
            raise KeyError(f"unknown blob {blob_id}")
        return bytes.fromhex(doc["hex"])

    def write_blob(self, content: bytes) -> str:
        blob_id = hashlib.sha1(content).hexdigest()
        self._db.upsert(self._blobs_col, blob_id, {"hex": content.hex()})
        return blob_id

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        tree_id = self.write_blob(json.dumps(summary).encode())
        n = len(self._db.collection(self._versions_col))
        version_id = f"v{n}"
        self._db.upsert(
            self._versions_col,
            version_id,
            {"n": n, "tree_id": tree_id, "parent": parent},
        )
        return version_id


class LocalDocumentService(DocumentService):
    def __init__(self, server: LocalServer, tenant_id: str, document_id: str):
        self._server = server
        self._tenant = tenant_id
        self._doc = document_id

    def connect_to_delta_stream(self, details: Any = None) -> LocalDeltaConnection:
        return LocalDeltaConnection(self._server.connect(self._tenant, self._doc, details))

    def connect_to_delta_storage(self) -> LocalDeltaStorage:
        return LocalDeltaStorage(self._server, self._tenant, self._doc)

    def connect_to_storage(self) -> LocalStorage:
        return LocalStorage(self._server, self._tenant, self._doc)


class LocalDocumentServiceFactory(DocumentServiceFactory):
    def __init__(self, server: LocalServer):
        self._server = server

    def create_document_service(
        self, tenant_id: str, document_id: str
    ) -> LocalDocumentService:
        return LocalDocumentService(self._server, tenant_id, document_id)
