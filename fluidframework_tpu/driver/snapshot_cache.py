"""Driver-side snapshot cache — the odsp-driver lesson.

Ref: packages/drivers/odsp-driver/src/odspCache.ts — the reference's
production driver caches version→tree→blob results per document so a
re-boot (page reload, new container for the same doc) issues no storage
round trips; correctness comes from delta catch-up (booting from an
older summary is always safe — the op stream brings the container
current), and the cache entry is invalidated when a newer summary is
committed (a summaryAck on the live stream).

Shared across a factory's documents; stats make the contract testable:
a second boot of an unchanged doc must serve entirely from here.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

DEFAULT_TTL_S = 120.0

#: cap on cached snapshot chunks (content-addressed, so eviction only
#: costs a refetch; insertion-order eviction approximates LRU well
#: enough because chunk reuse clusters on the most recent generations)
MAX_CHUNKS = 4096


class SnapshotCache:
    """``ttl_s`` bounds how stale an entry can get when no live
    connection of this factory observes the invalidating summaryAck
    (doc open in another process only): past the TTL the entry is a
    miss. Within the TTL a boot from a superseded summary is still
    correct as long as the service retains the covering ops
    (config.log_retention_ops margin)."""

    def __init__(self, ttl_s: float = DEFAULT_TTL_S,
                 max_chunks: int = MAX_CHUNKS):
        self._entries: dict[tuple, dict] = {}
        self._epochs: dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._ttl = ttl_s
        self.stats = {"hits": 0, "misses": 0, "invalidations": 0}
        # content-addressed snapcols chunks, shared across docs AND
        # versions (identical chunk → identical hash): summary
        # invalidation does NOT clear these — unchanged chunks of the
        # NEW version are exactly the reuse this cache exists for
        self._chunks: dict[str, bytes] = {}
        self._max_chunks = max_chunks
        self.chunk_stats = {"hits": 0, "misses": 0}

    def epoch(self, tenant_id: str, document_id: str) -> int:
        """Read BEFORE fetching what you intend to put: a put whose
        epoch is stale (an invalidation raced the fetch) is dropped
        instead of resurrecting the superseded snapshot."""
        with self._lock:
            return self._epochs.get((tenant_id, document_id), 0)

    def get(self, tenant_id: str, document_id: str) -> Optional[dict]:
        """``{"version": {...}, "tree": Any}`` or None."""
        with self._lock:
            key = (tenant_id, document_id)
            entry = self._entries.get(key)
            if entry is not None and \
                    time.monotonic() - entry["at"] > self._ttl:
                del self._entries[key]
                entry = None
            if entry is None:
                self.stats["misses"] += 1
                return None
            self.stats["hits"] += 1
            return entry

    def put(self, tenant_id: str, document_id: str, version: dict,
            tree: Any, epoch: Optional[int] = None) -> None:
        with self._lock:
            key = (tenant_id, document_id)
            if epoch is not None and self._epochs.get(key, 0) != epoch:
                return  # an invalidation raced the fetch: data is stale
            self._entries[key] = {"version": version, "tree": tree,
                                  "at": time.monotonic()}

    def get_chunk(self, chunk_hash: str) -> Optional[bytes]:
        with self._lock:
            data = self._chunks.get(chunk_hash)
            self.chunk_stats["hits" if data is not None else "misses"] += 1
            return data

    def put_chunk(self, chunk_hash: str, data: bytes) -> None:
        with self._lock:
            while len(self._chunks) >= self._max_chunks:
                del self._chunks[next(iter(self._chunks))]
            self._chunks[chunk_hash] = data

    def chunk_hashes(self) -> list[str]:
        """Hashes on hand — the ``have`` list a booting client sends so
        the server skips pushing chunks it already holds."""
        with self._lock:
            return list(self._chunks)

    def invalidate(self, tenant_id: str, document_id: str) -> None:
        """A newer summary committed: the cached boot source is stale
        (still CORRECT to boot from — ops catch up — but the next boot
        should not replay an ever-growing tail, and with retention on,
        must not outlive the covering ops)."""
        with self._lock:
            key = (tenant_id, document_id)
            self._epochs[key] = self._epochs.get(key, 0) + 1
            if self._entries.pop(key, None) is not None:
                self.stats["invalidations"] += 1
