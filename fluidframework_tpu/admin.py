"""Admin CLI: tenant CRUD + per-doc pipeline status over the admin RPCs.

Ref: server/admin (the reference's management portal) and riddler's
tenantManager REST (routerlicious/src/riddler/tenantManager.ts) — here
one CLI against the ordering core's admin frames (front_end.py
``_handle_admin``; gateways relay nothing admin — point this at a core).

    python -m fluidframework_tpu.admin status TENANT DOC --port P
    python -m fluidframework_tpu.admin docs --port P
    python -m fluidframework_tpu.admin tenants --port P
    python -m fluidframework_tpu.admin tenant-add ID SECRET --port P
    python -m fluidframework_tpu.admin tenant-rm ID --port P
    python -m fluidframework_tpu.admin monitor --port P [--interval S]
                                               [--count N]
    python -m fluidframework_tpu.admin metrics --port P [--history]
                                               [--name METRIC]
    python -m fluidframework_tpu.admin journal --port P [-n N]
        [--kind PREFIX] [--doc DOC] [--part K] [--fleet] [--chain ID]
    python -m fluidframework_tpu.admin flight dump --port P [--reason R]
    python -m fluidframework_tpu.admin bundle --out DIR --port P
    python -m fluidframework_tpu.admin --port P slo
    python -m fluidframework_tpu.admin placement --port P [--fleet]
    python -m fluidframework_tpu.admin placement heat --port P
    python -m fluidframework_tpu.admin placement boot --port P [--fleet]
    python -m fluidframework_tpu.admin placement rebalance --port P
    python -m fluidframework_tpu.admin placement drain CORE --port P
    python -m fluidframework_tpu.admin migrate TENANT DOC TARGET --port P

``placement`` prints the core's view of the routing plane: the epoch
table (global epoch + per-partition owner/addr/epoch), the core
membership (active/draining/drained), the partitions this core serves,
the lease liveness view, and the ``placement.*`` counter snapshot
(``--fleet`` sums the counters across every reachable core).
``placement heat`` fans out to every member and prints the windowed
per-partition heat table the rebalancer plans from; ``placement boot``
shows a cold-starting core's rehydration progress — docs booted vs
still pending per owned partition, the admission executor's state
(rate/burst/tokens, parked boots) and the ``boot.*`` counters proving
the lazy contract (``--fleet`` fans out to every member and prints the
fleet totals: the operator's one-stop view mid boot storm); ``placement
rebalance`` shows the self-driving loop's status (last plan,
suppression counts, flap count); ``placement drain CORE`` marks a
member draining — the loop evacuates its partitions and flips it to
drained for clean decommission. ``migrate`` triggers a live migration
of the doc's partition to the core at TARGET (a ``host:port`` address
as published in the epoch table) — point it at the CURRENT owner.

``slo`` prints one row per armed SLO spec — windowed p99 vs budget,
state (ok/warn/violated), burn progress — plus whether SLO-burn
shedding is armed (front_end ``--slo`` / ``--no-shed``).

``journal`` tails the core's control-plane audit journal
(obs/journal.py): every epoch bump, lease transfer, migration phase,
rebalance decision (suppressions included), SLO transition and flight
dump, each entry causally linked to what triggered it. ``--fleet``
fans out to every registered core and merges the journals ordered by
(epoch, ts) — the epoch table is the fleet's shared logical clock, so
a cross-core migration reads as one connected chain even under
wall-clock skew. ``--chain ID`` prints just the causal chain ending at
the given entry id, root first. ``metrics --history`` prints the
windowed series' retained history rings (~15 min at 10 s resolution)
instead of the instantaneous scrape. ``flight dump`` forces a flight-
recorder dump now and journals it. ``bundle --out DIR`` snapshots the
whole debug surface — placement table, per-core scrape + history +
journal tail + SLO/rebalancer status, reachable flight dumps — into
DIR for ``tools/doctor.py`` to triage offline.

``monitor`` is the service-monitor role (ref: server/service-monitor):
each tick it measures the front door's ping RTT (event-loop health) and
prints one line per live doc — seq, msn, connected clients, applier
lag (seq - applierSeq; "-" when no applier stage reports).

``--admin-secret`` must match the core's ``--admin-secret`` whenever one
is configured (and always on a tenancy-enforcing deployment).
"""

from __future__ import annotations

import argparse
import json
import sys


def _request(args, frame: dict) -> dict:
    from .driver.network import _Transport

    t = _Transport(args.host, args.port, timeout=10.0)
    try:
        return t.request(_frame(args, frame))
    finally:
        t.close()


def _monitor(args) -> int:
    """The service-monitor role: ping RTT + per-doc pipeline lag, one
    block per tick on stdout (ref: server/service-monitor)."""
    import time

    from .driver.network import _Transport

    t = _Transport(args.host, args.port, timeout=10.0)
    try:
        tick = 0
        while True:
            tick += 1
            t0 = time.perf_counter()
            docs = t.request(_frame(args, {"t": "admin_docs"}))["docs"]
            rtt_ms = (time.perf_counter() - t0) * 1e3
            print(f"tick {tick} rtt {rtt_ms:.1f}ms docs {len(docs)}")
            for d in docs:
                tenant, _, doc = d.partition("/")
                st = t.request(_frame(args, {
                    "t": "admin_status", "tenant": tenant,
                    "doc": doc}))["status"]
                if st is None:
                    continue
                lag = ("-" if st["applierSeq"] is None
                       else st["seq"] - st["applierSeq"])
                print(f"  {d}: seq {st['seq']} msn {st['msn']} "
                      f"clients {len(st['clients'])} applier_lag {lag}")
            if args.count and tick >= args.count:
                return 0
            time.sleep(args.interval)
    finally:
        t.close()


def _frame(args, frame: dict) -> dict:
    if args.admin_secret:
        frame["secret"] = args.admin_secret
    return frame


def _peer_request(args, addr: str, frame: dict) -> dict:
    """One admin RPC against a peer core at ``addr`` (host:port from the
    epoch table's membership) — the CLI-side fan-out for `placement
    heat`, sharing the deployment-wide admin secret."""
    from .driver.network import _Transport

    host, _, port = addr.rpartition(":")
    t = _Transport(host or "127.0.0.1", int(port), timeout=10.0)
    try:
        return t.request(_frame(args, dict(frame)))
    finally:
        t.close()


def _placement(args) -> int:
    if args.action == "drain":
        if not args.core:
            print("drain requires a CORE owner id "
                  "(see `admin placement` membership)")
            return 1
        reply = _request(args, {"t": "admin_placement_drain",
                                "owner": args.core})
        print(f"core {reply['owner']} marked draining: the rebalancer "
              "evacuates its partitions, then flips it to drained")
        return 0
    if args.action == "rebalance":
        frame = {"t": "admin_rebalance_status"}
        if args.fleet:
            frame["fleet"] = True
        st = _request(args, frame)["rebalance"]
        if not st.get("armed"):
            print("rebalancer: disarmed (start the core with --rebalance)")
            return 1
        drain = (" DRAINED" if st.get("drained")
                 else " draining" if st.get("draining") else "")
        print(f"rebalancer: armed on {st['owner']}{drain}  "
              f"tick {st['tick_s']}s dwell {st['dwell_s']}s "
              f"budget {st['budget']} improvement {st['improvement']}")
        print(f"  flaps {st['flaps']}  last_error {st['last_error']}")
        plan = st.get("last_plan")
        if plan is not None:
            print(f"  last plan: {len(plan['moves'])} move(s)  "
                  f"spread {plan['spread_before']} -> "
                  f"{plan['spread_after']}  "
                  f"suppressed hysteresis={plan['suppressed_hysteresis']} "
                  f"budget={plan['suppressed_budget']}")
            for m in plan["moves"]:
                print(f"    part {m['k']}: {m['src']} -> {m['dst']} "
                      f"(load {m['load']})")
        for h in st.get("history", []):
            print(f"  moved part {h['k']}: {h['src']} -> {h['dst']}")
        for name, v in sorted(st.get("fleet_counters", {}).items()):
            print(f"  {name} {v}")
        return 0
    if args.action == "boot":
        return _placement_boot(args)
    frame = {"t": "admin_placement"}
    if args.fleet:
        frame["fleet"] = True
    reply = _request(args, frame)
    pl = reply.get("placement")
    if pl is None:
        print("not a sharded core (no placement plane)")
        return 1
    if args.action == "heat":
        # per-core fan-out: every registered member answers for its own
        # windowed series (heat lives in each core's process registry)
        for owner, row in sorted(pl.get("cores", {}).items()):
            try:
                heat = _peer_request(args, row["addr"],
                                     {"t": "admin_core_heat"})["heat"]
            except (OSError, ValueError, RuntimeError) as e:
                print(f"core {owner} @ {row['addr']} [{row['state']}] "
                      f"unreachable: {e}")
                continue
            total = sum(h["ops"] for h in heat["parts"].values())
            drain = " (draining)" if heat["draining"] else ""
            print(f"core {owner} @ {row['addr']} [{row['state']}]"
                  f"{drain}  total {total:.1f} ops/s "
                  f"(window {heat['window_s']}s)")
            for k in sorted(heat["parts"], key=int):
                h = heat["parts"][k]
                print(f"  part {k}: {h['ops']:.1f} ops/s  "
                      f"{h['bytes']:.0f} B/s")
        return 0
    print(f"core {pl['owner']} @ {pl['address']}  "
          f"epoch {pl['epoch']}  owns {pl['owned']}")
    for owner, row in sorted(pl.get("cores", {}).items()):
        print(f"  core {owner} @ {row['addr']} [{row['state']}]")
    for k in sorted(pl["parts"], key=int):
        part = pl["parts"][k]
        print(f"  part {k}: {part['owner']} @ {part['addr']} "
              f"(epoch {part['epoch']})")
    for k, row in sorted(pl["leases"].items()):
        print(f"  lease {k}: {row}")
    for name, v in sorted(pl["counters"].items()):
        print(f"  {name} {v}")
    return 0


def _boot_row(owner: str, addr: str, boot: dict) -> tuple:
    """Print one core's rehydration progress; returns its (booted,
    pending, counters) contribution to the fleet totals."""
    ex = boot.get("executor") or {}
    booted = sum(p["docs_booted"] for p in boot.get("parts", []))
    pending = sum(p["docs_pending"] for p in boot.get("parts", []))
    print(f"core {boot.get('owner', owner)} @ {addr}  "
          f"booted {booted} pending {pending}  "
          f"executor rate {ex.get('rate')}/s burst {ex.get('burst')} "
          f"tokens {ex.get('tokens')} parked {ex.get('parked', 0)}")
    for part in boot.get("parts", []):
        print(f"  part {part['part']}: booted {part['docs_booted']} "
              f"pending {part['docs_pending']}")
    for name, v in sorted((boot.get("counters") or {}).items()):
        print(f"  {name} {v}")
    return booted, pending, boot.get("counters") or {}


def _placement_boot(args) -> int:
    """Rehydration progress (``placement boot``): how far a cold core
    is through its boot storm — per-partition booted/pending docs, the
    admission executor's bucket, and the ``boot.*`` counters. With
    ``--fleet``, fans out to every member and sums."""
    if not args.fleet:
        reply = _request(args, {"t": "admin_boot_status"})
        boot = reply.get("boot")
        if boot is None:
            print("not a sharded core (no boot plane)")
            return 1
        _boot_row("local", f"{args.host}:{args.port}", boot)
        return 0
    totals: dict = {}
    booted = pending = reached = 0
    for owner, addr in _fleet_cores(args).items():
        try:
            boot = _peer_request(
                args, addr, {"t": "admin_boot_status"})["boot"]
        except (OSError, ValueError, RuntimeError) as e:
            print(f"core {owner} @ {addr} unreachable: {e}")
            continue
        b, p, counters = _boot_row(owner, addr, boot)
        booted += b
        pending += p
        reached += 1
        for name, v in counters.items():
            totals[name] = totals.get(name, 0) + v
    print(f"fleet: {reached} core(s)  booted {booted} pending {pending}")
    for name, v in sorted(totals.items()):
        print(f"  {name} {v}")
    if totals.get("boot.part.full_replay", 0):
        print("WARNING: boot.part.full_replay nonzero — some doc paid "
              "a whole-log replay (missing summary or checkpoint?)")
        return 1
    return 0


def _fmt_entry(e: dict) -> str:
    import datetime

    try:
        ts = datetime.datetime.fromtimestamp(
            e.get("ts", 0)).strftime("%H:%M:%S.%f")[:-3]
    except (OverflowError, OSError, ValueError):
        ts = str(e.get("ts"))
    labels = " ".join(f"{k}={v}" for k, v in
                      sorted((e.get("labels") or {}).items()))
    cause = f" <- {e['cause']}" if e.get("cause") else ""
    epoch = e.get("epoch")
    return (f"{ts} e{epoch if epoch is not None else '-'} "
            f"[{e.get('id')}] {e.get('kind')}{cause}  {labels}")


def _journal_frame(args) -> dict:
    frame = {"t": "admin_journal", "n": args.n}
    if args.kind:
        frame["kind"] = args.kind
    if args.doc:
        frame["doc"] = args.doc
    if args.part is not None:
        frame["part"] = args.part
    return frame


def _fleet_cores(args) -> dict:
    """owner → addr for every registered member (falls back to the
    queried core alone on an unsharded deployment). Every member is
    captured — an ex-owner's journal is exactly what a migration
    post-mortem needs — but the BUNDLE marks members holding no
    partition as unrouted so the doctor's reachability rules skip
    them (membership rows never expire; a kill -9'd core's stale row
    must not read as an outage after its parts were re-claimed)."""
    pl = _request(args, {"t": "admin_placement"}).get("placement")
    if pl is None or not pl.get("cores"):
        return {"local": f"{args.host}:{args.port}"}
    return {owner: row["addr"]
            for owner, row in sorted(pl["cores"].items())}


def _journal_cmd(args) -> int:
    from .obs.journal import causal_chain, merge_entries

    if args.fleet:
        per_core = []
        for owner, addr in _fleet_cores(args).items():
            try:
                j = _peer_request(args, addr, _journal_frame(args))[
                    "journal"]
            except (OSError, ValueError, RuntimeError) as e:
                print(f"# core {owner} @ {addr} unreachable: {e}")
                continue
            per_core.append(j["entries"])
        entries = merge_entries(per_core)
    else:
        j = _request(args, _journal_frame(args))["journal"]
        if not j["armed"] and not j["entries"]:
            print("journal: disarmed on this core (sharded cores arm "
                  "automatically; single cores need --journal PATH)")
            return 1
        entries = j["entries"]
    if args.chain:
        entries = causal_chain(entries, args.chain)
        if not entries:
            print(f"no entry {args.chain!r} in the fetched window "
                  "(raise -n or check the id)")
            return 1
    for e in entries:
        print(_fmt_entry(e))
    return 0


def _metrics_history(args) -> int:
    reply = _request(args, {"t": "admin_metrics_history",
                            "name": args.name})
    # points carry the CORE's monotonic clock; rebase onto wall time
    # through the paired now stamps the RPC ships
    offset = reply["now_wall"] - reply["now_mono"]
    import datetime

    for name, series in sorted(reply["history"].items()):
        for s in series:
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(s["labels"].items()))
            print(f"{name}{{{labels}}}")
            for pt in s["points"]:
                wall = pt["t"] + offset
                hhmm = datetime.datetime.fromtimestamp(
                    wall).strftime("%H:%M:%S")
                mean = pt["sum"] / pt["count"] if pt["count"] else 0.0
                print(f"  {hhmm} count {pt['count']} "
                      f"mean {mean:.3f} max {pt['max']:.3f}")
    return 0


def _bundle(args) -> int:
    """Snapshot the fleet's debug surface into ``--out`` (the operator
    door tools/doctor.py triages from)."""
    import os
    import shutil
    import time

    out = args.out
    os.makedirs(out, exist_ok=True)
    manifest: dict = {"created": time.time(),
                      "entry": f"{args.host}:{args.port}", "cores": {}}
    pl = _request(args, {"t": "admin_placement"}).get("placement")
    if pl is not None:
        with open(os.path.join(out, "placement.json"), "w") as f:
            json.dump(pl, f, indent=2, default=str)
    routed = {p.get("owner")
              for p in ((pl or {}).get("parts") or {}).values()}
    cores = _fleet_cores(args)
    for owner, addr in cores.items():
        cdir = os.path.join(out, "cores", owner)
        os.makedirs(cdir, exist_ok=True)
        row: dict = {"addr": addr}
        if pl is not None and owner not in routed:
            # owns no partition at capture time: journals still matter
            # (migration chains live on ex-owners), but a failed
            # capture of a stale membership row is not an outage
            row["routed"] = False
        manifest["cores"][owner] = row
        try:
            scrape = _peer_request(
                args, addr, {"t": "admin_metrics_scrape"})["scrape"]
            with open(os.path.join(cdir, "scrape.prom"), "w") as f:
                f.write(scrape)
            counters = _peer_request(
                args, addr, {"t": "admin_counters"})["counters"]
            with open(os.path.join(cdir, "counters.json"), "w") as f:
                json.dump(counters, f, indent=2, default=str)
            hist = _peer_request(args, addr,
                                 {"t": "admin_metrics_history"})
            with open(os.path.join(cdir, "history.json"), "w") as f:
                json.dump(hist, f, default=str)
            slo = _peer_request(args, addr, {"t": "admin_slo_status"})
            with open(os.path.join(cdir, "slo.json"), "w") as f:
                json.dump({"slos": slo.get("slos", []),
                           "shedding": slo.get("shedding")}, f, indent=2)
            reb = _peer_request(
                args, addr,
                {"t": "admin_rebalance_status"})["rebalance"]
            with open(os.path.join(cdir, "rebalance.json"), "w") as f:
                json.dump(reb, f, indent=2, default=str)
            boot = _peer_request(
                args, addr, {"t": "admin_boot_status"}).get("boot")
            if boot is not None:
                with open(os.path.join(cdir, "boot.json"), "w") as f:
                    json.dump(boot, f, indent=2, default=str)
            j = _peer_request(args, addr, {"t": "admin_journal",
                                           "n": 1000})["journal"]
            row["journal_armed"] = j["armed"]
            with open(os.path.join(cdir, "journal.jsonl"), "w") as f:
                for e in j["entries"]:
                    f.write(json.dumps(e, separators=(",", ":"),
                                       default=str) + "\n")
            # flight dumps the journal references, when their paths are
            # readable from here (same-host deployments — the common
            # debug case; remote cores just keep the path breadcrumbs)
            copied = 0
            for e in j["entries"]:
                if e.get("kind") != "flight.dump":
                    continue
                path = (e.get("labels") or {}).get("path")
                if path and os.path.isfile(path):
                    fdir = os.path.join(cdir, "flight")
                    os.makedirs(fdir, exist_ok=True)
                    try:
                        shutil.copy(path, fdir)
                        copied += 1
                    except OSError:
                        pass
            row["flight_dumps_copied"] = copied
        except (OSError, ValueError, RuntimeError) as e:
            row["error"] = str(e)
            print(f"# core {owner} @ {addr} partially captured: {e}")
    # static-contract status of the build that captured the bundle:
    # fluidlint --json at the repo root, so a triage reads lint state
    # (including which concurrency waivers are in force) next to the
    # journal and metrics. Deployed captures without the repo checkout
    # just skip it — doctor treats a missing lint.json as "not captured".
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    if os.path.isdir(os.path.join(repo_root, "tools", "fluidlint")):
        import subprocess
        import sys as _sys

        # every pass except jaxpr: tracing the kernels costs ~20 s and
        # an incident-time capture should not — the jaxpr contracts
        # can't drift without a code change CI already gated anyway
        passes = [a for p in ("layers", "wire", "hygiene",
                              "metric-name", "storage", "journal-kind",
                              "concurrency")
                  for a in ("--pass", p)]
        try:
            r = subprocess.run(
                [_sys.executable, "-m", "tools.fluidlint", "--json",
                 *passes],
                cwd=repo_root, capture_output=True, text=True,
                timeout=120,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            lint = json.loads(r.stdout)
        except (OSError, ValueError, subprocess.TimeoutExpired) as e:
            print(f"# lint capture skipped: {e}")
        else:
            with open(os.path.join(out, "lint.json"), "w") as f:
                json.dump(lint, f, indent=2)
            manifest["lint_clean"] = lint.get("clean")
    with open(os.path.join(out, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    print(f"bundle written to {out} ({len(cores)} core(s)); triage "
          f"with: python tools/doctor.py {out}")
    return 0


def _history_cmd(args) -> int:
    """Doc history plane views/actions over the ``history_*`` doors.
    These ride the tenant token (doc scopes), not the admin secret —
    forking or time-traveling a doc is a data-plane act."""
    from .driver.network import _Transport

    t = _Transport(args.host, args.port, timeout=30.0)
    try:
        base = {"tenant": args.tenant, "doc": args.doc,
                "token": args.token}
        if args.action == "log":
            rid, reply = t.request_rid(dict(
                base, t="history_log", count=args.n or None))
            commits = t.take_history(rid)
            heads = {cid: name for name, cid in
                     (reply.get("refs") or {}).items()}
            for c in commits:
                head = heads.get(c["id"])
                fork_of = (c.get("extra") or {}).get("fork_of")
                line = (f"{c['id']} {c['version']} @seq {c['base_seq']} "
                        f"chunks {len(c['chunk_ids'])}")
                if head:
                    line += f" [{head}]"
                if fork_of:
                    line += f" fork-of {fork_of['doc']}@{fork_of['seq']}"
                print(line)
            return 0
        if args.action == "at":
            if args.seq is None:
                print("history at requires --seq", file=sys.stderr)
                return 2
            at = t.request(dict(base, t="history_at",
                                seq=args.seq))["at"]
            print(json.dumps(at, indent=2))
            return 0
        if args.action == "fork":
            res = t.request(dict(base, t="history_fork", seq=args.seq,
                                 new_doc=args.new_doc))["fork"]
            print(f"forked {args.tenant}/{args.doc}@{res['fork_seq']} "
                  f"-> {res['doc']} (base {res['version']} seq "
                  f"{res['base_seq']}, {res['shared_chunks']} shared "
                  f"chunk(s), {res['tail_ops']} tail op(s))")
            return 0
        # integrate: args.doc IS the fork
        res = t.request(dict(base, t="history_integrate"))["integrate"]
        print(f"integrated {res['ops']} op(s) from {res['fork']} "
              f"into {res['parent']}")
        return 0
    finally:
        t.close()


def _print_core_health(h: dict, indent: str = "") -> None:
    comps = h.get("components") or {}
    doors = ((h.get("probes") or {}).get("doors") or {})
    armed = h.get("armed", True)
    print(f"{indent}core {h.get('core') or '?'}: "
          f"{str(h.get('verdict', 'unknown')).upper()}"
          + ("" if armed else "  (health plane unarmed)"))
    for name, c in sorted(comps.items()):
        state = c.get("state", "?")
        mark = {"ok": " ", "degraded": "~",
                "critical": "!"}.get(state, "?")
        line = f"{indent}  {mark} {name:<10} {state}"
        if c.get("streak"):
            line += f"  (streak {c['streak']})"
        print(line)
        for reason in c.get("reasons", []):
            print(f"{indent}      - {reason}")
    if doors:
        print(f"{indent}  doors: " + "  ".join(
            f"{d}={v.get('last_ms', 0):.1f}ms"
            + ("" if v.get("ok")
               else f"[FAIL x{v.get('consec_failures')}]")
            for d, v in sorted(doors.items())))
    for r in h.get("slo_burn") or []:
        print(f"{indent}  burn: {r.get('slo')} [{r.get('state')}] "
              f"p99 {r.get('p99_ms')}ms / {r.get('budget_ms')}ms")
    for reason in h.get("reasons") or []:
        # synthetic row for an unreachable peer (no components)
        print(f"{indent}  - {reason}")


def _health_cmd(args) -> int:
    """`admin health [--fleet]`: the live go/no-go verdict. Exit 0
    only on OK — CI and the rolling-upgrade loop gate on the code, the
    way doctor.py gates on a quiet bundle."""
    frame = {"t": "admin_health"}
    if args.fleet:
        frame["fleet"] = 1
    reply = _request(args, frame)
    h = reply.get("health") or {}
    if args.fleet:
        verdict = str(h.get("verdict", "unknown"))
        cores = h.get("cores") or {}
        print(f"fleet: {verdict.upper()}  ({len(cores)} core(s))")
        for _owner, core_h in sorted(cores.items()):
            _print_core_health(core_h, indent="  ")
        return 0 if verdict == "ok" else 1
    _print_core_health(h)
    return 0 if h.get("verdict") == "ok" else 1


def main(argv=None) -> int:
    # the connection options are accepted before OR after the
    # subcommand (`admin --port P slo` and `admin slo --port P` both
    # work): the sub-level copies default to SUPPRESS so they override
    # the main-level values only when actually given
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--host", default=argparse.SUPPRESS)
    common.add_argument("--port", type=int, default=argparse.SUPPRESS)
    common.add_argument("--admin-secret", default=argparse.SUPPRESS)
    p = argparse.ArgumentParser(description="fluid service admin")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--admin-secret", default=None)
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("status", help="one doc's pipeline status",
                       parents=[common])
    s.add_argument("tenant")
    s.add_argument("doc")
    sub.add_parser("docs", help="list live docs", parents=[common])
    sub.add_parser("tenants", help="list registered tenants",
                   parents=[common])
    s = sub.add_parser("tenant-add", help="register a tenant",
                       parents=[common])
    s.add_argument("id")
    s.add_argument("secret")
    s = sub.add_parser("tenant-rm", help="deregister a tenant",
                       parents=[common])
    s.add_argument("id")
    s = sub.add_parser("monitor", help="live per-doc status ticker",
                       parents=[common])
    s.add_argument("--interval", type=float, default=2.0)
    s.add_argument("--count", type=int, default=0,
                   help="ticks before exiting (0 = forever)")
    s = sub.add_parser("metrics", parents=[common],
                       help="Prometheus text scrape of the core's "
                            "registry (--history: retained windowed "
                            "series rings instead)")
    s.add_argument("--history", action="store_true",
                   help="print the ~15 min windowed-series history "
                        "rings instead of the instantaneous scrape")
    s.add_argument("--name", default=None,
                   help="restrict --history to one windowed metric")
    s = sub.add_parser("journal", parents=[common],
                       help="tail the control-plane audit journal "
                            "(epoch bumps, leases, migrations, "
                            "rebalance decisions, SLO transitions)")
    s.add_argument("-n", type=int, default=100,
                   help="entries per core (default 100)")
    s.add_argument("--kind", default=None,
                   help="kind prefix filter (e.g. migration.)")
    s.add_argument("--doc", default=None, help="doc label filter")
    s.add_argument("--part", type=int, default=None,
                   help="partition label filter")
    s.add_argument("--fleet", action="store_true",
                   help="merge every core's journal ordered by "
                        "(epoch, ts)")
    s.add_argument("--chain", default=None, metavar="ID",
                   help="print the causal chain ending at entry ID, "
                        "root first")
    s = sub.add_parser("flight", parents=[common],
                       help="flight recorder controls: `flight dump` "
                            "forces a dump now and journals it")
    s.add_argument("action", choices=["dump"])
    s.add_argument("--reason", default=None,
                   help="free-text reason recorded in the journal")
    s = sub.add_parser("bundle", parents=[common],
                       help="capture a fleet debug bundle (placement, "
                            "scrapes, history, journals, SLO status, "
                            "flight dumps) into --out")
    s.add_argument("--out", required=True, metavar="DIR")
    sub.add_parser("slo", parents=[common],
                   help="armed SLO specs: windowed p99 vs "
                        "budget, state, burn progress")
    s = sub.add_parser("health", parents=[common],
                       help="live health plane: the streaming "
                            "doctor's verdict — canary probe doors, "
                            "per-component states with reasons; exit "
                            "0 only when OK (the go/no-go gate)")
    s.add_argument("--fleet", action="store_true",
                   help="fan out to every core in the epoch table; "
                        "worst verdict wins and an unreachable core "
                        "is critical")
    s = sub.add_parser("placement", parents=[common],
                       help="routing plane: epoch table, membership, "
                            "owned partitions, leases, placement.* "
                            "counters; subviews: heat / rebalance / "
                            "drain CORE")
    s.add_argument("action", nargs="?", default=None,
                   choices=["heat", "rebalance", "drain", "boot"],
                   help="heat: per-core per-partition heat table; "
                        "boot: cold-start rehydration progress "
                        "(booted/pending docs, executor, boot.* "
                        "counters; --fleet sums every core); "
                        "rebalance: loop status + last plan; "
                        "drain: mark CORE draining (evacuate + "
                        "decommission)")
    s.add_argument("core", nargs="?", default=None,
                   help="core owner id (drain only)")
    s.add_argument("--fleet", action="store_true",
                   help="sum placement counters across every reachable "
                        "core instead of just the queried one")
    s = sub.add_parser("history", parents=[common],
                       help="doc history plane: commit log, fork a doc "
                            "at a seq, resolve a point-in-time read, "
                            "integrate a fork back into its parent")
    s.add_argument("action", choices=["log", "fork", "at", "integrate"])
    s.add_argument("tenant")
    s.add_argument("doc", help="the doc (for integrate: the FORK doc)")
    s.add_argument("--seq", type=int, default=None,
                   help="fork/read-at sequence number (fork default: "
                        "head)")
    s.add_argument("--new-doc", default=None,
                   help="fork target doc id (default: generated)")
    s.add_argument("-n", type=int, default=20,
                   help="commits to list (log; 0 = all)")
    s.add_argument("--token", default=None,
                   help="tenant JWT when tenancy is enforcing")
    s = sub.add_parser("migrate", parents=[common],
                       help="live-migrate a doc's partition to another "
                            "core (point --port at the current owner)")
    s.add_argument("tenant")
    s.add_argument("doc")
    s.add_argument("target", help="target core address (host:port)")
    args = p.parse_args(argv)
    if args.port is None:
        p.error("--port is required")

    if args.cmd == "monitor":
        return _monitor(args)

    if args.cmd == "status":
        reply = _request(args, {"t": "admin_status", "tenant": args.tenant,
                                "doc": args.doc})
        if reply.get("status") is None:
            print(f"no live pipeline for {args.tenant}/{args.doc}")
            return 1
        print(json.dumps(reply["status"], indent=2))
    elif args.cmd == "metrics":
        if args.history:
            return _metrics_history(args)
        reply = _request(args, {"t": "admin_metrics_scrape"})
        sys.stdout.write(reply["scrape"])
    elif args.cmd == "journal":
        return _journal_cmd(args)
    elif args.cmd == "flight":
        reply = _request(args, {"t": "admin_flight_dump",
                                "reason": args.reason})
        print(f"dumped {reply['path']} (journal {reply['journal']})")
    elif args.cmd == "bundle":
        return _bundle(args)
    elif args.cmd == "slo":
        reply = _request(args, {"t": "admin_slo_status"})
        shed = "armed" if reply.get("shedding") else "off"
        rows = reply.get("slos", [])
        print(f"shedding: {shed}  specs: {len(rows)}")
        for r in rows:
            scope = r["pair"] + (f"@{r['tenant']}" if r["tenant"] else "")
            print(f"  {r['slo']}: {scope} p99 {r['p99_ms']}ms / "
                  f"budget {r['budget_ms']}ms [{r['state']}] "
                  f"burn {r['burn']}/{r['burn_ticks']} "
                  f"n={r['count']} window {r['window_s']}s")
    elif args.cmd == "health":
        return _health_cmd(args)
    elif args.cmd == "docs":
        reply = _request(args, {"t": "admin_docs"})
        for d in reply["docs"]:
            print(d)
    elif args.cmd == "placement":
        return _placement(args)
    elif args.cmd == "history":
        return _history_cmd(args)
    elif args.cmd == "migrate":
        reply = _request(args, {"t": "admin_migrate_doc",
                                "tenant": args.tenant, "doc": args.doc,
                                "target": args.target})
        fences = reply["fences"]
        if isinstance(fences, dict):
            fences = sum(fences.values())
        print(f"migrated partition {reply['k']} -> {reply['target']} "
              f"(epoch {reply['epoch']}, {fences} submit(s) fenced)")
    elif args.cmd == "tenants":
        reply = _request(args, {"t": "admin_tenants"})
        for tenant in reply["tenants"]:
            print(tenant)
    elif args.cmd == "tenant-add":
        _request(args, {"t": "admin_tenant_add", "id": args.id,
                        "tenant_secret": args.secret})
        print(f"registered {args.id}")
    elif args.cmd == "tenant-rm":
        reply = _request(args, {"t": "admin_tenant_remove",
                                "id": args.id})
        if not reply.get("ok"):
            print(f"unknown tenant {args.id}")
            return 1
        print(f"removed {args.id}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
