"""LocalOrderer: the REAL pipeline lambdas over the in-memory log.

Ref: memory-orderer/src/localOrderer.ts:88,228-270 — wires actual
Deli/Broadcaster/Scriptorium (and Scribe, §5 of the build plan) instances
over LocalKafka queues, so every test exercises the same stage code the
production sharded-log deployment runs. One LocalOrderer per document
(the document-router demux is the topic-per-doc layout here).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..protocol.messages import DocumentMessage, Nack, SequencedDocumentMessage
from .broadcaster import BroadcasterLambda, PubSub
from .core import InMemoryDb
from .deli import DeliCheckpoint, DeliLambda, RawMessage
from .local_log import LocalLog
from .scribe import SCRIBE_CHECKPOINT_COLLECTION, ScribeLambda
from .scriptorium import ScriptoriumLambda

CHECKPOINT_COLLECTION = "deli-checkpoints"

#: Lazy cold boot keeps this many ops below the acked boot summary in
#: the rebuilt scriptorium store when no retention margin is configured
#: — the same in-flight-backfill safety window config.log_retention_ops
#: defaults to.
LAZY_BOOT_MARGIN = 1000


def _versions_topic(tenant_id: str, document_id: str) -> str:
    return f"versions/{tenant_id}/{document_id}"


def restore_version_records(log, db, tenant_id: str,
                            document_id: str) -> None:
    """Rebuild acked summary-version records from the durable versions
    topic. After full process death the db is gone, and without these the
    summary chain (and, with retention, the doc) is unreachable — blob
    durability comes from the native chunk store; RECORD durability comes
    from here. Called by both the orderer and the storage facade (boot
    reads storage before any orderer exists)."""
    from .core import summary_versions_collection

    topic = _versions_topic(tenant_id, document_id)
    try:
        n = log.length(topic)
    except Exception:
        return
    if n <= 0:
        return
    col = summary_versions_collection(tenant_id, document_id)
    for i in range(n):
        rec = log.read(topic, i)
        if db.find_one(col, rec["handle"]) is None:
            db.upsert(col, rec["handle"], dict(rec["version"]))


def _checkpoint_topic(tenant_id: str, document_id: str) -> str:
    # per-doc topic: the newest checkpoint is simply the last record, and
    # old records compact trivially
    return f"checkpoints/{tenant_id}/{document_id}"


def _latest_log_checkpoint(log, tenant_id: str, document_id: str):
    """Newest checkpoint record for a doc from its checkpoint topic — the
    recovery source when the db died with the process (DurableLog)."""
    topic = _checkpoint_topic(tenant_id, document_id)
    try:
        n = log.length(topic)
        if n <= 0:
            return None
        return log.read(topic, n - 1)
    except Exception:
        return None


class LocalOrderer:
    def __init__(
        self,
        tenant_id: str,
        document_id: str,
        log: LocalLog,
        db: InMemoryDb,
        pubsub: PubSub,
        clock: Callable[[], float] = time.time,
        client_timeout: Optional[float] = None,
        logger=None,
        log_retention_ops: Optional[int] = None,
        external_scribe: bool = False,
        on_version_persisted=None,
        lazy_boot: bool = False,
    ):
        # fires once per newly-acked version, after the durable append —
        # the storage-process deployment advances the doc's named ref here
        self._on_version_persisted = on_version_persisted
        self.tenant_id = tenant_id
        self.document_id = document_id
        self._log = log
        self._db = db
        self._pubsub = pubsub
        self.raw_topic = f"rawops/{tenant_id}/{document_id}"
        self.deltas_topic = f"deltas/{tenant_id}/{document_id}"
        # set before the lambdas exist: boot replay routes through the
        # same funnels (order/_on_sequenced) that mark the state dirty
        self._dirty = False

        # restore deli from its checkpoint if present (restart path, ref:
        # deli/lambdaFactory.ts:54). Two sources: the db (in-proc restart)
        # and the log's checkpoint topic (process restart with a durable
        # log, where the db died too) — prefer whichever is newer.
        cp_doc = db.find_one(CHECKPOINT_COLLECTION, f"{tenant_id}/{document_id}")
        checkpoint = DeliCheckpoint.from_dict(cp_doc["state"]) if cp_doc else None
        log_cp = _latest_log_checkpoint(log, tenant_id, document_id)
        scribe_log_cp = None
        if log_cp is not None:
            log_deli = DeliCheckpoint.from_dict(log_cp["deli"])
            if checkpoint is None or log_deli.log_offset > checkpoint.log_offset:
                checkpoint = log_deli
                scribe_log_cp = log_cp["scribe"]

        kw = {"clock": clock}
        if client_timeout is not None:
            kw["client_timeout"] = client_timeout
        if logger is not None:
            kw["logger"] = logger.child("deli")
        self.deli = DeliLambda(
            tenant_id,
            document_id,
            send_sequenced=self._on_sequenced,
            send_nack=self._on_nack,
            checkpoint=checkpoint,
            send_raw=self.order,
            send_sequenced_batch=self._on_sequenced_batch,
            **kw,
        )
        self.scriptorium = ScriptoriumLambda(db)
        self.broadcaster = BroadcasterLambda(pubsub)
        scribe_cp = db.find_one(
            SCRIBE_CHECKPOINT_COLLECTION, f"{tenant_id}/{document_id}")
        scribe_state = scribe_log_cp or (scribe_cp["state"] if scribe_cp else None)
        self._retention_margin = (
            log_retention_ops
            if log_retention_ops is not None and log_retention_ops >= 0
            else None)
        on_committed = (self.apply_retention
                        if self._retention_margin is not None else None)

        # With an EXTERNAL scribe (per-stage process composition,
        # service/stage_runner.py), validation/acking happens in the
        # scribe process; this in-core instance is retained ONLY as the
        # ref-committer (commit_version is the single ref-update path)
        # driven by backchannel records — it is not subscribed to the
        # deltas topic, so its protocol replica stays untouched.
        self.external_scribe = external_scribe
        self.scribe = ScribeLambda(
            tenant_id,
            document_id,
            db,
            send_to_deli=self.order,
            checkpoint=scribe_state,
            on_summary_committed=on_committed,
            persist_version=self.persist_version_record,
        )
        restore_version_records(log, db, tenant_id, document_id)

        # deli replays the raw topic from 0 and self-skips via its
        # checkpointed log_offset (crash between append and ticket must
        # replay); scriptorium re-upserts idempotently; the broadcaster must
        # NOT replay history at live clients, so it joins at the tail.
        # Handler objects are kept for close(): bound-method attribute
        # access creates a fresh object each time, so unsubscribe needs the
        # exact references that were registered.
        #
        # LAZY COLD BOOT (fleet cold start): with ``lazy_boot`` and a
        # usable checkpoint + acked summary, the replay is O(tail), not
        # O(whole log). Deli and scribe resume one past their
        # checkpointed offsets (their handlers would skip every earlier
        # record anyway — subscribing past them skips the READS and
        # decodes); scriptorium keeps only the tail a joiner cannot get
        # from the latest acked summary, with the truncation declared
        # BEFORE the replay so the append path drops boundary overlap.
        raw_from = 0
        scrip_from = 0
        scribe_from = 0
        self.boot_mode = None  # None (in-proc warm) | fresh|lazy|full_replay
        if lazy_boot:
            boot_seq = self.acked_boot_seq()
            if log.length(self.raw_topic) <= 0:
                self.boot_mode = "fresh"
            elif checkpoint is not None and boot_seq is not None:
                margin = (self._retention_margin
                          if self._retention_margin is not None
                          else LAZY_BOOT_MARGIN)
                lazy_base = max(
                    log_cp.get("scriptorium_base", 0) if log_cp else 0,
                    boot_seq - margin, 0)
                raw_from = checkpoint.log_offset + 1
                if scribe_state is not None:
                    scribe_from = int(scribe_state.get("offset", -1)) + 1
                self.scriptorium.truncate_below(
                    tenant_id, document_id, lazy_base)
                scrip_from = log.first_offset_covering(
                    self.deltas_topic, lazy_base + 1)
                self.boot_mode = "lazy"
            else:
                # no checkpoint or no acked summary: a joiner would have
                # nothing to boot from but the ops — replay it all
                self.boot_mode = "full_replay"
        from ..obs.probe import CANARY_TENANT
        from .rehydrate import boot_counters
        if tenant_id == CANARY_TENANT:
            # canary isolation: the synthetic doc is summary-less by
            # design, so its (tiny) boots must not trip the cold-start
            # contract (boot.part.full_replay == 0) or the doctor's
            # boot_anomalies rule on a respawned core
            pass
        elif self.boot_mode == "lazy":
            boot_counters().inc("boot.part.lazy")
        elif self.boot_mode == "full_replay":
            boot_counters().inc("boot.part.full_replay")
        elif self.boot_mode == "fresh":
            boot_counters().inc("boot.part.fresh")
        self._subscriptions = [
            (self.raw_topic, self.deli.handler, raw_from),
            (self.deltas_topic, self.scriptorium.handler, scrip_from),
            (self.deltas_topic, self.broadcaster.handler, log.length(self.deltas_topic)),
        ]
        if not external_scribe:
            self._subscriptions.insert(
                2, (self.deltas_topic, self.scribe.handler, scribe_from))
        for topic, handler, from_offset in self._subscriptions:
            self._log.subscribe(topic, handler, from_offset=from_offset)
        # re-apply the persisted retention AFTER the deltas-topic replay
        # rebuilt the full store (the replay itself is what un-truncated)
        if log_cp is not None and log_cp.get("scriptorium_base", 0) > 0:
            self.scriptorium.truncate_below(
                tenant_id, document_id, log_cp["scriptorium_base"])

    # the front end calls this (alfred's connection.order()); accepts a
    # single RawMessage or a RawBoxcar (one log record either way)
    def order(self, raw) -> None:
        self._dirty = True
        self._log.append(self.raw_topic, raw)

    def persist_version_record(self, handle: str, version: dict) -> None:
        """Append an acked version record to the durable versions topic —
        the scribe-ref commit path (in-core scribe AND the external
        scribe's backchannel both land here)."""
        self._dirty = True
        self._log.append(_versions_topic(self.tenant_id, self.document_id),
                         {"handle": handle, "version": dict(version)})
        if self._on_version_persisted is not None:
            self._on_version_persisted(handle, dict(version))

    def acked_boot_seq(self) -> Optional[int]:
        """Capture seq of the version a joiner would boot from (latest
        acked by n) — None when no acked summary exists, or when the
        record predates capture-seq stamping."""
        from .core import summary_versions_collection

        col = summary_versions_collection(self.tenant_id, self.document_id)
        acked = [v for v in self._db.collection(col).values()
                 if v.get("acked")]
        if not acked:
            return None
        return max(acked, key=lambda v: v["n"]).get("seq")

    def apply_retention(self, capture_seq: int) -> None:
        """Truncate ops an acked summary covers, minus the in-flight
        backfill margin (config.log_retention_ops).

        The trim is CLAMPED to the boot version's capture seq: the ack
        chain orders by parent handle, not by seq, so a later-acked
        summary can capture an earlier seq than its predecessor — trimming
        to the raw commit head would then open a log_truncated hole below
        the only snapshot that heals it. No acked summary ⇒ no trim at
        all (a joiner would have nothing but full replay)."""
        if self._retention_margin is None:
            return
        boot_seq = self.acked_boot_seq()
        if boot_seq is None:
            return
        self._dirty = True  # the retained base rides the next checkpoint
        self.scriptorium.truncate_below(
            self.tenant_id, self.document_id,
            min(capture_seq, boot_seq) - self._retention_margin)

    def commit_external_version(self, handle: str, version: dict) -> None:
        """Apply an external scribe's version commit (stage_runner
        backchannel): the stage validated and acked; this process owns
        the db + versions topic + head."""
        from .core import summary_versions_collection

        col = summary_versions_collection(self.tenant_id, self.document_id)
        existing = self._db.find_one(col, handle)
        already_acked = bool(existing and existing.get("acked"))
        self._dirty = True
        self._db.upsert(col, handle, dict(version))
        self.scribe.last_summary_head = handle
        if not already_acked:
            self.persist_version_record(handle, version)

    def close(self) -> None:
        """Detach from the log (partition shutdown); a successor orderer
        resumes from the db checkpoint."""
        for topic, handler, _ in self._subscriptions:
            self._log.unsubscribe(topic, handler)

    def checkpoint(self) -> None:
        """Persist deli + scribe state (ref: deli checkpointContext.ts,
        scribe checkpointManager.ts → Mongo) — to the db and, so a durable
        log can recover it after full process death, to the log too. The
        scriptorium retention base rides along: without it a restart
        would rebuild the full delta store from the durable deltas topic
        and silently undo the truncation.

        Clean pipelines skip the write entirely: the 2s service ticker
        checkpoints every RESIDENT doc, and after a mass cold boot
        thousands of idle rehydrated pipelines would each pay a
        serialize + append per pass, stalling the event loop for tens
        of seconds. Dirty tracking makes the ticker O(touched docs),
        not O(resident docs); re-writing state identical to the last
        durable checkpoint is semantically a no-op anyway."""
        if not self._dirty:
            return
        deli_state = self.deli.checkpoint().to_dict()
        scribe_state = self.scribe.checkpoint_state()
        key = f"{self.tenant_id}/{self.document_id}"
        self._db.upsert(CHECKPOINT_COLLECTION, key, {"state": deli_state})
        self._db.upsert(SCRIBE_CHECKPOINT_COLLECTION, key, {"state": scribe_state})
        self._log.append(
            _checkpoint_topic(self.tenant_id, self.document_id),
            {"deli": deli_state, "scribe": scribe_state,
             "scriptorium_base": self.scriptorium.retained_base(
                 self.tenant_id, self.document_id)},
        )
        self._dirty = False

    def _on_sequenced(self, msg: SequencedDocumentMessage) -> None:
        self._dirty = True
        self._log.append(
            self.deltas_topic,
            {
                "tenant_id": self.tenant_id,
                "document_id": self.document_id,
                "message": msg,
            },
        )

    def _on_sequenced_batch(self, msgs) -> None:
        """A ticketed boxcar rides the deltas topic as one record, so the
        downstream stages (scriptorium/scribe/broadcaster) batch too.
        The array lane hands a SequencedArrayBatch (no per-op objects);
        the dict lane a list of SequencedDocumentMessage."""
        from .array_batch import SequencedArrayBatch

        self._dirty = True
        if type(msgs) is SequencedArrayBatch:
            record = {
                "tenant_id": self.tenant_id,
                "document_id": self.document_id,
                "abatch": msgs,
            }
        else:
            record = {
                "tenant_id": self.tenant_id,
                "document_id": self.document_id,
                "boxcar": msgs,
            }
        self._log.append(self.deltas_topic, record)

    def _on_nack(self, client_id: str, nack: Nack) -> None:
        self._pubsub.publish(f"nack/{self.tenant_id}/{self.document_id}/{client_id}", nack)
