"""GitStore: parent-linked commit DAG + named refs over a blob store.

Ref: the reference's storage is literally git — scribe's summary commit
creates a git commit whose ref the service advances
(services-client/src/gitManager.ts:13 getCommits/createCommit,
server/gitrest/src/routes/git, historian.ts:29 caching proxy). Version
records that merely flip an ``acked`` flag (round-3 shape) cannot walk
history or boot from a named head; this module adds the DAG:

- a COMMIT is a content-addressed blob
  ``{"t": "commit", "tree": id, "parents": [ids], "meta": {...}}`` —
  immutable, deduped, sharing the chunk store with trees/blobs;
- a REF is a named pointer (``heads/<tenant>/<doc>``) whose updates
  append to a durable oplog topic (the reflog), so refs survive process
  death and replay on open;
- ``history`` walks parent links from a ref or commit id.

The standalone storage process (storage_server.py) serves this over
RPCs; scribe's ack path advances the ref.
"""

from __future__ import annotations

import json
from typing import Optional

REFS_TOPIC = "refs"


def head_ref(tenant_id: str, document_id: str) -> str:
    return f"heads/{tenant_id}/{document_id}"


class GitStore:
    def __init__(self, blobs, refs_log=None):
        """``blobs``: put/get/has content store. ``refs_log``: a
        NativeOpLog (or None for ephemeral refs); the reflog topic is
        replayed on open — last write per name wins."""
        self._blobs = blobs
        self._refs_log = refs_log
        self._refs: dict[str, str] = {}
        if refs_log is not None:
            try:
                n = refs_log.length(REFS_TOPIC)
            except OSError:
                n = 0
            for i in range(n):
                rec = json.loads(refs_log.read(REFS_TOPIC, i))
                self._refs[rec["name"]] = rec["commit"]

    # ------------------------------------------------------------- commits

    def write_commit(self, tree_id: str, parents: list[str],
                     meta: Optional[dict] = None) -> str:
        blob = json.dumps(
            {"t": "commit", "tree": tree_id, "parents": sorted(parents),
             "meta": meta or {}},
            sort_keys=True, separators=(",", ":")).encode()
        return self._blobs.put(blob)

    def read_commit(self, commit_id: str) -> dict:
        obj = json.loads(self._blobs.get(commit_id).decode())
        if obj.get("t") != "commit":
            raise KeyError(f"{commit_id} is not a commit")
        return obj

    def history(self, start: str, limit: int = 50) -> list[dict]:
        """Commits from ``start`` (a ref name or commit id) following
        first parents, newest first — the git-log walk boot/debug
        tooling uses."""
        commit_id = self._refs.get(start, start)
        out = []
        while commit_id and len(out) < limit:
            c = self.read_commit(commit_id)
            out.append(dict(c, id=commit_id))
            commit_id = c["parents"][0] if c["parents"] else None
        return out

    # ---------------------------------------------------------------- refs

    def set_ref(self, name: str, commit_id: str) -> None:
        self._refs[name] = commit_id
        if self._refs_log is not None:
            self._refs_log.append(REFS_TOPIC, json.dumps(
                {"name": name, "commit": commit_id},
                separators=(",", ":")).encode())
            self._refs_log.flush()

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    def refs(self) -> dict:
        return dict(self._refs)
