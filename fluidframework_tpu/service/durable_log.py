"""DurableLog: the ordered-log interface over the native C++ op log.

Drop-in for LocalLog in LocalOrderer/LocalServer (same OrderedLogBase
machinery), but every record is persisted through native/oplog.cpp, so a
process restart resumes the pipeline from disk — the single-node
durability story the reference gets from Kafka+Mongo (SURVEY §2.9
consolidation note).

Values must be protocol messages or JSON-serializable structures; they
are encoded via protocol/serialization with explicit tagging, and user
dicts that happen to collide with the tag keys are escaped, so framing is
unambiguous. Subscriber positions are in-memory (the lambdas own their
checkpoints, as in the reference).
"""

from __future__ import annotations

from typing import Any

import json

from ..native.oplog import NativeOpLog
from ..protocol.serialization import message_from_dict, message_to_dict
from .local_log import OrderedLogBase

_TAG_MSG = "_msg"  # a wrapped protocol message
_TAG_ESC = "_esc"  # an escaped user dict that contained a tag key


def _wrap(value: Any) -> Any:
    """Recursively tag protocol messages / escape colliding user dicts."""
    if isinstance(value, dict):
        out = {k: _wrap(v) for k, v in value.items()}
        if _TAG_MSG in out or _TAG_ESC in out:
            return {_TAG_ESC: out}
        return out
    if isinstance(value, (list, tuple)):
        return [_wrap(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return {_TAG_MSG: message_to_dict(value)}


def _unwrap(value: Any) -> Any:
    if isinstance(value, dict):
        if _TAG_MSG in value and len(value) == 1:
            return message_from_dict(value[_TAG_MSG])
        if _TAG_ESC in value and len(value) == 1:
            return {k: _unwrap(v) for k, v in value[_TAG_ESC].items()}
        return {k: _unwrap(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_unwrap(v) for v in value]
    return value


def _encode_value(value: Any) -> bytes:
    return json.dumps(_wrap(value), separators=(",", ":")).encode()


def _decode_value(data: bytes) -> Any:
    return _unwrap(json.loads(data.decode()))


def _sanitize(topic: str) -> str:
    return topic.replace("/", ".")


class DurableLog(OrderedLogBase):
    """Persistent ordered topics with subscriber fan-out."""

    def __init__(self, directory: str):
        super().__init__()
        self._log = NativeOpLog(directory)

    def _store(self, topic: str, value: Any) -> int:
        return self._log.append(_sanitize(topic), _encode_value(value))

    def _load(self, topic: str, offset: int) -> Any:
        return _decode_value(self._log.read(_sanitize(topic), offset))

    def _stored_length(self, topic: str) -> int:
        return self._log.length(_sanitize(topic))

    def sync(self) -> None:
        self._log.sync()

    def close(self) -> None:
        self._log.close()
